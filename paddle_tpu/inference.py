"""Export / inference: deploy a trained model without its Python source.

Reference: the C inference API loads a *merged model* — config proto +
parameters in one artifact (``/root/reference/paddle/capi/
gradient_machine.h:51`` ``paddle_gradient_machine_create_for_inference_with_
parameters``, produced by ``trainer/MergeModel.cpp:17``) — and ``paddle.infer``
runs forward-only (``python/paddle/v2/inference.py``).

TPU-native: :func:`export` writes a directory bundling the model IR
(``model.json``, see ``paddle_tpu.core.config``) with the variables
(npz + CRC manifest, same format as training checkpoints);
:func:`load_inference_model` rebuilds the Module tree from the IR — no user
model code needed — and returns an :class:`InferenceModel` whose ``predict``
is a jit'd forward (``method=`` reaches alternative entry points, e.g. beam
``generate`` on seq2seq models).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from . import __version__
from .core import config as config_lib
from .train.checkpoint import (_flatten, _unflatten, atomic_dir,
                               verify_manifest, write_manifest)

__all__ = ["export", "load_inference_model", "InferenceModel", "infer",
           "merge_model", "dump_config", "model_diagram"]

_MODEL_FILE = "model.json"
_VARS_FILE = "variables.npz"

_log = logging.getLogger("paddle_tpu.inference")


def _hashable(v) -> bool:
    try:
        hash(v)
        return True
    except TypeError:
        return False


def export(path: str, model, variables: Dict[str, Any]) -> str:
    """Write the deployable bundle: model IR + variables (atomic, CRC'd).
    Multi-host: only process 0 writes (single-controller convention)."""
    cfg = config_lib.module_config(model)   # validate on every process
    if jax.process_index() != 0:
        return path
    with atomic_dir(path) as tmp:
        with open(os.path.join(tmp, _MODEL_FILE), "w") as f:
            f.write(config_lib.config_to_json(
                {"framework_version": __version__, **cfg}))
        host = jax.tree_util.tree_map(lambda x: np.asarray(x), variables)
        np.savez(os.path.join(tmp, _VARS_FILE), **_flatten(host))
        write_manifest(tmp)
    return path


class InferenceModel:
    """A rebuilt model + variables with jit-cached forward entry points.

    Decoder-LM bundles additionally serve incrementally: ``engine()``
    builds (once) a :class:`paddle_tpu.serve.DecodeEngine` over the
    bundle, ``generate()`` runs continuous-batched greedy decoding, and
    ``predict(method="prefill"|"decode_step")`` routes through the
    engine's fixed-shape compiled programs instead of the generic jit
    path (which would retrace per prompt shape and manage no KV)."""

    def __init__(self, model, variables: Dict[str, Any]):
        self.model = model
        self.variables = variables
        self._jitted: Dict[Any, Any] = {}
        self._engine = None
        self._unhashable_warned: set = set()

    # -- serving (paddle_tpu.serve) ----------------------------------------

    def engine(self, **engine_kwargs):
        """The bundle's serving engine, built on first use (kwargs are
        honored only on that first call — one engine per bundle)."""
        if self._engine is None:
            from .serve import DecodeEngine
            self._engine = DecodeEngine(self.model, self.variables,
                                        **engine_kwargs)
        elif engine_kwargs:
            _log.warning("engine() kwargs ignored — the serve engine was "
                         "already built for this bundle")
        return self._engine

    def generate(self, prompts: List[List[int]], max_new_tokens: int,
                 eos_id: Optional[int] = None,
                 **engine_kwargs) -> List[List[int]]:
        """Greedy-decode ``prompts`` through the continuous-batching
        scheduler; returns generated token lists in submission order."""
        from .serve import ContinuousBatchingScheduler
        sched = ContinuousBatchingScheduler(self.engine(**engine_kwargs))
        reqs = [sched.submit(list(p), max_new_tokens, eos_id=eos_id)
                for p in prompts]
        sched.run()
        return [r.tokens for r in reqs]

    def _serve_predict(self, method: str, *args, **kwargs):
        """The ``method="prefill"|"decode_step"`` route: a stateful
        incremental-decode session on the bundle's engine. ``prefill``
        admits each prompt row into a free slot and returns the first
        greedy tokens; ``decode_step`` advances one fixed-shape tick and
        returns the new token front (inactive lanes 0)."""
        eng = self.engine(**kwargs)
        if method == "decode_step":
            return eng.decode_tick()
        prompts = [list(np.asarray(p).ravel()) for p in args[0]]
        free = eng.free_slots()
        if len(prompts) > len(free):
            raise ValueError(
                f"{len(prompts)} prompts but only {len(free)} free slots "
                f"(max_slots={eng.max_slots}); evict or raise max_slots")
        # a session has no max_new_tokens bound, so reserve each slot's
        # full context capacity — decode_step may be called until W
        return np.asarray([eng.admit(slot, [int(t) for t in p],
                                     reserve_len=eng.context_width)
                           for slot, p in zip(free, prompts)], np.int32)

    # -- the generic path --------------------------------------------------

    def predict(self, *args, method: Optional[str] = None, **kwargs):
        """Run forward (train=False semantics; ``method`` selects an
        alternative entry point such as ``generate``/``decode``). Positional
        args are traced arrays; keyword args are static configuration
        (beam sizes etc.) and key the jit cache.

        ``method="prefill"`` / ``"decode_step"`` never take the generic
        path: they route through the serve engine's fixed-shape compiled
        programs (see :meth:`_serve_predict`)."""
        if method in ("prefill", "decode_step"):
            return self._serve_predict(method, *args, **kwargs)
        model = self.model
        try:
            key = (method, tuple(sorted(kwargs.items())))
            hash(key)
        except TypeError:
            key = None                       # unhashable static kwarg
        if key is None:
            bad = sorted(k for k, v in kwargs.items()
                         if not _hashable(v))
            if tuple(bad) not in self._unhashable_warned:
                self._unhashable_warned.add(tuple(bad))
                _log.warning(
                    "predict kwarg(s) %s are unhashable — falling back to "
                    "un-jitted model.apply (every call re-traces; no "
                    "compile cache). Pass hashable statics (tuples, not "
                    "lists/arrays), or use predict(method=\"prefill\"/"
                    "\"decode_step\") / generate() for the compiled "
                    "serving path.", ", ".join(repr(b) for b in bad))
            return model.apply(self.variables, *args, method=method,
                               **kwargs)
        if key not in self._jitted:
            def fn(variables, *a):
                return model.apply(variables, *a, method=method, **kwargs)
            self._jitted[key] = jax.jit(fn)
        args = tuple(jnp.asarray(a) if isinstance(a, (np.ndarray, list))
                     else a for a in args)
        return self._jitted[key](self.variables, *args)

    __call__ = predict


def load_inference_model(path: str, trusted: bool = False,
                         verify_crc: bool = True) -> InferenceModel:
    """Load an exported bundle; raises on CRC mismatch. ``trusted`` gates
    importing classes from outside paddle_tpu (a model file is data)."""
    verify_manifest(path, verify_crc=verify_crc)
    with open(os.path.join(path, _MODEL_FILE)) as f:
        cfg = config_lib.config_from_json(f.read())
    model = config_lib.build_module(cfg, trusted=trusted)
    with np.load(os.path.join(path, _VARS_FILE), allow_pickle=False) as z:
        variables = _unflatten({k: z[k] for k in z.files})
    variables = jax.tree_util.tree_map(jnp.asarray, variables)
    return InferenceModel(model, variables)


def infer(path_or_model, *args, method: Optional[str] = None, **kwargs):
    """One-shot convenience (the ``paddle.infer`` surface,
    ``v2/inference.py``): load (if given a path) and run forward."""
    m = (path_or_model if isinstance(path_or_model, InferenceModel)
         else load_inference_model(path_or_model))
    return m.predict(*args, method=method, **kwargs)


def merge_model(path: str, model, variables: Dict[str, Any]) -> str:
    """Bundle config + parameters into one deployable directory (reference:
    ``trainer/MergeModel.cpp:17`` and ``python/paddle/utils/merge_model.py``
    ``merge_v2_model``) — an alias of :func:`export`, named for parity."""
    return export(path, model, variables)


def dump_config(model, indent: int = 2) -> str:
    """Serialized model config as JSON text (reference:
    ``python/paddle/utils/dump_config.py`` — prints the generated proto)."""
    import json
    from paddle_tpu.core.config import module_config
    return json.dumps(module_config(model), indent=indent, sort_keys=True)


def model_diagram(model) -> str:
    """Graphviz dot text of the module containment tree (reference:
    ``python/paddle/utils/make_model_diagram.py`` — rendered the layer graph
    from a model config). Render with ``dot -Tpng``."""
    from paddle_tpu.core.config import _is_module

    lines = ["digraph model {", "  rankdir=TB;",
             '  node [shape=box, fontname="monospace"];']
    counter = [0]
    seen = {}         # id(module) -> node idx: shared instances render once

    def visit(mod, attr_name):
        if id(mod) in seen:
            return seen[id(mod)]
        idx = counter[0]
        counter[0] += 1
        seen[id(mod)] = idx
        cls = type(mod).__name__
        label = f"{attr_name}: {cls}" if attr_name else cls
        lines.append(f'  n{idx} [label="{label}"];')
        for name, val in sorted(vars(mod).items()):
            children = []
            if _is_module(val):
                children = [(name, val)]
            elif isinstance(val, (list, tuple)):
                children = [(f"{name}[{i}]", v) for i, v in enumerate(val)
                            if _is_module(v)]
            for cname, child in children:
                cidx = visit(child, cname)
                lines.append(f"  n{idx} -> n{cidx};")
        return idx

    visit(model, "")
    lines.append("}")
    return "\n".join(lines)
