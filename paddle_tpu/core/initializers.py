"""Parameter initializers.

Mirrors the reference's init strategies on ``ParameterConfig``
(``/root/reference/paddle/parameter/Parameter.h:60-340``,
``proto/ParameterConfig.proto:34`` — initial_mean/initial_std/initial_strategy):
normal, uniform, xavier (the reference's default 1/sqrt(fan_in)), msra, constant.
Implemented as pure ``(rng, shape, dtype) -> array`` functions for the module system.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

__all__ = [
    "zeros", "ones", "constant", "normal", "uniform", "xavier_uniform",
    "xavier_normal", "msra_normal", "lecun_normal", "orthogonal", "fan_in_uniform",
]


def zeros(rng, shape, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def ones(rng, shape, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


def constant(value):
    def _init(rng, shape, dtype=jnp.float32):
        return jnp.full(shape, value, dtype)
    return _init


def normal(stddev=0.01, mean=0.0):
    def _init(rng, shape, dtype=jnp.float32):
        return mean + stddev * jax.random.normal(rng, shape, dtype)
    return _init


def uniform(scale=0.01):
    def _init(rng, shape, dtype=jnp.float32):
        return jax.random.uniform(rng, shape, dtype, -scale, scale)
    return _init


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels HWIO: receptive field * channels
    receptive = int(np.prod(shape[:-2]))
    return shape[-2] * receptive, shape[-1] * receptive


def fan_in_uniform(rng, shape, dtype=jnp.float32):
    """The reference's default: U(-s, s), s = 1/sqrt(fan_in)
    (``config_parser.py`` default initial_strategy with initial_std=1/sqrt(size))."""
    fan_in, _ = _fans(shape)
    s = 1.0 / np.sqrt(max(1, fan_in))
    return jax.random.uniform(rng, shape, dtype, -s, s)


def xavier_uniform(rng, shape, dtype=jnp.float32):
    fan_in, fan_out = _fans(shape)
    s = np.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(rng, shape, dtype, -s, s)


def xavier_normal(rng, shape, dtype=jnp.float32):
    fan_in, fan_out = _fans(shape)
    s = np.sqrt(2.0 / (fan_in + fan_out))
    return s * jax.random.normal(rng, shape, dtype)


def msra_normal(rng, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    s = np.sqrt(2.0 / max(1, fan_in))
    return s * jax.random.normal(rng, shape, dtype)


def lecun_normal(rng, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    s = np.sqrt(1.0 / max(1, fan_in))
    return s * jax.random.normal(rng, shape, dtype)


def orthogonal(scale=1.0):
    def _init(rng, shape, dtype=jnp.float32):
        if len(shape) < 2:
            return normal(0.01)(rng, shape, dtype)
        rows, cols = int(np.prod(shape[:-1])), shape[-1]
        a = jax.random.normal(rng, (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        q = q.T if rows < cols else q
        return (scale * q[:rows, :cols]).reshape(shape).astype(dtype)
    return _init
