"""Variable-length sequence representation — successor of the reference's ``Argument``.

The reference carries ragged batches as a flat value matrix plus
``sequenceStartPositions`` / ``subSequenceStartPositions``
(``/root/reference/paddle/parameter/Argument.h:70-93``) so no padding is ever
materialized, and reorders time-steps for RNNs with ``SequenceToBatch``
(``paddle/gserver/layers/SequenceToBatch.h``).

XLA wants static shapes, so the TPU-native equivalent is *packing + segment IDs*:

  - :class:`SeqBatch` — padded ``[B, T, ...]`` data with ``lengths [B]``; masks are
    derived on device. This is the simple path for mildly ragged data.
  - :func:`pack_sequences` — bin-pack many ragged sequences into few fixed
    ``[rows, T]`` slots with ``segment_ids``/``positions``; attention and losses
    mask across segment boundaries. This recovers the reference's "no padding
    waste" property with fully static shapes (see SURVEY.md §5 long-context row).

Nested (sub-)sequences (the reference's ``subSequenceStartPositions``) are carried
as a second segment level: ``sub_segment_ids``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

__all__ = [
    "SeqBatch", "length_mask", "segment_mask", "causal_mask",
    "pack_sequences", "unpack_sequences", "positions_from_segments",
]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(eq=False)
class SeqBatch:
    """Padded batch of sequences: ``data [B, T, ...]``, ``lengths [B]`` (int32).

    Optional ``segment_ids [B, T]`` marks packed sub-sequences (0 = padding,
    1..k = packed sequences); when present, ``lengths`` is the per-row used length.
    """
    data: jax.Array
    lengths: jax.Array
    segment_ids: Optional[jax.Array] = None
    positions: Optional[jax.Array] = None

    # pytree protocol -------------------------------------------------------
    def tree_flatten(self):
        children = (self.data, self.lengths, self.segment_ids, self.positions)
        return children, None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # conveniences ----------------------------------------------------------
    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    @property
    def max_len(self) -> int:
        return self.data.shape[1]

    def mask(self) -> jax.Array:
        """[B, T] float32 1/0 validity mask."""
        if self.segment_ids is not None:
            return (self.segment_ids > 0).astype(jnp.float32)
        return length_mask(self.lengths, self.max_len)

    def attn_mask(self, causal: bool = False) -> jax.Array:
        """[B, T, T] attention mask honoring padding and packing boundaries."""
        if self.segment_ids is not None:
            m = segment_mask(self.segment_ids, self.segment_ids)
        else:
            v = self.mask()
            m = v[:, :, None] * v[:, None, :]
        if causal:
            m = m * causal_mask(self.max_len)[None]
        return m

    @staticmethod
    def from_list(seqs: Sequence[np.ndarray], max_len: Optional[int] = None,
                  pad_value=0) -> "SeqBatch":
        """Pad a list of [len, ...] arrays to a dense [B, T, ...] batch (host-side)."""
        n = len(seqs)
        t = max_len or max(len(s) for s in seqs)
        tail = np.asarray(seqs[0]).shape[1:]
        data = np.full((n, t) + tail, pad_value,
                       dtype=np.asarray(seqs[0]).dtype)
        lengths = np.zeros((n,), np.int32)
        for i, s in enumerate(seqs):
            s = np.asarray(s)[:t]
            data[i, :len(s)] = s
            lengths[i] = len(s)
        return SeqBatch(jnp.asarray(data), jnp.asarray(lengths))


def length_mask(lengths: jax.Array, max_len: int) -> jax.Array:
    """[B] lengths -> [B, T] float 1/0 mask."""
    pos = jnp.arange(max_len)[None, :]
    return (pos < lengths[:, None]).astype(jnp.float32)


def segment_mask(q_seg: jax.Array, kv_seg: jax.Array) -> jax.Array:
    """[B, Tq], [B, Tk] segment ids -> [B, Tq, Tk] same-segment mask (0 is pad)."""
    same = (q_seg[:, :, None] == kv_seg[:, None, :])
    valid = (q_seg[:, :, None] > 0) & (kv_seg[:, None, :] > 0)
    return (same & valid).astype(jnp.float32)


def causal_mask(t: int) -> jax.Array:
    return jnp.tril(jnp.ones((t, t), jnp.float32))


def positions_from_segments(segment_ids: np.ndarray) -> np.ndarray:
    """Per-token position within its own segment (host-side, numpy)."""
    b, t = segment_ids.shape
    out = np.zeros((b, t), np.int32)
    for i in range(b):
        pos, prev = 0, 0
        for j in range(t):
            s = segment_ids[i, j]
            pos = pos + 1 if (s == prev and s != 0) else 0
            out[i, j] = pos
            prev = s
    return out


def pack_sequences(seqs: Sequence[np.ndarray], row_len: int,
                   pad_value=0) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """First-fit bin-pack ragged sequences into ``[rows, row_len]`` slots.

    Returns ``(data, segment_ids, positions)`` (host-side numpy). Sequences longer
    than ``row_len`` are truncated. ``segment_ids`` are 1-based per row; 0 = pad.
    """
    order = np.argsort([-len(s) for s in seqs], kind="stable")
    tail = np.asarray(seqs[0]).shape[1:]
    dtype = np.asarray(seqs[0]).dtype
    rows: List[np.ndarray] = []
    segs: List[np.ndarray] = []
    free: List[int] = []   # free space per row
    nseg: List[int] = []
    for idx in order:
        s = np.asarray(seqs[idx])[:row_len]
        L = len(s)
        slot = -1
        for r in range(len(rows)):
            if free[r] >= L:
                slot = r
                break
        if slot < 0:
            rows.append(np.full((row_len,) + tail, pad_value, dtype))
            segs.append(np.zeros((row_len,), np.int32))
            free.append(row_len)
            nseg.append(0)
            slot = len(rows) - 1
        off = row_len - free[slot]
        rows[slot][off:off + L] = s
        nseg[slot] += 1
        segs[slot][off:off + L] = nseg[slot]
        free[slot] -= L
    data = np.stack(rows)
    segment_ids = np.stack(segs)
    return data, segment_ids, positions_from_segments(segment_ids)


def unpack_sequences(data: np.ndarray, segment_ids: np.ndarray) -> List[np.ndarray]:
    """Inverse of :func:`pack_sequences` (order not preserved)."""
    out = []
    for row, seg in zip(np.asarray(data), np.asarray(segment_ids)):
        for s in range(1, int(seg.max(initial=0)) + 1):
            out.append(row[seg == s])
    return out
