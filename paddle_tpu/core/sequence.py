"""Variable-length sequence representation — successor of the reference's ``Argument``.

The reference carries ragged batches as a flat value matrix plus
``sequenceStartPositions`` / ``subSequenceStartPositions``
(``/root/reference/paddle/parameter/Argument.h:70-93``) so no padding is ever
materialized, and reorders time-steps for RNNs with ``SequenceToBatch``
(``paddle/gserver/layers/SequenceToBatch.h``).

XLA wants static shapes, so the TPU-native equivalent is *packing + segment IDs*:

  - :class:`SeqBatch` — padded ``[B, T, ...]`` data with ``lengths [B]``; masks are
    derived on device. This is the simple path for mildly ragged data.
  - :func:`pack_sequences` — bin-pack many ragged sequences into few fixed
    ``[rows, T]`` slots with ``segment_ids``/``positions``; attention and losses
    mask across segment boundaries. This recovers the reference's "no padding
    waste" property with fully static shapes (see SURVEY.md §5 long-context row).

Nested (sub-)sequences (the reference's ``subSequenceStartPositions``) are carried
as a second segment level: ``sub_segment_ids``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

__all__ = [
    "SeqBatch", "NestedSeqBatch", "length_mask", "segment_mask", "causal_mask",
    "pack_sequences", "unpack_sequences", "positions_from_segments",
    "pack_nested_sequences", "unpack_nested_sequences",
]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(eq=False)
class SeqBatch:
    """Padded batch of sequences: ``data [B, T, ...]``, ``lengths [B]`` (int32).

    Optional ``segment_ids [B, T]`` marks packed sub-sequences (0 = padding,
    1..k = packed sequences); when present, ``lengths`` is the per-row used length.
    """
    data: jax.Array
    lengths: jax.Array
    segment_ids: Optional[jax.Array] = None
    positions: Optional[jax.Array] = None

    # pytree protocol -------------------------------------------------------
    def tree_flatten(self):
        children = (self.data, self.lengths, self.segment_ids, self.positions)
        return children, None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # conveniences ----------------------------------------------------------
    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    @property
    def max_len(self) -> int:
        return self.data.shape[1]

    def mask(self) -> jax.Array:
        """[B, T] float32 1/0 validity mask."""
        if self.segment_ids is not None:
            return (self.segment_ids > 0).astype(jnp.float32)
        return length_mask(self.lengths, self.max_len)

    def attn_mask(self, causal: bool = False) -> jax.Array:
        """[B, T, T] attention mask honoring padding and packing boundaries."""
        if self.segment_ids is not None:
            m = segment_mask(self.segment_ids, self.segment_ids)
        else:
            v = self.mask()
            m = v[:, :, None] * v[:, None, :]
        if causal:
            m = m * causal_mask(self.max_len)[None]
        return m

    @staticmethod
    def from_list(seqs: Sequence[np.ndarray], max_len: Optional[int] = None,
                  pad_value=0) -> "SeqBatch":
        """Pad a list of [len, ...] arrays to a dense [B, T, ...] batch (host-side)."""
        n = len(seqs)
        t = max_len or max(len(s) for s in seqs)
        tail = np.asarray(seqs[0]).shape[1:]
        data = np.full((n, t) + tail, pad_value,
                       dtype=np.asarray(seqs[0]).dtype)
        lengths = np.zeros((n,), np.int32)
        for i, s in enumerate(seqs):
            s = np.asarray(s)[:t]
            data[i, :len(s)] = s
            lengths[i] = len(s)
        return SeqBatch(jnp.asarray(data), jnp.asarray(lengths))


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(eq=False)
class NestedSeqBatch:
    """Two-level ragged batch — the reference's nested sequences
    (``subSequenceStartPositions``, ``parameter/Argument.h:84-93``), carried
    dense and static-shaped: ``data [B, S, T, ...]`` where S is the padded
    subsequence count and T the padded token count per subsequence.

    ``sub_lengths [B, S]`` gives tokens per subsequence (0 = unused slot);
    ``num_subseqs [B]`` gives subsequences per sequence. Hierarchical models
    run token-level ops on the flattened ``[B*S, T]`` view (inner RNN per
    subsequence — the reference's nested ``RecurrentGradientMachine`` frame)
    and sequence-level ops over the S axis (outer recurrence).
    """
    data: jax.Array
    sub_lengths: jax.Array
    num_subseqs: jax.Array

    def tree_flatten(self):
        return (self.data, self.sub_lengths, self.num_subseqs), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    @property
    def max_subseqs(self) -> int:
        return self.data.shape[1]

    @property
    def max_len(self) -> int:
        return self.data.shape[2]

    def token_mask(self) -> jax.Array:
        """[B, S, T] float validity mask over tokens."""
        pos = jnp.arange(self.max_len)[None, None, :]
        return (pos < self.sub_lengths[:, :, None]).astype(jnp.float32)

    def subseq_mask(self) -> jax.Array:
        """[B, S] float validity mask over subsequence slots."""
        return length_mask(self.num_subseqs, self.max_subseqs)

    def flat(self) -> SeqBatch:
        """Collapse to a token-level :class:`SeqBatch` of shape [B*S, T] —
        each subsequence becomes an independent sequence (the reference's
        trivial-nesting equivalence, ``test_RecurrentGradientMachine.cpp``)."""
        B, S = self.data.shape[:2]
        return SeqBatch(self.data.reshape((B * S,) + self.data.shape[2:]),
                        self.sub_lengths.reshape(B * S).astype(jnp.int32))

    @staticmethod
    def from_lists(seqs: Sequence[Sequence[np.ndarray]],
                   max_subseqs: Optional[int] = None,
                   max_len: Optional[int] = None,
                   pad_value=0) -> "NestedSeqBatch":
        """Build from a list (batch) of lists (subsequences) of [len, ...]
        arrays (host-side)."""
        B = len(seqs)
        S = max_subseqs or max(len(s) for s in seqs)
        T = max_len or max((len(ss) for s in seqs for ss in s), default=1)
        first = np.asarray(seqs[0][0])
        data = np.full((B, S, T) + first.shape[1:], pad_value, first.dtype)
        sub_lengths = np.zeros((B, S), np.int32)
        num = np.zeros((B,), np.int32)
        for i, subs in enumerate(seqs):
            num[i] = min(len(subs), S)
            for j, ss in enumerate(subs[:S]):
                ss = np.asarray(ss)[:T]
                data[i, j, :len(ss)] = ss
                sub_lengths[i, j] = len(ss)
        return NestedSeqBatch(jnp.asarray(data), jnp.asarray(sub_lengths),
                              jnp.asarray(num))


def length_mask(lengths: jax.Array, max_len: int) -> jax.Array:
    """[B] lengths -> [B, T] float 1/0 mask."""
    pos = jnp.arange(max_len)[None, :]
    return (pos < lengths[:, None]).astype(jnp.float32)


def segment_mask(q_seg: jax.Array, kv_seg: jax.Array) -> jax.Array:
    """[B, Tq], [B, Tk] segment ids -> [B, Tq, Tk] same-segment mask (0 is pad)."""
    same = (q_seg[:, :, None] == kv_seg[:, None, :])
    valid = (q_seg[:, :, None] > 0) & (kv_seg[:, None, :] > 0)
    return (same & valid).astype(jnp.float32)


def causal_mask(t: int) -> jax.Array:
    return jnp.tril(jnp.ones((t, t), jnp.float32))


def positions_from_segments(segment_ids: np.ndarray) -> np.ndarray:
    """Per-token position within its own segment (host-side). Uses the
    native kernel (``paddle_tpu/native/packer.cpp``) when built; the Python
    loop below is the reference fallback and the equality oracle."""
    seg = np.ascontiguousarray(np.asarray(segment_ids, np.int32))
    b, t = seg.shape
    from ..native import lib as _native_lib
    L = _native_lib()
    if L is not None:
        import ctypes
        out = np.zeros((b, t), np.int32)
        i32p = ctypes.POINTER(ctypes.c_int32)
        L.ptn_positions_from_segments(
            seg.ctypes.data_as(i32p), b, t, out.ctypes.data_as(i32p))
        return out
    out = np.zeros((b, t), np.int32)
    for i in range(b):
        pos, prev = 0, 0
        for j in range(t):
            s = seg[i, j]
            pos = pos + 1 if (s == prev and s != 0) else 0
            out[i, j] = pos
            prev = s
    return out


def pack_sequences(seqs: Sequence[np.ndarray], row_len: int,
                   pad_value=0) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """First-fit bin-pack ragged sequences into ``[rows, row_len]`` slots.

    Returns ``(data, segment_ids, positions)`` (host-side numpy). Sequences longer
    than ``row_len`` are truncated. ``segment_ids`` are 1-based per row; 0 = pad.
    """
    order = np.argsort([-len(s) for s in seqs], kind="stable")
    tail = np.asarray(seqs[0]).shape[1:]
    dtype = np.asarray(seqs[0]).dtype
    slots, offsets, n_rows = _first_fit(
        np.asarray([len(s) for s in seqs], np.int64), order, row_len)
    data = np.full((n_rows, row_len) + tail, pad_value, dtype)
    segment_ids = np.zeros((n_rows, row_len), np.int32)
    nseg = np.zeros(n_rows, np.int32)
    for idx in order:
        s = np.asarray(seqs[idx])[:row_len]
        L = len(s)
        slot, off = slots[idx], offsets[idx]
        data[slot, off:off + L] = s
        nseg[slot] += 1
        segment_ids[slot, off:off + L] = nseg[slot]
    return data, segment_ids, positions_from_segments(segment_ids)


def _first_fit(lengths: np.ndarray, order: np.ndarray, row_len: int):
    """First-fit placement in visit ``order``: per-sequence (slot, offset)
    and the row count. Native kernel when built (``packer.cpp``), Python
    fallback otherwise — both produce identical placements."""
    n = len(lengths)
    from ..native import lib as _native_lib
    L = _native_lib()
    if L is not None and n:
        import ctypes
        slots = np.zeros(n, np.int32)
        offsets = np.zeros(n, np.int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        n_rows = L.ptn_pack_first_fit(
            np.ascontiguousarray(lengths, np.int64).ctypes.data_as(i64p),
            np.ascontiguousarray(order, np.int64).ctypes.data_as(i64p),
            n, row_len,
            slots.ctypes.data_as(i32p), offsets.ctypes.data_as(i32p))
        return slots, offsets, int(n_rows)
    slots = np.zeros(n, np.int32)
    offsets = np.zeros(n, np.int32)
    free: List[int] = []
    for idx in order:
        length = min(int(lengths[idx]), row_len)
        slot = -1
        for r in range(len(free)):
            if free[r] >= length:
                slot = r
                break
        if slot < 0:
            free.append(row_len)
            slot = len(free) - 1
        slots[idx] = slot
        offsets[idx] = row_len - free[slot]
        free[slot] -= length
    return slots, offsets, len(free)


def unpack_sequences(data: np.ndarray, segment_ids: np.ndarray) -> List[np.ndarray]:
    """Inverse of :func:`pack_sequences` (order not preserved)."""
    out = []
    for row, seg in zip(np.asarray(data), np.asarray(segment_ids)):
        for s in range(1, int(seg.max(initial=0)) + 1):
            out.append(row[seg == s])
    return out


def pack_nested_sequences(seqs: Sequence[Sequence[np.ndarray]], row_len: int,
                          pad_value=0):
    """Pack nested (sequence-of-subsequence) data into fixed rows with TWO
    segment levels — the packed-row counterpart of the reference's
    ``subSequenceStartPositions`` (``Argument.h:84-93``).

    Each outer sequence's subsequences are laid out contiguously; rows carry
    ``segment_ids`` (one id per outer sequence) and ``sub_segment_ids`` (one
    id per subsequence, unique within the row). Returns ``(data,
    segment_ids, sub_segment_ids, positions)``; ``positions`` restart at
    each *sub*sequence (the inner recurrence boundary). Outer sequences
    longer than ``row_len`` are truncated whole-subsequence-first.
    """
    flat_seqs = []
    sub_counts = []
    for subs in seqs:
        total = 0
        kept = []
        for ss in subs:
            ss = np.asarray(ss)
            if total + len(ss) > row_len:
                break
            kept.append(ss)
            total += len(ss)
        if not kept:       # degenerate: truncate the first subsequence
            kept = [np.asarray(subs[0])[:row_len]]
        flat_seqs.append(np.concatenate(kept, 0))
        sub_counts.append([len(k) for k in kept])

    data, segment_ids, _ = pack_sequences(flat_seqs, row_len, pad_value)
    # Mark subsequence boundaries using the same placements pack_sequences
    # used (one _first_fit call — identical policy by construction).
    order = np.argsort([-len(s) for s in flat_seqs], kind="stable")
    slots, offsets, _ = _first_fit(
        np.asarray([len(s) for s in flat_seqs], np.int64), order, row_len)
    sub_segment_ids = np.zeros_like(segment_ids)
    sub_counter = np.zeros(segment_ids.shape[0], np.int32)
    for idx in order:
        slot, pos = int(slots[idx]), int(offsets[idx])
        for sublen in sub_counts[idx]:
            sub_counter[slot] += 1
            sub_segment_ids[slot, pos:pos + sublen] = sub_counter[slot]
            pos += sublen
    positions = positions_from_segments(sub_segment_ids)
    return data, segment_ids, sub_segment_ids, positions


def unpack_nested_sequences(data: np.ndarray, segment_ids: np.ndarray,
                            sub_segment_ids: np.ndarray):
    """Inverse of :func:`pack_nested_sequences` (order not preserved):
    returns a list of lists of arrays."""
    out = []
    for row, seg, sub in zip(np.asarray(data), np.asarray(segment_ids),
                             np.asarray(sub_segment_ids)):
        for s in range(1, int(seg.max(initial=0)) + 1):
            sel = seg == s
            subs_here = np.unique(sub[sel])
            out.append([row[sel & (sub == u)] for u in subs_here if u > 0])
    return out
