"""Device & mesh abstraction — the TPU-native ``Place``.

The reference models devices as ``Place = variant<CPUPlace, GPUPlace, FPGAPlace>``
(``/root/reference/paddle/platform/place.h:46-98``) with per-place allocators,
device contexts, and kernel registries. On TPU the device model is a *mesh*: a
logical N-D array of chips over which arrays are sharded and collectives run on
ICI. This module owns mesh construction and the standard logical axis names used
throughout the framework:

  - ``data``  : batch (data parallel; grads psum over this axis)
  - ``model`` : tensor parallel (weight shards; activations all-gather/reduce)
  - ``seq``   : sequence/context parallel (ring attention over ppermute)
  - ``pipe``  : pipeline stages
  - ``expert``: MoE expert parallel

Single-chip runs use a trivial 1-device mesh so every training step is written
once, mesh-polymorphic (the analog of the reference compiling CPU+GPU from one
kernel template, ``paddle/math/BaseMatrix.h:131``).
"""

from __future__ import annotations

import contextlib
import os
from typing import Dict, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "DATA_AXIS", "MODEL_AXIS", "SEQ_AXIS", "PIPE_AXIS", "EXPERT_AXIS",
    "make_mesh", "single_device_mesh", "local_mesh", "default_mesh",
    "current_mesh", "use_mesh", "named_sharding", "replicated", "shard_batch",
    "host_count", "host_id",
]

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"

_tls = __import__("threading").local()


def _mesh_stack() -> list:
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


def make_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Build a mesh from {axis_name: size}. Sizes must multiply to #devices
    (a size of -1 is inferred). Axis order follows dict order; put the
    fastest-communicating axis (model/tensor) innermost so it rides ICI.
    """
    devs = list(devices if devices is not None else jax.devices())
    sizes = dict(axes)
    n = len(devs)
    infer = [k for k, v in sizes.items() if v == -1]
    if len(infer) > 1:
        raise ValueError("at most one axis may be -1")
    known = int(np.prod([v for v in sizes.values() if v != -1]))
    if infer:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[infer[0]] = n // known
    total = int(np.prod(list(sizes.values())))
    if total != n:
        raise ValueError(f"mesh {sizes} needs {total} devices, have {n}")
    arr = np.array(devs).reshape(tuple(sizes.values()))
    return Mesh(arr, tuple(sizes.keys()))


def single_device_mesh(device=None) -> Mesh:
    dev = device or jax.devices()[0]
    return Mesh(np.array([dev]).reshape(1), (DATA_AXIS,))


def local_mesh(data: int = -1, model: int = 1, seq: int = 1) -> Mesh:
    """Mesh over all visible devices: data-parallel outer, model-parallel inner."""
    axes = {DATA_AXIS: data}
    if seq != 1:
        axes[SEQ_AXIS] = seq
    if model != 1:
        axes[MODEL_AXIS] = model
    return make_mesh(axes)


def default_mesh() -> Mesh:
    """All devices on the data axis (pure DP) — the common small-model default."""
    return make_mesh({DATA_AXIS: -1})


def current_mesh() -> Optional[Mesh]:
    stack = _mesh_stack()
    return stack[-1] if stack else None


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    stack = _mesh_stack()
    stack.append(mesh)
    try:
        with mesh:
            yield mesh
    finally:
        stack.pop()


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch, axis: str = DATA_AXIS):
    """Device-put a host batch with leading dim sharded over the data axis."""
    def _put(x):
        if np.ndim(x) == 0:
            return jax.device_put(x, NamedSharding(mesh, P()))
        spec = (axis,) + (None,) * (np.ndim(x) - 1)
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))
    return jax.tree_util.tree_map(_put, batch)


def host_count() -> int:
    return jax.process_count()


def host_id() -> int:
    return jax.process_index()
