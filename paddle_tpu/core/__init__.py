"""Core: module system, mesh/device abstraction, sequence representation, dtypes."""

from . import config, initializers
from .config import build_module, module_config
from .dtypes import Policy, bfloat16_compute, current_policy, float32, use_policy
from .mesh import (DATA_AXIS, EXPERT_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS,
                   default_mesh, local_mesh, make_mesh, named_sharding,
                   replicated, shard_batch, single_device_mesh, use_mesh)
from .module import Module, Sequential, current_rng, no_params
from .sequence import (SeqBatch, causal_mask, length_mask, pack_sequences,
                       positions_from_segments, segment_mask, unpack_sequences)
