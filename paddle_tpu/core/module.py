"""Functional module system — the TPU-native successor of the reference's layer graph.

The reference builds models as a C++ ``Layer`` graph driven by protobuf configs
(``paddle/gserver/layers/Layer.h:62``, ``python/paddle/trainer/config_parser.py``).
Here a model is a tree of :class:`Module` objects that produce *pure functions*:

    net = Linear(10)
    variables = net.init(rng, x)          # {'params': {...}, 'state': {...}}
    y = net.apply(variables, x)           # pure — safe under jax.jit / pjit / grad

Parameters live in a plain nested-dict pytree, so every JAX transform
(``jit``/``grad``/``vmap``/``pjit``/``shard_map``) applies directly; sharding a model
over a TPU mesh is just sharding this pytree (see ``paddle_tpu.parallel``).

Mutable collections ("state", e.g. BatchNorm running stats — the analog of the
reference's ``Parameter`` typed buffers, ``paddle/parameter/Parameter.h:60``) are
threaded functionally: ``apply(..., mutable=('state',))`` returns
``(out, updated_variables)``.
"""

from __future__ import annotations

import threading
import zlib
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from . import initializers as init_lib

__all__ = ["Module", "Sequential", "current_rng", "no_params",
           "is_initializing"]


class ModuleError(Exception):
    pass


class _Frame:
    """Per-init/apply execution context (thread-local)."""

    __slots__ = ("variables", "rngs", "mode", "mutable", "path", "counters",
                 "rng_counters", "touched", "active")

    def __init__(self, variables, rngs, mode, mutable):
        self.variables = variables          # {'params': nested, 'state': nested, ...}
        self.rngs = dict(rngs or {})        # {'params': key, 'dropout': key, ...}
        self.mode = mode                    # 'init' | 'apply'
        self.mutable = frozenset(mutable)
        self.path: list[str] = []
        self.counters: Dict[Tuple[str, ...], Dict[str, int]] = {}
        self.rng_counters: Dict[Tuple[str, ...], int] = {}
        self.touched = False                # any state write happened
        self.active: list[int] = []         # module-instance id stack


_tls = threading.local()


def _frame() -> _Frame:
    fr = getattr(_tls, "frame", None)
    if fr is None:
        raise ModuleError(
            "Module methods that access parameters must run under "
            "Module.init(...) or Module.apply(...).")
    return fr


def _get_node(tree: dict, path: Sequence[str], create: bool) -> dict:
    node = tree
    for p in path:
        if p not in node:
            if not create:
                raise KeyError("/".join(path))
            node[p] = {}
        node = node[p]
    return node


def is_initializing() -> bool:
    """True while tracing under ``Module.init`` (parameter creation), False
    under ``apply`` or outside any module frame. Lets a forward() choose a
    trace-only fast path (e.g. the rematerialized scan-over-layers in
    ``models/transformer.py``) that cannot create parameters itself."""
    fr = getattr(_tls, "frame", None)
    return fr is not None and fr.mode == "init"


def current_rng(kind: str = "dropout") -> jax.Array:
    """Fetch a fresh RNG key of the given kind inside forward()."""
    fr = _frame()
    if kind not in fr.rngs:
        if fr.mode == "init" and "params" in fr.rngs:
            # During init any stream derives from the main key — init(train=True)
            # with dropout must not force the caller to thread extra rngs.
            fr.rngs[kind] = jax.random.fold_in(
                fr.rngs["params"], zlib.crc32(kind.encode()) & 0x7FFFFFFF)
        else:
            raise ModuleError(
                f"rng '{kind}' requested but not provided; pass "
                f"rngs={{'{kind}': key}} to init/apply")
    path = tuple(fr.path)
    cnt = fr.rng_counters.get((kind,) + path, 0)
    fr.rng_counters[(kind,) + path] = cnt + 1
    key = fr.rngs[kind]
    # Deterministic per-path derivation. Must be stable across processes (multi-host
    # SPMD inits the same params everywhere), so use crc32, not salted hash().
    h = zlib.crc32("/".join((kind,) + path + (str(cnt),)).encode()) & 0x7FFFFFFF
    return jax.random.fold_in(key, h)


class Module:
    """Base class. Subclasses define hyperparameters in ``__init__`` (always call
    ``super().__init__()``) and computation in ``forward(*args, **kwargs)``.

    Submodules may be created in ``__init__`` (preferred — attribute name becomes
    the parameter-tree key) or inline in ``forward`` (auto-named ``Cls_i``).
    Calling the same Module instance twice shares its parameters (the reference's
    parameter-sharing via shared ``ParameterConfig`` names).
    """

    def __init__(self, name: Optional[str] = None):
        object.__setattr__(self, "_name", name)

    def __init_subclass__(cls, **kw):
        """Every Module subclass auto-registers in the model-IR registry and
        records its constructor args on instantiation, making any model
        serializable to a config ("config is data" — the reference's
        ModelConfig contract, ``proto/ModelConfig.proto:656``; see
        ``paddle_tpu.core.config``)."""
        super().__init_subclass__(**kw)
        import functools

        from . import config as _config
        if "<locals>" not in cls.__qualname__:
            _config.register_module(cls)
        orig = cls.__init__

        @functools.wraps(orig)
        def recording_init(self, *args, **kwargs):
            if not hasattr(self, "_init_record"):   # outermost subclass wins
                object.__setattr__(self, "_init_record",
                                   {"cls": cls, "args": args,
                                    "kwargs": kwargs})
            orig(self, *args, **kwargs)

        cls.__init__ = recording_init

    # -- naming ---------------------------------------------------------------

    def __setattr__(self, key, value):
        if isinstance(value, Module) and getattr(value, "_name", None) is None:
            object.__setattr__(value, "_name", key)
        elif isinstance(value, (list, tuple)):
            for i, v in enumerate(value):
                if isinstance(v, Module) and getattr(v, "_name", None) is None:
                    object.__setattr__(v, "_name", f"{key}_{i}")
        object.__setattr__(self, key, value)

    def _ensure_name(self, fr: _Frame) -> str:
        if self._name is None:
            level = fr.counters.setdefault(tuple(fr.path), {})
            cls = type(self).__name__
            idx = level.get(cls, 0)
            level[cls] = idx + 1
            object.__setattr__(self, "_name", f"{cls}_{idx}")
        return self._name

    # -- variable access ------------------------------------------------------

    def param(self, name: str, init: Callable, shape: Sequence[int] = (),
              dtype=None) -> jax.Array:
        """Declare/fetch a trainable parameter at the current path."""
        fr = _frame()
        coll = fr.variables.setdefault("params", {})
        node = _get_node(coll, fr.path, create=(fr.mode == "init"))
        if fr.mode == "init" and name not in node:
            rng = current_rng("params")
            node[name] = init(rng, tuple(shape), dtype or jnp.float32)
        if name not in node:
            raise ModuleError(f"missing param {'/'.join(fr.path + [name])}")
        return node[name]

    def state(self, name: str, init: Callable, shape: Sequence[int] = (),
              dtype=None) -> jax.Array:
        """Declare/fetch a non-trainable state variable (running stats etc.)."""
        fr = _frame()
        coll = fr.variables.setdefault("state", {})
        node = _get_node(coll, fr.path, create=(fr.mode == "init"))
        if fr.mode == "init" and name not in node:
            if callable(init):
                import inspect
                try:
                    nargs = len(inspect.signature(init).parameters)
                except (TypeError, ValueError):
                    nargs = 3
                if nargs == 0:
                    node[name] = init()
                else:
                    node[name] = init(jax.random.PRNGKey(0), tuple(shape),
                                      dtype or jnp.float32)
            else:
                node[name] = init
        if name not in node:
            raise ModuleError(f"missing state {'/'.join(fr.path + [name])}")
        return node[name]

    def subtree(self, collection: str = "params"):
        """This submodule's raw ``collection`` subtree — callable from the
        PARENT's forward, without entering the submodule's scope. The scan/
        remat paths use it to stack homogeneous sibling submodules' params
        ([L, ...] leading layer axis) and re-apply one submodule over the
        stack (``models/transformer.py``)."""
        fr = _frame()
        name = self._ensure_name(fr)
        return _get_node(fr.variables.get(collection, {}),
                         list(fr.path) + [name], create=False)

    def update_state(self, name: str, value: jax.Array) -> None:
        """Write a state variable. No-op outside init unless 'state' is mutable."""
        fr = _frame()
        if fr.mode == "apply" and "state" not in fr.mutable:
            return
        coll = fr.variables.setdefault("state", {})
        node = _get_node(coll, fr.path, create=True)
        node[name] = value
        fr.touched = True

    # -- execution ------------------------------------------------------------

    def scope(self):
        """Context manager entering this module's parameter scope — for methods
        other than forward() that declare/fetch params (e.g. RNN cell ``step``,
        ``Embedding.table``, ``CRF.weights``). Idempotent: if this instance's
        scope is already active (we're inside its forward or another scoped
        method), no extra path segment is pushed — so helper methods can wrap
        themselves in scope() and be callable both internally and externally."""
        import contextlib

        @contextlib.contextmanager
        def _scope():
            fr = _frame()
            if fr.active and fr.active[-1] == id(self):
                yield self
                return
            name = self._ensure_name(fr)
            fr.path.append(name)
            fr.active.append(id(self))
            fr.counters[tuple(fr.path)] = {}
            try:
                yield self
            finally:
                fr.path.pop()
                fr.active.pop()
        return _scope()

    def __call__(self, *args, **kwargs):
        fr = _frame()
        name = self._ensure_name(fr)
        fr.path.append(name)
        fr.active.append(id(self))
        fr.counters[tuple(fr.path)] = {}
        try:
            return self.forward(*args, **kwargs)
        finally:
            fr.path.pop()
            fr.active.pop()

    def forward(self, *args, **kwargs):  # pragma: no cover - abstract
        raise NotImplementedError

    def init(self, rng, *args, rngs: Optional[dict] = None, **kwargs):
        """Run forward once, creating all variables. Returns the variables dict."""
        all_rngs = {"params": rng}
        if rngs:
            all_rngs.update(rngs)
        fr = _Frame({}, all_rngs, "init", mutable=("params", "state"))
        prev = getattr(_tls, "frame", None)
        _tls.frame = fr
        try:
            self(*args, **kwargs)
        finally:
            _tls.frame = prev
        fr.variables.setdefault("params", {})
        fr.variables.setdefault("state", {})
        return fr.variables

    def apply(self, variables, *args, rngs: Optional[dict] = None,
              mutable: Sequence[str] = (), method=None, **kwargs):
        """Pure application. With ``mutable`` non-empty returns (out, new_vars).

        ``method`` names (or is) an alternative entry point — e.g.
        ``model.apply(vs, x, method="generate")`` for beam search or
        ``crf.apply(vs, em, lengths, method="decode")`` — executed inside this
        module's parameter scope.
        """
        if isinstance(mutable, str):
            mutable = (mutable,)
        # Shallow-copy the mutable collections so writes don't alias caller state.
        vs = dict(variables)
        for c in mutable:
            vs[c] = jax.tree_util.tree_map(lambda x: x, vs.get(c, {}))
        fr = _Frame(vs, rngs, "apply", mutable=mutable)
        prev = getattr(_tls, "frame", None)
        _tls.frame = fr
        try:
            if method is None:
                out = self(*args, **kwargs)
            else:
                fn = getattr(self, method) if isinstance(method, str) else method
                with self.scope():
                    out = fn(*args, **kwargs)
        finally:
            _tls.frame = prev
        if mutable:
            return out, {c: fr.variables.get(c, {}) for c in mutable}
        return out


class Sequential(Module):
    """Chain of modules applied in order (the reference's linear layer stacking)."""

    def __init__(self, *layers: Module, name: Optional[str] = None):
        super().__init__(name=name)
        self.layers = list(layers)

    def forward(self, x, **kwargs):
        for layer in self.layers:
            x = layer(x, **_filter_kwargs(layer, kwargs))
        return x


def _filter_kwargs(mod: Module, kwargs: dict) -> dict:
    """Keep only kwargs the layer's forward can accept (by name or **kwargs)."""
    if not kwargs:
        return kwargs
    import inspect
    try:
        sig = inspect.signature(mod.forward)
    except (TypeError, ValueError):
        return {}
    if any(p.kind == inspect.Parameter.VAR_KEYWORD
           for p in sig.parameters.values()):
        return kwargs
    names = {p.name for p in sig.parameters.values()
             if p.kind in (inspect.Parameter.POSITIONAL_OR_KEYWORD,
                           inspect.Parameter.KEYWORD_ONLY)}
    return {k: v for k, v in kwargs.items() if k in names}


def no_params(fn: Callable) -> Callable:
    """Wrap a pure function as a Module-compatible callable."""
    class _Fn(Module):
        def forward(self, *a, **k):
            return fn(*a, **k)
    m = _Fn(name=getattr(fn, "__name__", "fn"))
    return m
