"""Model IR — "config is data", the TPU-native ``ModelConfig``.

The reference's whole v1 surface rests on serializable model configs: Python
generates a protobuf graph (``/root/reference/python/paddle/trainer/
config_parser.py:4289`` -> ``proto/ModelConfig.proto:656``) that the C++
engine instantiates, and deployment ships exactly that config next to the
weights (``trainer/MergeModel.cpp:17``). Here the graph engine is the Module
tree itself, so the IR records *how to rebuild the tree*: every Module
subclass auto-registers, every instantiation records its constructor args
(hyperparameters only — arrays never appear in configs), and
:func:`module_config` / :func:`build_module` round-trip a model through plain
JSON. Shared submodule instances (tied weights) serialize as references.

Security: :func:`build_module` only instantiates classes from ``paddle_tpu``
or explicitly registered ones unless ``trusted=True`` — a model file is data,
not code.
"""

from __future__ import annotations

import importlib
from typing import Any, Callable, Dict

__all__ = ["register_module", "register_callable", "module_config",
           "build_module", "config_to_json", "config_from_json"]

MODULE_REGISTRY: Dict[str, type] = {}
_FN_BY_NAME: Dict[str, Callable] = {}
_FN_BY_ID: Dict[int, str] = {}


def _qualname(cls: type) -> str:
    # ':' separates the importable module from the (possibly nested, dotted)
    # class qualname so the two can be split apart unambiguously on load.
    return f"{cls.__module__}:{cls.__qualname__}"


def register_module(cls: type) -> type:
    """Register a Module class for IR round-trips (automatic for every
    Module subclass via ``__init_subclass__``)."""
    MODULE_REGISTRY[_qualname(cls)] = cls
    return cls


def register_callable(name: str, fn: Callable) -> Callable:
    """Register a plain callable (initializer etc.) so it may appear in
    constructor args."""
    _FN_BY_NAME[name] = fn
    _FN_BY_ID[id(fn)] = name
    return fn


def _register_builtin_callables():
    from . import initializers as I
    for name in getattr(I, "__all__", dir(I)):
        fn = getattr(I, name, None)
        if callable(fn) and id(fn) not in _FN_BY_ID:
            register_callable(f"initializers.{name}", fn)


_register_builtin_callables()


def _is_module(x) -> bool:
    return hasattr(x, "_init_record") and hasattr(x, "forward")


def _encode(x, memo: Dict[int, int], mods: list):
    if x is None or isinstance(x, (bool, int, float, str)):
        return x
    if _is_module(x):
        if id(x) in memo:
            return {"__ref__": memo[id(x)]}
        rec = x._init_record
        if "<locals>" in rec["cls"].__qualname__:
            raise TypeError(
                f"{rec['cls'].__qualname__} is a local class (e.g. a "
                f"no_params wrapper) and cannot be rebuilt from a config")
        idx = len(mods)
        memo[id(x)] = idx
        mods.append(None)          # reserve slot (stable index under nesting)
        mods[idx] = {
            "class": _qualname(rec["cls"]),
            "args": [_encode(a, memo, mods) for a in rec["args"]],
            "kwargs": {k: _encode(v, memo, mods)
                       for k, v in rec["kwargs"].items()},
        }
        return {"__ref__": idx}
    if isinstance(x, type):
        if _qualname(x) not in MODULE_REGISTRY:
            raise TypeError(f"unregistered class in config: {x!r}")
        return {"__cls__": _qualname(x)}
    if callable(x):
        name = _FN_BY_ID.get(id(x))
        if name is None:
            raise TypeError(
                f"non-serializable callable in constructor args: {x!r}; "
                f"register it with paddle_tpu.core.config.register_callable")
        return {"__fn__": name}
    if isinstance(x, tuple):
        return {"__tuple__": [_encode(v, memo, mods) for v in x]}
    if isinstance(x, list):
        return [_encode(v, memo, mods) for v in x]
    if isinstance(x, dict):
        if any(not isinstance(k, str) for k in x):
            raise TypeError(
                "dict constructor args must have string keys to survive a "
                f"JSON round-trip; got keys {list(x)!r}")
        return {"__dict__": {k: _encode(v, memo, mods)
                             for k, v in x.items()}}
    import numpy as np
    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.floating,)):
        return float(x)
    if isinstance(x, np.ndarray) or type(x).__module__.startswith("jax"):
        # Small constant arrays in constructor args (e.g. detection priors)
        # serialize as dtype-tagged nested lists — the ModelConfig analog of
        # the reference's inline parameter blobs.
        arr = np.asarray(x)
        return {"__ndarray__": arr.astype(np.float64).tolist()
                if arr.dtype.kind == "f" else arr.tolist(),
                "dtype": arr.dtype.name, "shape": list(arr.shape)}
    raise TypeError(f"non-serializable constructor arg: {type(x)!r}")


def module_config(model) -> dict:
    """Serialize a Module tree to a JSON-safe dict (the ModelConfig analog)."""
    if not _is_module(model):
        raise TypeError(f"not a Module: {model!r}")
    memo: Dict[int, int] = {}
    mods: list = []
    root = _encode(model, memo, mods)
    return {"format": 1, "modules": mods, "root": root["__ref__"]}


def _resolve_class(qual: str, trusted: bool) -> type:
    if qual in MODULE_REGISTRY:
        return MODULE_REGISTRY[qual]
    if ":" not in qual:
        raise ValueError(f"malformed class reference {qual!r}")
    mod_name = qual.split(":", 1)[0]
    if not trusted and not (mod_name == "paddle_tpu"
                            or mod_name.startswith("paddle_tpu.")):
        raise ValueError(
            f"refusing to import {qual!r} from an untrusted model config; "
            f"pass trusted=True or pre-register the class")
    importlib.import_module(mod_name)   # import triggers auto-registration
    if qual not in MODULE_REGISTRY:
        raise KeyError(f"{qual} did not register as a Module")
    return MODULE_REGISTRY[qual]


def _decode(x, built: list, cfgs: list, trusted: bool):
    if isinstance(x, dict):
        if "__ref__" in x:
            return _build_ref(x["__ref__"], built, cfgs, trusted)
        if "__cls__" in x:
            return _resolve_class(x["__cls__"], trusted)
        if "__fn__" in x:
            if x["__fn__"] not in _FN_BY_NAME:
                raise KeyError(f"unknown callable {x['__fn__']!r}")
            return _FN_BY_NAME[x["__fn__"]]
        if "__tuple__" in x:
            return tuple(_decode(v, built, cfgs, trusted)
                         for v in x["__tuple__"])
        if "__dict__" in x:
            return {k: _decode(v, built, cfgs, trusted)
                    for k, v in x["__dict__"].items()}
        if "__ndarray__" in x:
            import numpy as np
            return np.asarray(x["__ndarray__"],
                              dtype=np.dtype(x["dtype"])).reshape(x["shape"])
        raise ValueError(f"malformed config node: {x!r}")
    if isinstance(x, list):
        return [_decode(v, built, cfgs, trusted) for v in x]
    return x


def _build_ref(idx: int, built: list, cfgs: list, trusted: bool):
    if built[idx] is None:
        cfg = cfgs[idx]
        cls = _resolve_class(cfg["class"], trusted)
        args = [_decode(a, built, cfgs, trusted) for a in cfg["args"]]
        kwargs = {k: _decode(v, built, cfgs, trusted)
                  for k, v in cfg["kwargs"].items()}
        built[idx] = cls(*args, **kwargs)
    return built[idx]


def build_module(config: dict, trusted: bool = False):
    """Rebuild the Module tree from :func:`module_config` output."""
    if config.get("format") != 1:
        raise ValueError(f"unknown config format: {config.get('format')!r}")
    cfgs = config["modules"]
    built: list = [None] * len(cfgs)
    return _build_ref(config["root"], built, cfgs, trusted)


def config_to_json(config: dict) -> str:
    import json
    return json.dumps(config, indent=2, sort_keys=True)


def config_from_json(text: str) -> dict:
    import json
    return json.loads(text)
