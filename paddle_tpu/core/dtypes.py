"""Dtype policy for TPU execution.

The reference is float32-everywhere (``real`` typedef, ``paddle/math``). On TPU the
MXU natively multiplies bfloat16 with float32 accumulation, so the framework uses a
*policy*: params kept in float32, compute optionally cast to bfloat16, reductions
and losses in float32. This is the standard mixed-precision recipe and is what the
benchmarks run with.
"""

from __future__ import annotations

import contextlib
import dataclasses

import jax.numpy as jnp

__all__ = ["Policy", "float32", "bfloat16_compute", "current_policy", "use_policy",
           "canonicalize"]


@dataclasses.dataclass(frozen=True)
class Policy:
    param_dtype: object = jnp.float32
    compute_dtype: object = jnp.float32
    accum_dtype: object = jnp.float32

    def cast_compute(self, x):
        return jnp.asarray(x, self.compute_dtype)

    def cast_accum(self, x):
        return jnp.asarray(x, self.accum_dtype)


float32 = Policy()
bfloat16_compute = Policy(param_dtype=jnp.float32, compute_dtype=jnp.bfloat16,
                          accum_dtype=jnp.float32)

_tls = __import__("threading").local()


def _stack() -> list:
    if not hasattr(_tls, "stack"):
        _tls.stack = [float32]
    return _tls.stack


def current_policy() -> Policy:
    return _stack()[-1]


@contextlib.contextmanager
def use_policy(policy: Policy):
    _stack().append(policy)
    try:
        yield policy
    finally:
        _stack().pop()


def canonicalize(name) -> object:
    if isinstance(name, str):
        return {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
                "float16": jnp.float16, "int32": jnp.int32,
                "int8": jnp.int8}[name]
    return name
