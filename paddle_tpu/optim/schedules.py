"""Learning-rate schedules.

Mirrors the reference's LR policy set
(``/root/reference/paddle/parameter/LearningRateScheduler.cpp:50-172``): constant,
poly, caffe_poly, exp, discexp, linear (+pass_manual via manual), plus modern
warmup/cosine for the transformer-era models. A schedule is a pure
``step -> multiplier`` function applied to the base LR (multiplied, matching the
reference's ``calcLearningRate`` contract).
"""

from __future__ import annotations

from typing import Callable, Sequence, Tuple

import jax.numpy as jnp

__all__ = ["constant", "poly", "caffe_poly", "exponential", "discexp", "linear",
           "manual", "warmup_linear", "cosine_decay", "chain"]

Schedule = Callable


def constant():
    return lambda step: 1.0


def poly(a: float, b: float):
    """lr * (1 + a*t)^(-b) (reference ``BaseLearningRateScheduler`` poly)."""
    return lambda step: (1.0 + a * step) ** (-b)


def caffe_poly(a: float, b: float, max_steps: int):
    """lr * (1 - t/max)^b (reference caffe_poly)."""
    return lambda step: (1.0 - jnp.minimum(step, max_steps) / max_steps) ** b


def exponential(a: float, b: float):
    """lr * a^(t/b) (reference exp)."""
    return lambda step: a ** (step / b)


def discexp(a: float, b: float):
    """lr * a^floor(t/b) — discrete exponential (reference discexp)."""
    return lambda step: a ** jnp.floor(step / b)


def linear(a: float, b: float):
    """max(lr - a*t, b) as a multiplier of lr=1 (reference linear)."""
    return lambda step: jnp.maximum(1.0 - a * step, b)


def manual(boundaries: Sequence[int], values: Sequence[float]):
    """Piecewise-constant by step/sample count (reference manual &
    pass_manual: ``LearningRateScheduler.cpp:107-150``)."""
    bs = list(boundaries)
    vs = list(values)
    assert len(vs) == len(bs) + 1

    def sched(step):
        mult = jnp.asarray(vs[0])
        for b, v in zip(bs, vs[1:]):
            mult = jnp.where(step >= b, v, mult)
        return mult
    return sched


def warmup_linear(warmup_steps: int):
    return lambda step: jnp.minimum(1.0, (step + 1) / max(1, warmup_steps))


def cosine_decay(decay_steps: int, alpha: float = 0.0):
    def sched(step):
        t = jnp.minimum(step, decay_steps) / decay_steps
        return alpha + (1 - alpha) * 0.5 * (1 + jnp.cos(jnp.pi * t))
    return sched


def chain(*schedules: Schedule) -> Schedule:
    """Multiply schedules (e.g. warmup * cosine)."""
    def sched(step):
        m = 1.0
        for s in schedules:
            m = m * s(step)
        return m
    return sched
