"""First-order optimizers as pure pytree transforms.

The reference's optimizer family (``/root/reference/paddle/parameter/
FirstOrderOptimizer.h:24-346``: SGD+momentum, SparseMomentum, Adagrad,
DecayedAdagrad, AdaDelta, RMSProp, Adam, Adamax, OptimizerWithGradientClipping;
fluid adds ftrl/proximal ops in ``paddle/operators``) runs per-parameter-block on
CPU/GPU or *remotely inside the parameter server* (``ParameterServer2::doOperation``).

TPU-native design: an optimizer is ``(init_state, update)`` over arbitrary
pytrees — state lives on device, is sharded with the params over the mesh (this
replaces the pserver's sharded optimizer state), and the whole update fuses into
the pjit'd train step. Composition (clipping → decay → rule → averaging) mirrors
the reference's updater-hook chain.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from . import schedules as S

__all__ = [
    "Optimizer", "sgd", "momentum", "adagrad", "decayed_adagrad", "adadelta",
    "rmsprop", "adam", "adamax", "ftrl", "lamb", "chain", "clip_by_global_norm",
    "clip_by_value", "weight_decay", "l1_decay", "polyak_average",
    "static_pruning", "apply_updates",
    "global_norm",
]

PyTree = Any
tmap = jax.tree_util.tree_map


@dataclasses.dataclass(frozen=True)
class Optimizer:
    """A gradient transform: state = init(params); updates, state = update(
    grads, state, params, step). ``updates`` are *deltas added to params*."""
    init: Callable[[PyTree], PyTree]
    update: Callable[[PyTree, PyTree, PyTree, jax.Array], Tuple[PyTree, PyTree]]

    def apply(self, grads, state, params, step):
        """Convenience: returns (new_params, new_state)."""
        updates, new_state = self.update(grads, state, params, step)
        return apply_updates(params, updates), new_state


def apply_updates(params, updates):
    return tmap(lambda p, u: (p + u).astype(p.dtype), params, updates)


def global_norm(tree) -> jax.Array:
    leaves = [jnp.sum(jnp.square(x.astype(jnp.float32)))
              for x in jax.tree_util.tree_leaves(tree)]
    return jnp.sqrt(jnp.sum(jnp.stack(leaves))) if leaves else jnp.asarray(0.0)


def _lr_fn(lr, schedule):
    if callable(lr):
        if schedule is not None:
            raise ValueError(
                "pass either a callable lr or (base lr + schedule), not both")
        return lambda step: lr(step)
    sched = schedule or S.constant()
    return lambda step: lr * sched(step)


# -- base rules ---------------------------------------------------------------

def sgd(lr, schedule=None) -> Optimizer:
    """Plain SGD (reference: ``SgdOptimizer``, FirstOrderOptimizer.h:24)."""
    lrf = _lr_fn(lr, schedule)

    def init(params):
        return ()

    def update(grads, state, params, step):
        return tmap(lambda g: -lrf(step) * g.astype(jnp.float32), grads), state
    return Optimizer(init, update)


def momentum(lr, mu: float = 0.9, nesterov: bool = False,
             schedule=None) -> Optimizer:
    """SGD with (Nesterov) momentum (reference: momentum term in
    ``SgdOptimizer``; ``MomentumOp`` in fluid)."""
    lrf = _lr_fn(lr, schedule)

    def init(params):
        return tmap(lambda p: jnp.zeros_like(p, jnp.float32), params)

    def update(grads, vel, params, step):
        new_v = tmap(lambda v, g: mu * v - lrf(step) * g.astype(jnp.float32),
                     vel, grads)
        if nesterov:
            upd = tmap(lambda v, g: mu * v - lrf(step) * g.astype(jnp.float32),
                       new_v, grads)
        else:
            upd = new_v
        return upd, new_v
    return Optimizer(init, update)


def adagrad(lr, eps: float = 1e-6, schedule=None) -> Optimizer:
    """Adagrad (reference: ``AdagradParameterOptimizer``,
    FirstOrderOptimizer.h:124)."""
    lrf = _lr_fn(lr, schedule)

    def init(params):
        return tmap(lambda p: jnp.zeros_like(p, jnp.float32), params)

    def update(grads, accum, params, step):
        new_a = tmap(lambda a, g: a + jnp.square(g.astype(jnp.float32)),
                     accum, grads)
        upd = tmap(lambda a, g: -lrf(step) * g.astype(jnp.float32)
                   / (jnp.sqrt(a) + eps), new_a, grads)
        return upd, new_a
    return Optimizer(init, update)


def decayed_adagrad(lr, rho: float = 0.95, eps: float = 1e-6,
                    schedule=None) -> Optimizer:
    """Decayed Adagrad (reference: ``DecayedAdagradParameterOptimizer``,
    FirstOrderOptimizer.h:153)."""
    lrf = _lr_fn(lr, schedule)

    def init(params):
        return tmap(lambda p: jnp.zeros_like(p, jnp.float32), params)

    def update(grads, accum, params, step):
        new_a = tmap(lambda a, g: rho * a + (1 - rho)
                     * jnp.square(g.astype(jnp.float32)), accum, grads)
        upd = tmap(lambda a, g: -lrf(step) * g.astype(jnp.float32)
                   / (jnp.sqrt(a) + eps), new_a, grads)
        return upd, new_a
    return Optimizer(init, update)


def adadelta(rho: float = 0.95, eps: float = 1e-6, lr: float = 1.0,
             schedule=None) -> Optimizer:
    """AdaDelta (reference: ``AdaDeltaParameterOptimizer``,
    FirstOrderOptimizer.h:181)."""
    lrf = _lr_fn(lr, schedule)

    class St(NamedTuple):
        accum: PyTree
        accum_update: PyTree

    def init(params):
        zeros = lambda: tmap(lambda p: jnp.zeros_like(p, jnp.float32), params)
        return St(zeros(), zeros())  # distinct buffers: donation-safe

    def update(grads, st, params, step):
        new_a = tmap(lambda a, g: rho * a + (1 - rho)
                     * jnp.square(g.astype(jnp.float32)), st.accum, grads)
        upd = tmap(lambda a, au, g: -lrf(step)
                   * jnp.sqrt(au + eps) / jnp.sqrt(a + eps)
                   * g.astype(jnp.float32), new_a, st.accum_update, grads)
        new_au = tmap(lambda au, u: rho * au + (1 - rho) * jnp.square(u),
                      st.accum_update, upd)
        return upd, St(new_a, new_au)
    return Optimizer(init, update)


def rmsprop(lr, rho: float = 0.95, eps: float = 1e-6, momentum_coef: float = 0.0,
            centered: bool = True, schedule=None) -> Optimizer:
    """RMSProp (reference: ``RMSPropParameterOptimizer``,
    FirstOrderOptimizer.h:215 — the reference keeps both E[g^2] and E[g],
    i.e. the centered variant)."""
    lrf = _lr_fn(lr, schedule)

    class St(NamedTuple):
        ms: PyTree
        mg: PyTree
        mom: PyTree

    def init(params):
        zeros = lambda: tmap(lambda p: jnp.zeros_like(p, jnp.float32), params)
        return St(zeros(), zeros(), zeros())

    def update(grads, st, params, step):
        g32 = tmap(lambda g: g.astype(jnp.float32), grads)
        new_ms = tmap(lambda a, g: rho * a + (1 - rho) * jnp.square(g),
                      st.ms, g32)
        if centered:
            new_mg = tmap(lambda a, g: rho * a + (1 - rho) * g, st.mg, g32)
            denom = tmap(lambda ms, mg: jnp.sqrt(ms - jnp.square(mg) + eps),
                         new_ms, new_mg)
        else:
            new_mg = st.mg
            denom = tmap(lambda ms: jnp.sqrt(ms + eps), new_ms)
        raw = tmap(lambda g, d: -lrf(step) * g / d, g32, denom)
        if momentum_coef > 0:
            new_mom = tmap(lambda m, r: momentum_coef * m + r, st.mom, raw)
            return new_mom, St(new_ms, new_mg, new_mom)
        return raw, St(new_ms, new_mg, st.mom)
    return Optimizer(init, update)


def adam(lr, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
         schedule=None) -> Optimizer:
    """Adam (reference: ``AdamParameterOptimizer``, FirstOrderOptimizer.h:268;
    also the pserver-side remote Adam via doOperation)."""
    lrf = _lr_fn(lr, schedule)

    class St(NamedTuple):
        m: PyTree
        v: PyTree

    def init(params):
        zeros = lambda: tmap(lambda p: jnp.zeros_like(p, jnp.float32), params)
        return St(zeros(), zeros())

    def update(grads, st, params, step):
        t = step + 1
        new_m = tmap(lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32),
                     st.m, grads)
        new_v = tmap(lambda v, g: b2 * v + (1 - b2)
                     * jnp.square(g.astype(jnp.float32)), st.v, grads)
        bc1 = 1 - b1 ** t
        bc2 = 1 - b2 ** t
        upd = tmap(lambda m, v: -lrf(step) * (m / bc1)
                   / (jnp.sqrt(v / bc2) + eps), new_m, new_v)
        return upd, St(new_m, new_v)
    return Optimizer(init, update)


def adamax(lr, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
           schedule=None) -> Optimizer:
    """Adamax (reference: ``AdamaxParameterOptimizer``,
    FirstOrderOptimizer.h:303)."""
    lrf = _lr_fn(lr, schedule)

    class St(NamedTuple):
        m: PyTree
        u: PyTree

    def init(params):
        zeros = lambda: tmap(lambda p: jnp.zeros_like(p, jnp.float32), params)
        return St(zeros(), zeros())

    def update(grads, st, params, step):
        t = step + 1
        new_m = tmap(lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32),
                     st.m, grads)
        new_u = tmap(lambda u, g: jnp.maximum(b2 * u,
                                              jnp.abs(g.astype(jnp.float32))),
                     st.u, grads)
        upd = tmap(lambda m, u: -lrf(step) / (1 - b1 ** t) * m / (u + eps),
                   new_m, new_u)
        return upd, St(new_m, new_u)
    return Optimizer(init, update)


def ftrl(lr, lambda1: float = 0.0, lambda2: float = 0.0, beta: float = 1.0,
         schedule=None) -> Optimizer:
    """FTRL-proximal (fluid ``ftrl_op.cc``) — the sparse-LR/CTR optimizer."""
    lrf = _lr_fn(lr, schedule)

    class St(NamedTuple):
        n: PyTree
        z: PyTree

    def init(params):
        zeros = lambda: tmap(lambda p: jnp.zeros_like(p, jnp.float32), params)
        return St(zeros(), zeros())

    def update(grads, st, params, step):
        lr_t = lrf(step)

        def upd_leaf(n, z, g, p):
            g = g.astype(jnp.float32)
            p = p.astype(jnp.float32)
            new_n = n + g * g
            sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr_t
            new_z = z + g - sigma * p
            new_p = jnp.where(
                jnp.abs(new_z) <= lambda1,
                0.0,
                -(new_z - jnp.sign(new_z) * lambda1)
                / ((beta + jnp.sqrt(new_n)) / lr_t + lambda2))
            return new_p - p, new_n, new_z

        triples = tmap(upd_leaf, st.n, st.z, grads, params)
        outer = jax.tree_util.tree_structure(grads)
        inner = jax.tree_util.tree_structure((0, 0, 0))
        upd, new_n, new_z = jax.tree_util.tree_transpose(outer, inner, triples)
        return upd, St(new_n, new_z)
    return Optimizer(init, update)


def lamb(lr, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-6,
         wd: float = 0.0, schedule=None) -> Optimizer:
    """LAMB — layerwise-adaptive Adam for large-batch TPU training (beyond the
    reference's set; standard for pod-scale data parallelism)."""
    lrf = _lr_fn(lr, schedule)

    class St(NamedTuple):
        m: PyTree
        v: PyTree

    def init(params):
        zeros = lambda: tmap(lambda p: jnp.zeros_like(p, jnp.float32), params)
        return St(zeros(), zeros())

    def update(grads, st, params, step):
        t = step + 1
        new_m = tmap(lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32),
                     st.m, grads)
        new_v = tmap(lambda v, g: b2 * v + (1 - b2)
                     * jnp.square(g.astype(jnp.float32)), st.v, grads)

        def upd_leaf(m, v, p):
            mhat = m / (1 - b1 ** t)
            vhat = v / (1 - b2 ** t)
            r = mhat / (jnp.sqrt(vhat) + eps) + wd * p.astype(jnp.float32)
            p_norm = jnp.linalg.norm(p.astype(jnp.float32))
            r_norm = jnp.linalg.norm(r)
            trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
            return -lrf(step) * trust * r

        return tmap(upd_leaf, new_m, new_v, params), St(new_m, new_v)
    return Optimizer(init, update)


# -- composable wrappers (the reference's clipping/decay/averaging hooks) -----

def chain(*transforms: Optimizer) -> Optimizer:
    """Compose gradient transforms left-to-right (clip → decay → rule)."""

    def init(params):
        return tuple(t.init(params) for t in transforms)

    def update(grads, states, params, step):
        new_states = []
        cur = grads
        for t, st in zip(transforms, states):
            cur, ns = t.update(cur, st, params, step)
            new_states.append(ns)
        return cur, tuple(new_states)
    return Optimizer(init, update)


def clip_by_global_norm(max_norm: float) -> Optimizer:
    """Global-norm clip (reference: ``OptimizerWithGradientClipping``,
    FirstOrderOptimizer.h:346 + ``error_clipping_threshold``)."""

    def init(params):
        return ()

    def update(grads, state, params, step):
        norm = global_norm(grads)
        scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
        return tmap(lambda g: g * scale, grads), state
    return Optimizer(init, update)


def clip_by_value(limit: float) -> Optimizer:
    def init(params):
        return ()

    def update(grads, state, params, step):
        return tmap(lambda g: jnp.clip(g, -limit, limit), grads), state
    return Optimizer(init, update)


def weight_decay(decay: float) -> Optimizer:
    """L2 decay added to gradients (reference: ``Regularizer.cpp`` L2, applied
    pre-update; decoupled variants can chain after the rule instead)."""

    def init(params):
        return ()

    def update(grads, state, params, step):
        return tmap(lambda g, p: g + decay * p.astype(g.dtype), grads,
                    params), state
    return Optimizer(init, update)


def l1_decay(decay: float) -> Optimizer:
    """L1 subgradient decay (reference: ``Regularizer.cpp`` L1 with lazy
    catch-up for sparse rows; dense form here)."""

    def init(params):
        return ()

    def update(grads, state, params, step):
        return tmap(lambda g, p: g + decay * jnp.sign(p.astype(g.dtype)),
                    grads, params), state
    return Optimizer(init, update)


def polyak_average(decay: float = 0.999) -> "EMA":
    return EMA(decay)


@dataclasses.dataclass(frozen=True)
class EMA:
    """Polyak/EMA parameter averaging (reference: ``AverageOptimizer``,
    ``parameter/AverageOptimizer.h`` — apply/restore around eval)."""
    decay: float = 0.999

    def init(self, params):
        return tmap(lambda p: p.astype(jnp.float32), params)

    def update(self, avg, params, step=None):
        d = self.decay
        return tmap(lambda a, p: d * a + (1 - d) * p.astype(jnp.float32),
                    avg, params)


def static_pruning(inner: Optimizer, sparsity: float) -> Optimizer:
    """Static magnitude pruning around an optimizer (reference:
    ``StaticPruningHook``, ``parameter/ParameterUpdaterHook.cpp:39-61`` —
    a fixed sparsity mask chosen by |weight| magnitude, applied so pruned
    weights stay exactly zero through training).

    The mask is computed once from the params seen at ``init`` (per-tensor
    magnitude threshold at the given sparsity) and stored in the optimizer
    state; every update is masked and the first update also zeroes the
    pruned weights themselves (mask*param - param correction).
    """
    assert 0.0 <= sparsity < 1.0

    class St(NamedTuple):
        inner: PyTree
        mask: PyTree
        first: jax.Array

    def make_mask(p):
        flat = jnp.abs(p.astype(jnp.float32)).ravel()
        k = int(sparsity * flat.size)
        if k == 0:
            return jnp.ones_like(p, jnp.float32)
        # rank-based (argsort of argsort): exactly k entries pruned even
        # under ties — a magnitude threshold would wipe a whole
        # zero-initialized tensor (every bias) instead of the k requested
        rank = jnp.argsort(jnp.argsort(flat))
        return (rank >= k).astype(jnp.float32).reshape(p.shape)

    def init(params):
        return St(inner.init(params), tmap(make_mask, params),
                  jnp.asarray(True))

    def update(grads, st, params, step):
        grads = tmap(lambda g, m: g * m.astype(g.dtype), grads, st.mask)
        upd, new_inner = inner.update(grads, st.inner, params, step)
        upd = tmap(lambda u, m: u * m.astype(u.dtype), upd, st.mask)
        # on the first step also snap pruned weights to exactly zero
        upd = tmap(lambda u, p, m: jnp.where(
            st.first & (m < 0.5), -p.astype(u.dtype), u),
            upd, params, st.mask)
        return upd, St(new_inner, st.mask, jnp.asarray(False))

    return Optimizer(init, update)
