"""Sparse-row parameter updates — the SelectedRows / SparseRowMatrix analog.

The reference trains huge embedding tables by never touching the full table
in a step: the trainer prefetches only the rows present in the batch
(``GradientMachine::prefetch``; ``SparseRemoteParameterUpdater``,
``trainer/RemoteParameterUpdater.h:265``), gradients are per-row
(``SelectedRows``, ``framework/selected_rows.h``; ``SparseRowCpuMatrix``,
``math/SparseRowMatrix.h:31``), the optimizer updates only touched rows, and
L1/L2 regularisation catches up lazily when a row is next touched
(``parameter/Regularizer.cpp``).

TPU-native translation — the same contract without a parameter server:

1. **Prefetch = fixed-size unique + gather.** ``jnp.unique(size=...)`` keeps
   shapes static under jit; out-of-vocab padding collapses onto a sentinel
   row that is masked on gather and dropped on scatter.
2. **The gradient is taken w.r.t. the GATHERED rows [U, D]**, not the table:
   the model consumes ``rows[gather_idx]``, so ``jax.grad`` produces a
   naturally row-sparse gradient and nothing [vocab, D]-shaped ever enters
   the autodiff graph (the dense ``jnp.take`` VJP would materialise a full
   table-shaped buffer every step — the exact failure SURVEY §2.3's sparse
   row demanded we avoid).
3. **Row-wise optimizers for free**: every elementwise rule in
   :mod:`.optimizers` (sgd/adagrad/adam/ftrl/...) operates on the [U, D]
   row slice of its [vocab, D] slot buffers. FTRL and Adagrad are
   lazy-correct by construction (zero grad => zero delta); momentum-style
   rules intentionally differ from their dense counterparts for untouched
   rows, exactly as the reference's dedicated ``SparseMomentumParameter
   Optimizer`` did.
4. **Commit = scatter into donated buffers**: inside one jit with the table
   donated, ``table.at[rows].set`` lowers to an in-place scatter.
5. **Lazy decay catch-up**: per-row last-touched step; on prefetch, the
   multiplicative L2 (and shrinkage L1) the dense run would have applied on
   idle steps is applied in closed form (``Regularizer.cpp`` lazy path).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from .optimizers import Optimizer

__all__ = ["SparseTable", "sparse_table", "sparse_prefetch", "sparse_commit",
           "l2_catchup", "l1_catchup", "Prefetched"]

tmap = jax.tree_util.tree_map


class SparseTable(NamedTuple):
    """A large embedding table + its row-sharded optimizer slots (pytree).

    ``rows``: [vocab, dim]; ``slots``: the row optimizer's state over the
    table (leaves [vocab, dim]); ``last_step``: [vocab] int32, the step each
    row was last updated (-1 = never), driving lazy decay catch-up."""
    rows: jax.Array
    slots: Any
    last_step: jax.Array


class Prefetched(NamedTuple):
    """One batch's working set of a :class:`SparseTable`.

    ``uniq``: [U] unique row ids (sentinel ``vocab`` = padding slot);
    ``gather_idx``: for each flat input id, its position in ``uniq``;
    ``rows``/``slots``: the gathered [U, D] row values / optimizer state;
    ``idle_steps``: [U] steps since each row was last updated (for lazy
    decay catch-up)."""
    uniq: jax.Array
    gather_idx: jax.Array
    rows: jax.Array
    slots: Any
    idle_steps: jax.Array


def sparse_table(init_fn: Callable, rng, vocab: int, dim: int,
                 optimizer: Optimizer,
                 dtype=jnp.float32) -> SparseTable:
    """Materialise a table and its optimizer slots (the only [vocab, D]
    allocations the sparse path ever makes — the same buffers the reference
    held on its pservers)."""
    rows = init_fn(rng, (vocab, dim), dtype)
    return SparseTable(rows=rows, slots=optimizer.init(rows),
                       last_step=jnp.full((vocab,), -1, jnp.int32))


def sparse_prefetch(table: SparseTable, ids: jax.Array, step,
                    catchup: Optional[Callable] = None) -> Prefetched:
    """Gather the batch's unique rows ([U, D], U = ids.size — static shape).

    ``ids``: any-shape int array; negatives (padding) map to the sentinel.
    ``catchup(rows, idle_steps)`` optionally applies lazy decay
    (:func:`l2_catchup` / :func:`l1_catchup`) to the gathered values."""
    vocab = table.rows.shape[0]
    flat = ids.reshape(-1)
    flat = jnp.where((flat >= 0) & (flat < vocab), flat, vocab)
    uniq, gather_idx = jnp.unique(flat, return_inverse=True, size=flat.size,
                                  fill_value=vocab)
    safe = jnp.minimum(uniq, vocab - 1)
    valid = (uniq < vocab)[:, None]
    rows = jnp.take(table.rows, safe, axis=0) * valid.astype(table.rows.dtype)
    slots = tmap(lambda s: jnp.take(s, safe, axis=0), table.slots)
    # Idle steps = steps where a dense run would have applied decay-only
    # updates: every step since the last touch (exclusive) — or since step 0
    # for never-touched rows, matching the dense path which decays all rows
    # from the start.
    last = table.last_step[safe]
    idle = jnp.where(last < 0, step,
                     jnp.maximum(step - last - 1, 0)).astype(jnp.int32)
    if catchup is not None:
        rows = catchup(rows, idle)
    return Prefetched(uniq, gather_idx.reshape(ids.shape), rows, slots, idle)


def sparse_commit(table: SparseTable, pre: Prefetched, new_rows,
                  new_slots, step) -> SparseTable:
    """Scatter updated rows/slots back (out-of-bounds sentinel dropped).
    Donate ``table`` in the enclosing jit and XLA updates in place."""
    rows = table.rows.at[pre.uniq].set(
        new_rows.astype(table.rows.dtype), mode="drop")
    slots = tmap(lambda tbl, new: tbl.at[pre.uniq].set(
        new.astype(tbl.dtype), mode="drop"), table.slots, new_slots)
    last = table.last_step.at[pre.uniq].set(jnp.int32(step), mode="drop")
    return SparseTable(rows, slots, last)


def l2_catchup(lr: float, decay: float) -> Callable:
    """Closed-form catch-up for the idle steps' L2 decay: dense SGD+L2
    multiplies by ``(1 - lr*decay)`` every step a row is not in the batch
    (``Regularizer.cpp`` L2, lazy path)."""

    def apply(rows, idle_steps):
        f = (1.0 - lr * decay) ** idle_steps.astype(jnp.float32)
        return rows * f[:, None].astype(rows.dtype)
    return apply


def l1_catchup(lr: float, decay: float) -> Callable:
    """Closed-form catch-up for idle L1 shrinkage: each idle step moves the
    weight ``lr*decay`` toward zero, stopping at zero
    (``Regularizer.cpp`` L1, lazy path)."""

    def apply(rows, idle_steps):
        shrink = (lr * decay) * idle_steps.astype(jnp.float32)[:, None]
        mag = jnp.maximum(jnp.abs(rows) - shrink.astype(rows.dtype), 0.0)
        return jnp.sign(rows) * mag
    return apply


# -- host-offloaded tables ----------------------------------------------------

class HostSparseTable:
    """A sparse table whose storage lives in HOST memory — for tables larger
    than device HBM (the regime the reference served with parameter
    servers: the trainer only ever held the rows of the current batch,
    ``SparseRemoteParameterUpdater`` + ``CacheRowCpuMatrix``,
    ``math/SparseRowMatrix.h:31``).

    Storage (``rows``, optimizer ``slots``, ``last_step``) are host numpy
    arrays; :meth:`prefetch` gathers the batch's unique rows host-side and
    ships only [U, D] to the device; :meth:`commit` scatters updated rows
    back. The device never sees a [vocab, D] buffer. Same lazy-decay
    catch-up semantics as the device path (:func:`sparse_prefetch`).
    """

    def __init__(self, rows, optimizer: Optimizer, catchup=None):
        self.rows = np.asarray(rows)
        # optimizer slots built on a single-row probe then expanded: avoids
        # ever materialising a second full table on device
        probe = jnp.asarray(self.rows[:1])
        slot_probe = optimizer.init(probe)
        self.slots = tmap(
            lambda s: np.zeros((self.rows.shape[0],) + tuple(s.shape[1:]),
                               s.dtype), slot_probe)
        self.last_step = np.full((self.rows.shape[0],), -1, np.int64)
        self.optimizer = optimizer
        self.catchup = catchup

    @property
    def vocab(self):
        return self.rows.shape[0]

    def prefetch(self, ids, step: int):
        """Host-side unique+gather; returns (uniq [U], gather_idx like ids,
        device rows [U, D], device slots).

        U is FIXED at ``ids.size`` regardless of the batch's duplicate
        structure (padding slots carry the sentinel id ``vocab`` and zero
        rows, exactly like the device path's ``jnp.unique(size=...)``): a
        jitted consumer of the [U, D] rows sees one static shape across
        batches and compiles once — the reference's fixed working set
        (``CacheRowCpuMatrix``, ``math/SparseRowMatrix.h``)."""
        flat = np.asarray(ids).reshape(-1)
        flat = np.where((flat >= 0) & (flat < self.vocab), flat, self.vocab)
        uniq, inverse = np.unique(flat, return_inverse=True)
        pad = flat.size - uniq.size
        if pad:
            uniq = np.concatenate(
                [uniq, np.full(pad, self.vocab, uniq.dtype)])
        valid = uniq < self.vocab
        safe = np.minimum(uniq, self.vocab - 1)
        rows = self.rows[safe] * valid[:, None].astype(self.rows.dtype)
        if self.catchup is not None:
            last = self.last_step[safe]
            idle = np.where(last < 0, step,
                            np.maximum(step - last - 1, 0)).astype(np.int32)
            rows = np.asarray(self.catchup(jnp.asarray(rows),
                                           jnp.asarray(idle)))
        slots = tmap(lambda s: jnp.asarray(s[safe]), self.slots)
        return (uniq, inverse.reshape(np.shape(ids)), jnp.asarray(rows),
                slots)

    def commit(self, uniq, new_rows, new_slots, step: int) -> None:
        """Scatter updated rows/slots back into host storage (in place)."""
        keep = uniq < self.vocab
        idx = uniq[keep]
        self.rows[idx] = np.asarray(new_rows)[keep]
        flat_self = jax.tree_util.tree_leaves(self.slots)
        flat_new = jax.tree_util.tree_leaves(new_slots)
        for s_host, s_new in zip(flat_self, flat_new):
            s_host[idx] = np.asarray(s_new)[keep]
        self.last_step[idx] = step

    def update(self, uniq, row_grads, rows, slots, step) -> None:
        """Row optimizer update + commit in one call (host driver side)."""
        upd, new_slots = self.optimizer.update(row_grads, slots, rows,
                                               jnp.asarray(step))
        self.commit(uniq, np.asarray(rows + upd), new_slots, step)


__all__ += ["HostSparseTable"]
