"""Optimizers as pure pytree transforms + LR schedules (successor of
paddle/parameter optimizers and the pserver's remote optimizer tier)."""

from . import schedules
from .optimizers import (EMA, Optimizer, adadelta, adagrad, adam, adamax,
                         apply_updates, chain, clip_by_global_norm,
                         clip_by_value, decayed_adagrad, ftrl, global_norm,
                         l1_decay, lamb, momentum, polyak_average, rmsprop,
                         sgd, static_pruning, weight_decay)
