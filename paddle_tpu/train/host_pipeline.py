"""Async host pipeline — double-buffered group staging + a bounded
in-flight dispatch window for the fused hot loop.

The problem (telemetry PR's measurement): with ``steps_per_call`` fusion
the per-call dispatch constant is amortized, but the host still serializes
each group: stack K*M batches (``_stack_group``), ``device_put`` them
(``_shard_fused``), dispatch, then ``jax.device_get`` the losses for the
event replay — and that fetch fences the host until the device call
finishes, so group N+1 cannot even be staged while call N runs. The fix is
the standard production input-pipeline design (tf.data, NVIDIA DALI): the
host work moves off the critical path and the host-side result fetch is
deferred behind a bounded window of in-flight calls.

Three cooperating pieces, all host-side (no change to any compiled
program — ``pipeline_depth`` is bit-exact with the serial loop):

- :class:`GroupStager` — ONE background thread that stacks and
  ``device_put``-shards the NEXT group while the current call runs on
  device (double buffering: its input queue holds at most one raw group,
  so at most one group is being staged while one staged group awaits
  dispatch — bounded host memory). ``device_put`` from a worker thread is
  safe: it touches no trainer state, only the shared per-leaf sharding
  rule.
- the bounded **in-flight window** — up to ``pipeline_depth`` dispatched
  fused calls whose host replay (events, costs, evaluator updates,
  logging, telemetry records) is deferred. JAX's async dispatch already
  chains call N's donated outputs into call N+1 without a host sync; the
  window just stops the host from asking for the losses too early.
- the **drain policy** — replay is FIFO, so the serial event order is
  preserved exactly. A drain happens (a) when the window is full (oldest
  group only), (b) BEFORE the save at every ``saving_period`` checkpoint
  boundary (the save must observe a quiesced ``train_state`` — no later
  dispatch may have advanced it — and ``nan_check``'s skip-the-poisoned-
  save rule needs the group's losses on host), and (c) at pass end.

Telemetry accounting (``obs.Telemetry`` step records): ``stage_ms`` is the
background stack+shard wall for the call's group, ``drain_wait_ms`` the
time the host actually blocked fetching the call's losses at drain, and
``overlap_frac`` the fraction of staging cost hidden from the main thread
(1.0 = the staged group was ready the moment the dispatcher wanted it).
Per-call ``device_ms`` is None in pipelined mode — a per-call
``block_until_ready`` fence would serialize exactly the pipeline this
module exists to build (the README "fencing rule"). Per-record
throughput/MFU are therefore absent too (a late-drained group's ~0 wait
would inflate them arbitrarily); ``Telemetry.summary()`` derives the
honest aggregate ``pipelined_steps_per_sec`` from record timestamps.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import queue
import threading
import time
from typing import Any, Callable, List, Optional

from ..obs.trace import tspan

__all__ = ["GroupStager", "StagedUnit", "StagedGroup", "FusedPipeline"]

_log = logging.getLogger("paddle_tpu.host_pipeline")


@dataclasses.dataclass
class StagedUnit:
    """One dispatch-ready slice of a group: the [k, m_eff, batch, ...]
    device tree plus the background staging timings."""
    offset: int          # first host-batch index within the group buffer
    m_eff: int
    batches: Any         # device pytree, already placed by the shared rule
    stack_s: float
    shard_s: float


@dataclasses.dataclass
class StagedGroup:
    buf_start: int       # pass-relative index of the group's first batch
    buf_len: int
    units: List[StagedUnit]
    boundary: bool       # crosses a saving_period checkpoint boundary
    crc: Optional[int]   # fingerprint of the group's last host batch
                         # (computed in the stager, only for boundary groups)
    flow: Optional[int] = None   # tracer flow id linking this group's
                                 # staging span to its dispatch + drain spans


@dataclasses.dataclass
class _PendingGroup:
    """A dispatched-but-not-replayed group in the in-flight window."""
    staged: StagedGroup
    results: List[tuple]     # per-dispatch tuples, the _finalize_group layout
    overlap_frac: Optional[float]


class GroupStager:
    """One background staging thread with a depth-1 input queue.

    ``submit`` hands a raw group to the worker (blocking only when the
    worker is still busy with the previous group — backpressure, so host
    memory stays bounded at ~2 raw groups + the staged device trees).
    ``get`` returns the next staged group in submission order together
    with the time the caller blocked waiting for it. Worker exceptions
    re-raise in the caller at the next ``submit``/``get``.
    """

    _POLL_S = 0.05

    def __init__(self, stage_fn: Callable[[Any], Any],
                 join_timeout: float = 10.0):
        self._stage_fn = stage_fn
        self._join_timeout = float(join_timeout)
        self._in: queue.Queue = queue.Queue(maxsize=1)
        self._out: queue.Queue = queue.Queue()
        self._exc: List[BaseException] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="paddle_tpu.host_pipeline.stager")
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            try:
                item = self._in.get(timeout=self._POLL_S)
            except queue.Empty:
                continue
            try:
                self._out.put(self._stage_fn(item))
            except BaseException as e:   # surface in the consumer
                self._exc.append(e)
                self._out.put(None)      # wake a blocked get()
                return

    def _check(self):
        if self._exc:
            raise self._exc[0]

    def submit(self, work) -> float:
        """Enqueue one raw group; returns seconds blocked on backpressure."""
        t0 = time.perf_counter()
        while True:
            self._check()
            try:
                self._in.put(work, timeout=self._POLL_S)
                return time.perf_counter() - t0
            except queue.Full:
                continue

    def get(self, block: bool):
        """Next staged group as ``(StagedGroup, wait_s)``; None when
        ``block=False`` and nothing is staged yet."""
        t0 = time.perf_counter()
        while True:
            self._check()
            try:
                item = self._out.get(block=False) if not block else \
                    self._out.get(timeout=self._POLL_S)
            except queue.Empty:
                if not block:
                    return None
                continue
            if item is None:             # worker died; _check raises
                self._check()
                raise RuntimeError("host-pipeline stager exited unexpectedly")
            return item, time.perf_counter() - t0

    def close(self) -> bool:
        """Stop the worker and join it. Returns True when the thread
        missed the join deadline (stuck in a long ``device_put``/transport
        call?) — the daemon thread is leaked rather than blocking shutdown
        forever, but never silently: the caller gets the flag and the log
        names the thread."""
        self._stop.set()
        self._thread.join(timeout=self._join_timeout)
        if self._thread.is_alive():
            _log.warning(
                "host-pipeline stager thread %r did not exit within %.1fs "
                "of close() — leaking the daemon thread (a device_put or "
                "transport call is likely wedged); telemetry summary will "
                "carry stager_leaked=True",
                self._thread.name, self._join_timeout)
            return True
        return False


class FusedPipeline:
    """Drives the fused hot loop with ``pipeline_depth`` >= 2: groups flow
    reader -> stager thread -> dispatch -> in-flight window -> FIFO drain.

    The trainer owns the math (``_stage_group_work``, ``_dispatch_fused``,
    ``_finalize_group``); this class owns only the overlap scheduling and
    the drain policy. One instance per pass (the window never spans a
    pass boundary — pass end drains everything).
    """

    def __init__(self, trainer, pass_id, rng, handler, costs, log_period,
                 saving_period, checkpoint_dir, checkpoint_keep, save_fn,
                 depth: int):
        self._tr = trainer
        self._pass_id = pass_id
        self._rng = rng
        self._handler = handler
        self._costs = costs
        self._log_period = log_period
        self._saving_period = saving_period
        self._checkpoint_dir = checkpoint_dir
        self._checkpoint_keep = checkpoint_keep
        self._save_fn = save_fn
        self.depth = max(2, int(depth))
        self._stager = GroupStager(trainer._stage_group_work)
        self._window: collections.deque = collections.deque()
        self._n_submitted = 0
        self._n_dispatched = 0
        # backpressure wait from submit(), attributed to the next
        # dispatched group's overlap accounting
        self._carry_wait_s = 0.0

    # -- submission ---------------------------------------------------------

    def submit(self, buf, buf_start: int):
        """Hand one complete group buffer to the pipeline. Never blocks on
        device results unless the window or a checkpoint boundary forces a
        drain."""
        sp = self._saving_period
        end = buf_start + len(buf)
        boundary = bool(sp and self._checkpoint_dir
                        and end // sp > buf_start // sp)
        self._pump()                      # free the stager's output first
        self._carry_wait_s += self._stager.submit(
            (list(buf), buf_start, boundary))
        self._n_submitted += 1
        self._pump()

    def _pump(self):
        """Dispatch every already-staged group without blocking."""
        while self._n_dispatched < self._n_submitted:
            got = self._stager.get(block=False)
            if got is None:
                return
            self._dispatch_staged(*got)

    def _dispatch_staged(self, sg: StagedGroup, wait_s: float):
        tr = self._tr
        # window bound: make room BEFORE dispatching so at most `depth`
        # calls are ever in flight
        while self._in_flight_calls() >= self.depth:
            self._drain_one()
        wait = wait_s + self._carry_wait_s
        self._carry_wait_s = 0.0
        results, stage_total = [], 0.0
        for u in sg.units:
            losses, stats, health, rec = tr._dispatch_fused(
                None, self._rng, staged=u, defer=True, flow=sg.flow)
            stage_total += u.stack_s + u.shard_s
            results.append((sg.buf_start + u.offset, u.m_eff, losses, stats,
                            tr._host_step, health, rec))
        overlap = (max(0.0, min(1.0, 1.0 - wait / stage_total))
                   if stage_total > 0 else None)
        self._window.append(_PendingGroup(sg, results, overlap))
        self._n_dispatched += 1
        if sg.boundary:
            # checkpoint boundary: the save inside _finalize_group must see
            # train_state quiesced at exactly this group's last step — no
            # later dispatch may run first, so drain everything now.
            self.drain_all()

    def _in_flight_calls(self) -> int:
        return sum(len(g.results) for g in self._window)

    # -- drain --------------------------------------------------------------

    def _drain_one(self):
        pg = self._window.popleft()
        # the drain span closes the group's flow: staging (stager thread)
        # -> dispatch -> drain, arrow-linked in the trace viewer
        with tspan(self._tr.tracer, "drain", flow_end=pg.staged.flow,
                   group=pg.staged.buf_start):
            self._tr._finalize_group(
                self._pass_id, pg.staged.buf_start, pg.staged.buf_len,
                pg.results, self._handler, self._costs, self._log_period,
                self._saving_period, self._checkpoint_dir,
                self._checkpoint_keep, self._save_fn,
                crc_fn=lambda: pg.staged.crc,
                drain_timing=True, overlap_frac=pg.overlap_frac)

    def drain_all(self):
        while self._window:
            self._drain_one()

    def flush(self):
        """Pass end: dispatch everything still staging, then drain the
        whole window (FIFO — serial event order)."""
        while self._n_dispatched < self._n_submitted:
            sg, wait_s = self._stager.get(block=True)
            self._dispatch_staged(sg, wait_s)
        self.drain_all()

    def close(self):
        leaked = self._stager.close()
        if leaked and self._tr.telemetry is not None:
            self._tr.telemetry.stager_leaked = True
