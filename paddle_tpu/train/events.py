"""Training events — the v2 event-callback surface.

Reference: ``/root/reference/python/paddle/v2/event.py`` (BeginPass/EndPass/
BeginIteration/EndIteration with cost + evaluator results) consumed by the
``SGD.train`` loop (``v2/trainer.py:169-194``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

__all__ = ["BeginPass", "EndPass", "BeginIteration", "EndIteration",
           "TestResult", "TelemetryRecord"]


@dataclasses.dataclass
class BeginPass:
    pass_id: int


@dataclasses.dataclass
class EndPass:
    pass_id: int
    metrics: Dict[str, float] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class BeginIteration:
    pass_id: int
    batch_id: int


@dataclasses.dataclass
class EndIteration:
    pass_id: int
    batch_id: int
    step: int
    cost: float
    metrics: Dict[str, float] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class TestResult:
    pass_id: int
    cost: float
    metrics: Dict[str, float] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class TelemetryRecord:
    """Fired once per telemetry step record (fused: one per device call,
    AFTER the call's event replay; plain: one per step) when a
    :class:`paddle_tpu.obs.Telemetry` is attached to the Trainer. The
    ``record`` dict is the same JSON-safe object the sinks received —
    step-time breakdown, retrace/compile counters, health scalars, memory.
    Never fired for untelemetered runs."""
    record: Dict[str, Any] = dataclasses.field(default_factory=dict)
