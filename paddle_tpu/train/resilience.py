"""Elastic fault tolerance: the preemption-aware training supervisor
(ISSUE 10).

The reference fork's answer to fleet-scale flakiness was a dedicated
fault-tolerant tier — the Go pserver/master with etcd leases, CRC'd
checkpoints, and task requeue (PAPER.md, ``go/``). TPU-native, the
pserver tier is gone (gradients move inside the compiled step), so what
remains of that story is exactly the *recovery* path: something must
supervise the training process, classify its failures, and restart it
from the newest VALID checkpoint. PR 1–9 built the crash-atomic save
half (``train/checkpoint.py``); this module is the recovery half —
Gemini's lesson (PAPERS.md [R1]) that goodput comes from making
restart/resume a first-class, continuously tested subsystem, and
Bamboo's [R2] that preemption must be a *clean, expected* exit, not a
failure mode.

Three pieces:

- :func:`run_resilient` — run a training pass factory under retry with
  exponential backoff + seeded jitter. Failures are classified
  (:func:`classify_failure`): a poisoned checkpoint quarantines and
  falls back one pass (the ``load_latest_valid`` chain does the heavy
  lifting during ``Trainer(resume=True)``); transient I/O retries in
  place; a crash retries with resume; a *repeated same-step* failure —
  the deterministic-bug signature — gives up loud instead of burning
  restarts on a fault that will recur forever. NaN losses
  (``FloatingPointError`` from ``nan_check``) are fatal immediately:
  restarting replays the same batches into the same NaN.
- **Preemption**: :func:`install_preemption_handler` turns SIGTERM/
  SIGINT into ``trainer.request_stop()`` — the trainer quiesces at the
  next group boundary (drains the host pipeline and the async
  checkpointer, writes a final mid-pass checkpoint with the iterator
  position) and exits via :class:`~paddle_tpu.train.faults.Preempted`,
  which the supervisor returns as status ``"preempted"``, never
  retries.
- **Telemetry**: every restart/fallback emits a ``kind="restart"`` /
  ``kind="fallback"`` record through the trainer's telemetry (when
  attached), so a run's JSONL tells the whole recovery story.

Dead-host detection and reformed-mesh restart live in
:mod:`paddle_tpu.parallel.multihost` (heartbeat files under the
checkpoint root + ``detect_dead_hosts`` + ``plan_reform``); pass
``heartbeat_interval_s=`` here to have the supervisor keep this host's
heartbeat fresh for the fleet watchdog.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import signal
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import checkpoint as ckpt_lib
from .faults import InjectedCrash, Preempted

__all__ = ["run_resilient", "RunResult", "classify_failure",
           "install_preemption_handler", "SupervisorGaveUp", "Preempted"]

_log = logging.getLogger("paddle_tpu.resilience")


class SupervisorGaveUp(RuntimeError):
    """The supervisor exhausted its restart budget, or the same failure
    recurred at the same step enough times to look deterministic. The
    original failure is chained (``__cause__``)."""


def classify_failure(exc: BaseException) -> str:
    """Map a training failure to a recovery policy:

    - ``"poisoned_checkpoint"`` — checkpoint integrity failure
      (:class:`~paddle_tpu.train.checkpoint.CorruptCheckpointError`):
      quarantine the latest pass and fall back one pass before retrying.
    - ``"fatal"`` — deterministic poison (``FloatingPointError`` from
      ``nan_check``): a restart replays the same batches into the same
      NaN; re-raise immediately, loud.
    - ``"transient_io"`` — ``OSError`` family (flaky disk/transport):
      retry in place after backoff.
    - ``"crash"`` — everything else (including
      :class:`~paddle_tpu.train.faults.InjectedCrash`): retry with
      resume from the newest valid checkpoint.
    """
    if isinstance(exc, ckpt_lib.CorruptCheckpointError):
        return "poisoned_checkpoint"
    if isinstance(exc, FloatingPointError):
        return "fatal"
    if isinstance(exc, InjectedCrash):
        return "crash"
    if isinstance(exc, OSError):
        return "transient_io"
    return "crash"


@dataclasses.dataclass
class RunResult:
    """What :func:`run_resilient` hands back: the terminal status
    (``"completed"`` | ``"preempted"``), the final ``TrainState`` (the
    preempted case returns the quiesced state the checkpoint captured),
    and the recovery ledger — restart count, checkpoint dirs quarantined
    by the fallback chain, and one dict per failed attempt."""
    status: str
    state: Any
    restarts: int
    fallbacks: List[str]
    attempts: List[Dict[str, Any]]
    preempted: Optional[Preempted] = None


def install_preemption_handler(trainer,
                               signals: Tuple[int, ...] = (signal.SIGTERM,
                                                           signal.SIGINT)):
    """Route SIGTERM/SIGINT into ``trainer.request_stop()`` — the
    preemption notice most schedulers send before reclaiming a host. The
    handler only sets a flag (async-signal-safe); the trainer quiesces at
    its next group boundary and exits via
    :class:`~paddle_tpu.train.faults.Preempted`. Returns a ``restore()``
    callable reinstating the previous handlers (call it when the trainer
    is done — e.g. in a ``finally``). Main thread only (CPython's signal
    rule)."""
    prev = {}

    def handler(signum, frame):
        trainer.request_stop(f"signal {signum}")

    for s in signals:
        prev[s] = signal.signal(s, handler)

    def restore():
        for s, h in prev.items():
            signal.signal(s, h)

    return restore


def _emit(trainer, record: Dict[str, Any]) -> None:
    """Best-effort telemetry emit through the attempt's trainer."""
    tel = getattr(trainer, "telemetry", None)
    if tel is not None:
        try:
            tel.emit_event(record)
        except Exception:
            _log.exception("resilience telemetry emit failed")


def run_resilient(make_trainer_fn: Callable[[], Any], reader: Callable,
                  *, checkpoint_dir: str, num_passes: int = 1,
                  max_restarts: int = 3, backoff_s: float = 0.5,
                  backoff_max_s: float = 30.0, same_step_limit: int = 3,
                  seed: int = 0, classify: Callable = classify_failure,
                  sleep_fn: Callable[[float], None] = time.sleep,
                  install_signals: bool = False,
                  heartbeat_interval_s: Optional[float] = None,
                  **train_kwargs) -> RunResult:
    """Run a training job under the restart supervisor.

    Args:
      make_trainer_fn: zero-arg factory returning a FRESH, initialized
        :class:`~paddle_tpu.train.trainer.Trainer` for each attempt (a
        crashed attempt's donated buffers and worker threads are dead
        weight — never reuse the instance).
      reader: the training reader callable, passed to every attempt's
        ``train()``. Must be deterministic for mid-pass resume to replay
        the interrupted pass exactly (the trainer's batch-fingerprint
        check warns when it is not).
      checkpoint_dir: the shared checkpoint root — the supervisor's only
        durable state. Every attempt runs ``resume=True`` against it.
      num_passes / **train_kwargs: forwarded to ``Trainer.train``
        (``saving_period``, ``checkpoint_async``, ``event_handler``, …).
      max_restarts: restart budget; exceeding it raises
        :class:`SupervisorGaveUp` chained to the last failure.
      backoff_s / backoff_max_s: exponential backoff base/cap between
        retries, with seeded multiplicative jitter (+0–25%) so a fleet
        of restarting workers doesn't stampede the checkpoint store.
      same_step_limit: a failure with the same (type, step) signature
        this many times in a row is treated as deterministic — give up
        loud instead of spending the budget on it.
      classify: failure → policy mapping (see :func:`classify_failure`).
      sleep_fn: injection point for tests (replaces ``time.sleep``).
      install_signals: also route SIGTERM/SIGINT to a graceful stop for
        the duration of each attempt (main thread only).
      heartbeat_interval_s: when set, keep this host's heartbeat file
        under ``checkpoint_dir`` fresh for the whole supervised run
        (:class:`paddle_tpu.parallel.multihost.HostHeartbeat`), so a
        fleet watchdog's ``detect_dead_hosts`` sees the truth.

    Returns a :class:`RunResult`; raises :class:`SupervisorGaveUp` when
    the budget is spent, and re-raises ``"fatal"`` failures immediately.
    """
    rng = random.Random(seed)
    attempts: List[Dict[str, Any]] = []
    fallbacks: List[str] = []
    restarts = 0
    last_sig: Optional[Tuple[str, Optional[int]]] = None
    same_sig = 0
    heartbeat = None
    if heartbeat_interval_s is not None:
        from ..parallel import multihost
        heartbeat = multihost.HostHeartbeat(checkpoint_dir,
                                            interval_s=heartbeat_interval_s)
        heartbeat.start()
    try:
        while True:
            trainer = make_trainer_fn()
            restore_signals = (install_preemption_handler(trainer)
                               if install_signals else None)
            try:
                state = trainer.train(reader, num_passes=num_passes,
                                      checkpoint_dir=checkpoint_dir,
                                      resume=True, **train_kwargs)
                fallbacks.extend(trainer.last_quarantined)
                return RunResult(status="completed", state=state,
                                 restarts=restarts, fallbacks=fallbacks,
                                 attempts=attempts)
            except Preempted as p:
                # the CLEAN exit: a graceful stop quiesced and
                # checkpointed — hand control back, never retry
                fallbacks.extend(trainer.last_quarantined)
                _log.warning("training preempted cleanly: %s", p)
                return RunResult(status="preempted",
                                 state=trainer.train_state,
                                 restarts=restarts, fallbacks=fallbacks,
                                 attempts=attempts, preempted=p)
            except Exception as exc:
                fallbacks.extend(trainer.last_quarantined)
                kind = classify(exc)
                step = getattr(exc, "step", None)
                if step is None:
                    step = getattr(trainer, "_host_step", None)
                attempts.append({"failure": kind,
                                 "error": f"{type(exc).__name__}: {exc}",
                                 "step": step})
                if kind == "fatal":
                    _log.error("fatal training failure (%s) — not "
                               "retrying: %s", type(exc).__name__, exc)
                    raise
                sig = (type(exc).__name__, step)
                same_sig = same_sig + 1 if sig == last_sig else 1
                last_sig = sig
                if same_sig >= same_step_limit:
                    raise SupervisorGaveUp(
                        f"failure {sig[0]} at step {sig[1]} recurred "
                        f"{same_sig}x — deterministic, giving up (see "
                        f"attempts ledger)") from exc
                restarts += 1
                if restarts > max_restarts:
                    raise SupervisorGaveUp(
                        f"restart budget exhausted ({max_restarts}); last "
                        f"failure {sig[0]} at step {sig[1]}") from exc
                if kind == "poisoned_checkpoint":
                    # belt and braces: the resume path quarantines on
                    # load, but a poison detected elsewhere (async-save
                    # fence, explicit restore) must not be re-read
                    pid = ckpt_lib.latest_pass(checkpoint_dir)
                    if pid is not None:
                        d = ckpt_lib._resolve_pass_dir(checkpoint_dir, pid)
                        q = ckpt_lib.quarantine_pass_dir(d)
                        fallbacks.append(q)
                        _emit(trainer, {"kind": "fallback", "pass_id": pid,
                                        "quarantined": q})
                delay = min(backoff_max_s,
                            backoff_s * (2.0 ** (restarts - 1)))
                delay *= 1.0 + 0.25 * rng.random()
                _log.warning(
                    "training attempt failed (%s at step %s): %s — "
                    "restart %d/%d after %.2fs backoff",
                    kind, step, exc, restarts, max_restarts, delay)
                _emit(trainer, {"kind": "restart", "attempt": restarts,
                                "failure": kind, "step": step,
                                "error": f"{type(exc).__name__}: "
                                         f"{str(exc)[:200]}",
                                "backoff_s": round(delay, 3)})
                sleep_fn(delay)
            finally:
                if restore_signals is not None:
                    restore_signals()
    finally:
        if heartbeat is not None:
            heartbeat.stop()
