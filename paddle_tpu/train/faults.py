"""Deterministic fault injection for the training loop (ISSUE 10).

The recovery path is only trustworthy if it is *continuously tested* —
the lesson of Gemini's fast-recovery work (PAPERS.md [R1]) and of elastic
systems like Bamboo [R2], and the reason the reference fork shipped a
fault-tolerant Go pserver with its own failure drills (PAPER.md,
``go/pserver``). A recovery path exercised only by real outages is a
recovery path that rots. This module is the injection plane those tests
drive: a :class:`FaultSchedule` is a seeded, step-indexed description of
*which* named injection point fires *when*, threaded through the Trainer,
the checkpoint writer, and the host-pipeline stager.

Design rules:

- **Off by default, zero overhead when off.** ``Trainer(faults=None)``
  takes the exact pre-PR hot loop: every injection point is behind an
  ``if faults is not None`` host-side check, no traced-step change, no
  extra dispatch or fence (``tests/test_resilience.py`` pins this in the
  PR-2/4/6 style).
- **Deterministic.** Points are keyed by optimizer step, save index, or
  group start — never wall clock — so a seeded schedule reproduces the
  same failure in every run, and the kill-anywhere sweep can assert
  bit-equality against the uninterrupted run.
- **One-shot.** Each armed point fires once and disarms (recorded in
  :attr:`FaultSchedule.fired`), so the supervisor's retry of the same
  work proceeds — the injected fault models a transient event (a
  preemption, a flaky disk), not a permanent defect.

Injection points (who checks them):

- ``crash_at_step`` — Trainer, after the host replay of that optimizer
  step: raises :class:`InjectedCrash` (simulated process death mid-pass).
- ``preempt_at_step`` — Trainer, same spot: requests a graceful stop
  (the SIGTERM path without a signal), which quiesces and raises
  :class:`Preempted` at the next group boundary.
- ``fail_save_at`` — ``checkpoint._write_pass_dir``, before any byte is
  written on the N-th save: raises :class:`InjectedSaveError` (the save
  never lands; recovery resumes from the previous pass).
- ``corrupt_checkpoint_file`` — after the N-th save's atomic swap: flips
  one byte in the pass dir's first ``.npz`` so its manifest CRC no
  longer matches (latent corruption the fallback chain must catch).
- ``slow_save`` — after the N-th save's swap: sleeps, exercising the
  drain/fence behavior around a long in-flight async write.
- ``stager_error_at_group`` — the host-pipeline stager thread, at the
  group starting at that batch index: raises in the worker, surfacing
  through ``GroupStager``'s producer-error propagation.

Serving points (ISSUE 11; checked by ``serve.fleet.ServingFleet``, keyed
by fleet tick index or fleet request id — never wall clock):

- ``kill_replica_at_tick`` — ``(tick, replica)``: that replica dies at
  the start of that fleet tick (stops ticking AND heartbeating; the
  router must OBSERVE the death via heartbeat staleness and resubmit).
- ``stall_replica_at_tick`` — ``(tick, replica, n_ticks)``: the replica
  hangs for ``n_ticks`` (no work, no beats) then wakes — the zombie
  drill: if it was declared dead meanwhile it must self-fence.
- ``drop_submit_at`` — fleet request id whose replica delivery is lost
  after the router records the assignment (a lost RPC); the reconcile
  sweep must notice and resubmit.
- ``duplicate_submit_at`` — fleet request id delivered twice (an RPC
  retry racing its original); the rid-keyed idempotency boundary must
  drop the duplicate.

Process-level points (ISSUE 13; a replica is a real child process —
``serve.fleet`` fires these against the transport seam):

- ``sigkill_replica_at_tick`` — ``(tick, replica)``: SIGKILL the
  replica's process at that fleet tick. Unlike ``kill_replica_at_tick``
  (which models death abstractly for in-process workers too), this is
  the REAL kill for subprocess replicas; the beats stop, the router
  observes staleness, the autoscaler cold-spawns a replacement.
- ``transport_hang_at`` — ``(tick, replica)``: that replica's reply to
  the tick message never arrives (the child does the work, the reply is
  lost) — the parent's per-message timeout fires and the at-least-once
  retransmit recovers the cached reply.
- ``corrupt_reply_at`` — ``(tick, replica)``: the reply frame arrives
  garbled (valid length prefix, unparseable body) — classified as a
  transport error, never a router crash; the retransmit recovers.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["FaultSchedule", "InjectedFault", "InjectedCrash",
           "InjectedSaveError", "Preempted"]

_log = logging.getLogger("paddle_tpu.faults")


class InjectedFault(RuntimeError):
    """Base class for every scheduled fault (so tests and classifiers can
    tell injected failures from organic ones)."""


class InjectedCrash(InjectedFault):
    """Simulated process death at an optimizer step (``crash_at_step``)."""

    def __init__(self, msg: str, step: Optional[int] = None):
        super().__init__(msg)
        self.step = step


class InjectedSaveError(OSError):
    """Simulated I/O failure inside the checkpoint write path
    (``fail_save_at``) — an OSError so the supervisor classifies it as
    transient, like a real flaky disk."""


class Preempted(Exception):
    """Graceful-stop exit status: SIGTERM/SIGINT (or an injected
    preemption) was handled at a group boundary — the host pipeline was
    drained, a quiesced checkpoint was written, and training exited
    cleanly mid-run. The supervisor treats this as a CLEAN exit (status
    ``"preempted"``), never as a failure to retry."""

    def __init__(self, pass_id: int, next_batch: int, reason: str = ""):
        super().__init__(
            f"training preempted at pass {pass_id} batch {next_batch}"
            + (f" ({reason})" if reason else ""))
        self.pass_id = pass_id
        self.next_batch = next_batch
        self.reason = reason


class FaultSchedule:
    """A seeded, step-indexed fault plan (see module docstring for the
    points). All indices are 0-based except optimizer steps, which follow
    the trainer's 1-based host step count. Thread-safe: the save and
    stager points fire from worker threads.

    Args:
      seed: folds into nothing today but names the run (recorded in
        ``describe()``) — schedules are fully deterministic by
        construction, the seed exists so sweeps can label themselves.
      crash_at_step: optimizer step (int) after whose host replay an
        :class:`InjectedCrash` raises.
      preempt_at_step: optimizer step at which a graceful stop is
        requested (handled at the next group boundary).
      fail_save_at: save index (0-based count of checkpoint writes
        through this schedule) whose write raises
        :class:`InjectedSaveError` before writing anything.
      corrupt_checkpoint_file: save index whose landed pass dir gets one
        byte flipped in its first ``.npz`` (CRC now mismatches).
      slow_save: ``(save_index, seconds)`` — that save sleeps after its
        swap (worker thread for async saves).
      stager_error_at_group: pass-relative batch index of the group
        whose staging raises :class:`InjectedFault` in the stager
        thread.
    """

    def __init__(self, seed: int = 0, *,
                 crash_at_step: Optional[int] = None,
                 preempt_at_step: Optional[int] = None,
                 fail_save_at: Optional[int] = None,
                 corrupt_checkpoint_file: Optional[int] = None,
                 slow_save: Optional[Tuple[int, float]] = None,
                 stager_error_at_group: Optional[int] = None,
                 kill_replica_at_tick: Optional[Tuple[int, int]] = None,
                 stall_replica_at_tick:
                 Optional[Tuple[int, int, int]] = None,
                 drop_submit_at: Optional[int] = None,
                 duplicate_submit_at: Optional[int] = None,
                 sigkill_replica_at_tick:
                 Optional[Tuple[int, int]] = None,
                 transport_hang_at: Optional[Tuple[int, int]] = None,
                 corrupt_reply_at: Optional[Tuple[int, int]] = None):
        self.seed = int(seed)
        self.crash_at_step = crash_at_step
        self.preempt_at_step = preempt_at_step
        self.fail_save_at = fail_save_at
        self.corrupt_checkpoint_file = corrupt_checkpoint_file
        self.slow_save = slow_save
        self.stager_error_at_group = stager_error_at_group
        self.kill_replica_at_tick = kill_replica_at_tick
        self.stall_replica_at_tick = stall_replica_at_tick
        self.drop_submit_at = drop_submit_at
        self.duplicate_submit_at = duplicate_submit_at
        self.sigkill_replica_at_tick = sigkill_replica_at_tick
        self.transport_hang_at = transport_hang_at
        self.corrupt_reply_at = corrupt_reply_at
        self._lock = threading.Lock()
        self._save_count = 0
        # (point, key) tuples, in firing order — the sweep's assertions
        # and the supervisor's failure signatures both read this
        self.fired: List[Tuple[str, Any]] = []

    # -- bookkeeping ---------------------------------------------------------

    def _fire_once(self, point: str, key) -> bool:
        """Record ``(point, key)`` and return True exactly once."""
        with self._lock:
            if (point, key) in self.fired:
                return False
            self.fired.append((point, key))
        _log.warning("fault injection: %s fired (key=%r)", point, key)
        return True

    def describe(self) -> Dict[str, Any]:
        return {"seed": self.seed,
                "crash_at_step": self.crash_at_step,
                "preempt_at_step": self.preempt_at_step,
                "fail_save_at": self.fail_save_at,
                "corrupt_checkpoint_file": self.corrupt_checkpoint_file,
                "slow_save": self.slow_save,
                "stager_error_at_group": self.stager_error_at_group,
                "kill_replica_at_tick": self.kill_replica_at_tick,
                "stall_replica_at_tick": self.stall_replica_at_tick,
                "drop_submit_at": self.drop_submit_at,
                "duplicate_submit_at": self.duplicate_submit_at,
                "sigkill_replica_at_tick": self.sigkill_replica_at_tick,
                "transport_hang_at": self.transport_hang_at,
                "corrupt_reply_at": self.corrupt_reply_at,
                "fired": list(self.fired)}

    # -- trainer step points -------------------------------------------------

    def maybe_crash_step(self, step: int) -> None:
        """Raise :class:`InjectedCrash` when ``step`` is the armed crash
        step (one-shot). Called by the trainer after each optimizer
        step's host replay."""
        if self.crash_at_step is not None and step == self.crash_at_step \
                and self._fire_once("crash_at_step", step):
            raise InjectedCrash(f"injected crash at step {step}", step=step)

    def should_preempt(self, step: int) -> bool:
        """True (once) when ``step`` is the armed preemption step."""
        return (self.preempt_at_step is not None
                and step == self.preempt_at_step
                and self._fire_once("preempt_at_step", step))

    # -- checkpoint-writer points --------------------------------------------

    def on_write_begin(self, pass_id: int) -> int:
        """Called by ``checkpoint._write_pass_dir`` before any write.
        May raise :class:`InjectedSaveError` — the save never lands.
        Returns this save's index (passed back to
        :meth:`on_write_complete`)."""
        with self._lock:
            idx = self._save_count
            self._save_count += 1
        if self.fail_save_at is not None and idx == self.fail_save_at \
                and self._fire_once("fail_save_at", idx):
            raise InjectedSaveError(
                f"injected save failure (save #{idx}, pass {pass_id})")
        return idx

    def on_write_complete(self, final_dir: str, pass_id: int,
                          idx: int) -> None:
        """Called after the atomic swap landed ``final_dir``. May sleep
        (``slow_save``) or flip a byte in the pass's first ``.npz``
        (``corrupt_checkpoint_file``)."""
        if self.slow_save is not None and idx == self.slow_save[0] \
                and self._fire_once("slow_save", idx):
            time.sleep(float(self.slow_save[1]))
        if self.corrupt_checkpoint_file is not None \
                and idx == self.corrupt_checkpoint_file \
                and self._fire_once("corrupt_checkpoint_file", idx):
            corrupt_one_file(final_dir)

    # -- serving-fleet points (ISSUE 11) -------------------------------------

    def kill_replica_for_tick(self, tick: int) -> Optional[int]:
        """The replica id to kill at fleet tick ``tick`` (one-shot), or
        None. Checked by ``ServingFleet.tick`` before any work."""
        if self.kill_replica_at_tick is not None \
                and tick == self.kill_replica_at_tick[0] \
                and self._fire_once("kill_replica_at_tick", tick):
            return int(self.kill_replica_at_tick[1])
        return None

    def stall_replica_for_tick(self, tick: int
                               ) -> Optional[Tuple[int, int]]:
        """``(replica, n_ticks)`` to stall starting at fleet tick
        ``tick`` (one-shot), or None."""
        if self.stall_replica_at_tick is not None \
                and tick == self.stall_replica_at_tick[0] \
                and self._fire_once("stall_replica_at_tick", tick):
            return (int(self.stall_replica_at_tick[1]),
                    int(self.stall_replica_at_tick[2]))
        return None

    def sigkill_replica_for_tick(self, tick: int) -> Optional[int]:
        """The replica id whose PROCESS gets SIGKILL at fleet tick
        ``tick`` (one-shot), or None. For in-process workers this
        degrades to the abstract kill — the point exists so the same
        schedule drills both replica modes."""
        if self.sigkill_replica_at_tick is not None \
                and tick == self.sigkill_replica_at_tick[0] \
                and self._fire_once("sigkill_replica_at_tick", tick):
            return int(self.sigkill_replica_at_tick[1])
        return None

    def should_hang_transport(self, tick: int, replica: int) -> bool:
        """True (once) when ``replica``'s reply to the tick message at
        fleet tick ``tick`` should be lost (the per-message-timeout
        drill)."""
        return (self.transport_hang_at is not None
                and (tick, replica) == (self.transport_hang_at[0],
                                        self.transport_hang_at[1])
                and self._fire_once("transport_hang_at",
                                    (tick, replica)))

    def should_corrupt_reply(self, tick: int, replica: int) -> bool:
        """True (once) when ``replica``'s reply at fleet tick ``tick``
        should arrive garbled (the classified-corruption drill)."""
        return (self.corrupt_reply_at is not None
                and (tick, replica) == (self.corrupt_reply_at[0],
                                        self.corrupt_reply_at[1])
                and self._fire_once("corrupt_reply_at",
                                    (tick, replica)))

    def should_drop_submit(self, rid: int) -> bool:
        """True (once) when fleet request ``rid``'s replica delivery
        should be lost."""
        return (self.drop_submit_at is not None
                and rid == self.drop_submit_at
                and self._fire_once("drop_submit_at", rid))

    def should_duplicate_submit(self, rid: int) -> bool:
        """True (once) when fleet request ``rid`` should be delivered
        twice."""
        return (self.duplicate_submit_at is not None
                and rid == self.duplicate_submit_at
                and self._fire_once("duplicate_submit_at", rid))

    # -- stager point --------------------------------------------------------

    def maybe_stager_error(self, buf_start: int) -> None:
        """Raise in the stager thread for the group starting at
        ``buf_start`` (one-shot)."""
        if self.stager_error_at_group is not None \
                and buf_start == self.stager_error_at_group \
                and self._fire_once("stager_error_at_group", buf_start):
            raise InjectedFault(
                f"injected stager error at group {buf_start}")


def corrupt_one_file(pass_dir: str) -> Optional[str]:
    """Flip one byte mid-file in ``pass_dir``'s first ``.npz`` — the
    manifest CRC (computed before the flip) no longer matches, so the
    next load raises and the fallback chain must quarantine. Returns the
    corrupted path (None when the dir holds no ``.npz``)."""
    for name in sorted(os.listdir(pass_dir)):
        if not name.endswith(".npz"):
            continue
        path = os.path.join(pass_dir, name)
        size = os.path.getsize(path)
        if size == 0:
            continue
        with open(path, "r+b") as f:
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0xFF]))
        _log.warning("fault injection: corrupted %s (byte %d flipped)",
                     path, size // 2)
        return path
    return None
