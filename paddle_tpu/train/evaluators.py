"""Evaluators — streaming metrics over batches.

Reference: ``/root/reference/paddle/gserver/evaluators/Evaluator.cpp`` registry
(classification_error, precision_recall, pnpair, rankauc, chunk, ctc_edit_
distance, detection_map, sum/column_sum + printers). Design: each evaluator has
a jit-safe ``batch(outputs, batch) -> dict[str, array]`` piece producing small
sufficient statistics on device, and host-side ``update``/``result`` that
accumulate across batches — so metrics ride inside the compiled train step and
only scalars cross to host (no HBM round-trips of activations).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np
import jax.numpy as jnp

__all__ = ["Evaluator", "ClassificationError", "PrecisionRecall", "Auc",
           "RankAuc", "PnPair", "ChunkEvaluator", "CtcErrorEvaluator",
           "DetectionMAP", "SumEvaluator", "ColumnSumEvaluator", "ValuePrinter",
           "MaxIdPrinter", "SequenceTextPrinter", "EvaluatorSet"]


class Evaluator:
    name = "evaluator"

    def batch_stats(self, outputs, batch) -> Dict[str, Any]:
        """Device-side sufficient statistics (runs under jit)."""
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def update(self, stats: Dict[str, np.ndarray]):
        raise NotImplementedError

    def result(self) -> Dict[str, float]:
        raise NotImplementedError


class ClassificationError(Evaluator):
    """top-1 error (reference: ``ClassificationErrorEvaluator``)."""

    def __init__(self, name="classification_error"):
        self.name = name
        self.reset()

    def batch_stats(self, outputs, batch):
        labels = batch["label"]
        pred = jnp.argmax(outputs, axis=-1)
        valid = (labels >= 0)
        wrong = jnp.sum((pred != labels) & valid)
        return {"wrong": wrong, "total": jnp.sum(valid)}

    def reset(self):
        self._wrong = 0
        self._total = 0

    def update(self, stats):
        self._wrong += int(stats["wrong"])
        self._total += int(stats["total"])

    def result(self):
        err = self._wrong / max(1, self._total)
        return {self.name: err, "accuracy": 1.0 - err}


class PrecisionRecall(Evaluator):
    """Binary/micro-averaged precision-recall-F1 (reference:
    ``PrecisionRecallEvaluator``)."""

    def __init__(self, threshold: float = 0.5, name="precision_recall"):
        self.name = name
        self.threshold = threshold
        self.reset()

    def batch_stats(self, outputs, batch):
        labels = batch["label"]
        if outputs.ndim > 1 and outputs.shape[-1] == 2:
            pred = jnp.argmax(outputs, -1)
        else:
            score = outputs[..., 0] if outputs.ndim > 1 else outputs
            pred = (score > self.threshold).astype(jnp.int32)
        labels = labels.astype(jnp.int32)
        tp = jnp.sum((pred == 1) & (labels == 1))
        fp = jnp.sum((pred == 1) & (labels == 0))
        fn = jnp.sum((pred == 0) & (labels == 1))
        return {"tp": tp, "fp": fp, "fn": fn}

    def reset(self):
        self._tp = self._fp = self._fn = 0

    def update(self, stats):
        self._tp += int(stats["tp"])
        self._fp += int(stats["fp"])
        self._fn += int(stats["fn"])

    def result(self):
        p = self._tp / max(1, self._tp + self._fp)
        r = self._tp / max(1, self._tp + self._fn)
        f1 = 2 * p * r / max(1e-9, p + r)
        return {"precision": p, "recall": r, "f1": f1}


class Auc(Evaluator):
    """ROC AUC via fixed-bin histogram (reference: ``AucEvaluator`` — same
    binned approach, Evaluator.cpp)."""

    def __init__(self, num_bins: int = 1024, from_logits: bool = False,
                 name="auc"):
        self.name = name
        self.num_bins = num_bins
        self.from_logits = from_logits
        self.reset()

    def batch_stats(self, outputs, batch):
        import jax
        labels = batch["label"].astype(jnp.int32)
        if outputs.ndim > 1 and outputs.shape[-1] == 2:
            score = jax.nn.softmax(outputs, -1)[..., 1]
        else:
            score = outputs[..., 0] if outputs.ndim > 1 else outputs
            if self.from_logits:      # map logits into [0,1) for binning
                score = jax.nn.sigmoid(score)
        idx = jnp.clip((score * self.num_bins).astype(jnp.int32), 0,
                       self.num_bins - 1)
        pos = jnp.zeros(self.num_bins).at[idx].add(labels == 1)
        neg = jnp.zeros(self.num_bins).at[idx].add(labels == 0)
        return {"pos": pos, "neg": neg}

    def reset(self):
        self._pos = np.zeros(self.num_bins)
        self._neg = np.zeros(self.num_bins)

    def update(self, stats):
        self._pos += np.asarray(stats["pos"])
        self._neg += np.asarray(stats["neg"])

    def result(self):
        # integrate from the highest bin down
        tp = np.cumsum(self._pos[::-1])
        fp = np.cumsum(self._neg[::-1])
        tot_p, tot_n = tp[-1], fp[-1]
        if tot_p == 0 or tot_n == 0:
            return {self.name: 0.5}
        tpr = np.concatenate([[0.0], tp / tot_p])
        fpr = np.concatenate([[0.0], fp / tot_n])
        auc = float(np.trapezoid(tpr, fpr))
        return {self.name: auc}


class RankAuc(Evaluator):
    """Exact ROC AUC from accumulated (score, label) pairs (reference:
    ``RankAucEvaluator``, ``gserver/evaluators/Evaluator.cpp`` — computes AUC
    over the full score column). Holds every score on the host until
    ``result`` — exact but O(N) memory; use the binned :class:`Auc` for
    unbounded streams."""

    def __init__(self, name="rankauc"):
        self.name = name
        self.reset()

    def batch_stats(self, outputs, batch):
        score = outputs[..., 0] if outputs.ndim > 1 else outputs
        return {"score": score, "label": batch["label"],
                "weight": batch.get("weight",
                                    jnp.ones(score.shape[0]))}

    def reset(self):
        self._scores, self._labels, self._weights = [], [], []

    def update(self, stats):
        lab = np.asarray(stats["label"])
        keep = lab >= 0                       # -1 = padding, as everywhere
        self._scores.append(np.asarray(stats["score"], np.float64)[keep])
        self._labels.append(lab[keep])
        self._weights.append(np.asarray(stats["weight"], np.float64)[keep])

    def result(self):
        if not self._scores:
            return {self.name: 0.5}
        s = np.concatenate(self._scores)
        y = np.concatenate(self._labels)
        w = np.concatenate(self._weights)
        order = np.argsort(s, kind="stable")
        s, y, w = s[order], y[order], w[order]
        # average rank per tied-score group (Mann-Whitney with ties)
        ranks = np.empty(len(s))
        i = 0
        cum = 0.0
        while i < len(s):
            j = i
            while j < len(s) and s[j] == s[i]:
                j += 1
            block_w = w[i:j].sum()
            # weighted average 1-based rank of the tied block
            ranks[i:j] = cum + (block_w + 1.0) / 2.0
            cum += block_w
            i = j
        pos = y == 1
        w_pos = w[pos].sum()
        w_neg = w[~pos].sum()
        if w_pos == 0 or w_neg == 0:
            return {self.name: 0.5}
        auc = ((ranks[pos] * w[pos]).sum() - w_pos * (w_pos + 1) / 2.0) / (
            w_pos * w_neg)
        return {self.name: float(auc)}


class PnPair(Evaluator):
    """Positive-negative pair ordering accuracy within query groups
    (reference: ``PnpairEvaluator``, ``gserver/evaluators/Evaluator.cpp`` —
    counts correctly-ordered / mis-ordered / tied (pos, neg) score pairs per
    query). ``batch['query']`` gives group ids (defaults to one global
    group); groups must not span batches."""

    def __init__(self, name="pnpair"):
        self.name = name
        self.reset()

    def batch_stats(self, outputs, batch):
        score = outputs[..., 0] if outputs.ndim > 1 else outputs
        return {"score": score, "label": batch["label"],
                "query": batch.get("query",
                                   jnp.zeros(score.shape[0], jnp.int32))}

    def reset(self):
        self._correct = self._wrong = self._tie = 0.0

    def update(self, stats):
        s = np.asarray(stats["score"], np.float64)
        y = np.asarray(stats["label"])
        q = np.asarray(stats["query"])
        keep = y >= 0
        s, y, q = s[keep], y[keep], q[keep]
        # One lexsort for the whole batch, then per-group rank counting —
        # O(B log B) instead of a full-batch mask per query id. Within each
        # group, Mann-Whitney: correct + tie/2 = sum(pos ranks) - npos(npos+1)/2;
        # ties counted per equal-score block.
        order = np.lexsort((s, q))
        s, y, q = s[order], y[order], q[order]
        starts = np.flatnonzero(np.r_[True, q[1:] != q[:-1]])
        bounds = np.r_[starts, len(q)]
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            sg, yg = s[lo:hi], y[lo:hi]
            npos = int((yg == 1).sum())
            nneg = int((yg == 0).sum())
            if not npos or not nneg:
                continue
            # average 1-based ranks over tied blocks (sg already sorted)
            blk = np.flatnonzero(np.r_[True, sg[1:] != sg[:-1]])
            blk = np.r_[blk, len(sg)]
            ranks = np.repeat((blk[:-1] + blk[1:] + 1) / 2.0, np.diff(blk))
            bp = np.add.reduceat((yg == 1).astype(np.float64), blk[:-1])
            bn = np.add.reduceat((yg == 0).astype(np.float64), blk[:-1])
            tie = float((bp * bn).sum())
            u = ranks[yg == 1].sum() - npos * (npos + 1) / 2.0
            correct = u - 0.5 * tie
            self._correct += correct
            self._tie += tie
            self._wrong += npos * nneg - correct - tie

    def result(self):
        total = self._correct + self._wrong + self._tie
        acc = ((self._correct + 0.5 * self._tie) / total) if total else 0.5
        return {self.name: acc, "pnpair_pairs": total}


class ChunkEvaluator(Evaluator):
    """Chunk (NER span) F1 with IOB labeling (reference:
    ``ChunkEvaluator.cpp:294`` — plain-IOB scheme). Host-side extraction from
    predicted/gold tag sequences; stats stay device-friendly (tag arrays)."""

    def __init__(self, num_tag_types: int, scheme: str = "IOB",
                 name="chunk"):
        assert scheme == "IOB"
        self.name = name
        self.num_tag_types = num_tag_types
        self.reset()

    def batch_stats(self, outputs, batch):
        # outputs: predicted tags [B, T] (already decoded); pass through
        return {"pred": outputs, "gold": batch["label"],
                "length": batch["length"]}

    def _chunk_codes(self, tags, lengths):
        """Vectorized span extraction over the whole [B, T] batch (the
        reference computes chunk stats per batch in C++,
        ChunkEvaluator.cpp:294; a per-token Python loop would dominate the
        host loop on real corpora). Tag encoding (plain IOB): B-k = 2k,
        I-k = 2k+1, O = 2*num_tag_types. A chunk begins on a B- tag OR on an
        I-k tag with no active k-span (after O or a different type — the
        reference's ``isChunkBegin``, ChunkEvaluator.cpp:236). Returns one
        int64 code per chunk encoding (flat_start, flat_end, type); codes are
        unique since starts are."""
        tags = np.asarray(tags)
        B, T = tags.shape
        o_tag = 2 * self.num_tag_types
        valid = np.arange(T)[None, :] < np.asarray(lengths)[:, None]
        tags = np.where(valid, tags, o_tag)
        is_o = tags >= o_tag
        typ = np.where(is_o, -1, tags // 2)
        is_b = (~is_o) & (tags % 2 == 0)
        prev_typ = np.concatenate([np.full((B, 1), -1), typ[:, :-1]], axis=1)
        prev_in = np.concatenate([np.zeros((B, 1), bool), ~is_o[:, :-1]],
                                 axis=1)
        # begin: B- tag, or any in-chunk tag not continuing the previous span
        begins = ~is_o & (is_b | ~(prev_in & (prev_typ == typ)))
        # a chunk ends at t unless t+1 continues it (in-chunk and not a begin)
        cont = ~is_o & ~begins
        next_cont = np.concatenate([cont[:, 1:], np.zeros((B, 1), bool)],
                                   axis=1)
        ends = ~is_o & ~next_cont
        flat_b = np.flatnonzero(begins)
        flat_e = np.flatnonzero(ends)
        # begins/ends interleave s1<=e1<s2<=e2… within each row and rows have
        # equal counts, so row-major flattening keeps the pairing aligned.
        types = typ.ravel()[flat_b]
        n = np.int64(B) * T + 1
        return (flat_b.astype(np.int64) * n + flat_e) * (
            self.num_tag_types + 1) + types

    def reset(self):
        self._correct = self._pred = self._gold = 0

    def update(self, stats):
        pred = np.asarray(stats["pred"])
        gold = np.asarray(stats["gold"])
        lengths = np.asarray(stats["length"])
        pc = self._chunk_codes(pred, lengths)
        gc = self._chunk_codes(gold, lengths)
        self._correct += len(np.intersect1d(pc, gc, assume_unique=True))
        self._pred += len(pc)
        self._gold += len(gc)

    def result(self):
        p = self._correct / max(1, self._pred)
        r = self._correct / max(1, self._gold)
        f1 = 2 * p * r / max(1e-9, p + r)
        return {"chunk_precision": p, "chunk_recall": r, "chunk_f1": f1}


def _collapse_ctc_path(path: np.ndarray, blank: int) -> np.ndarray:
    """Best-path → label string: drop blanks, merge repeats not separated by a
    blank (reference: ``CTCErrorEvaluator.cpp`` ``path2String``)."""
    if len(path) == 0:
        return path
    prev = np.concatenate([[blank], path[:-1]])
    keep = (path != blank) & ((path != prev) | (prev == blank))
    return path[keep]


def _edit_distance_matrix(gold: np.ndarray, gold_len: np.ndarray,
                          hyp: np.ndarray, hyp_len: np.ndarray) -> np.ndarray:
    """Levenshtein DP tables for a whole batch at once, [B, N+1, M+1].

    The left-to-right dependency ``d[i,j] = min(..., d[i,j-1]+1)`` is resolved
    without a scalar loop: with ``tmp[j] = min(d[i-1,j]+1, d[i-1,j-1]+cost)``,
    unrolling gives ``d[i,j] = min_{k<=j}(tmp[k]-k) + j`` — a running minimum,
    so each row is one ``np.minimum.accumulate`` over the batch. Rows (gold
    positions) remain a Python loop of length N.
    """
    B = gold.shape[0]
    N = int(gold_len.max(initial=0))
    M = int(hyp_len.max(initial=0))
    jj = np.arange(M + 1)
    D = np.empty((B, N + 1, M + 1), np.int32)
    D[:, 0, :] = jj[None, :]
    for i in range(1, N + 1):
        cost = (gold[:, i - 1][:, None] != hyp[:, :M]).astype(np.int32)
        prev = D[:, i - 1, :]
        tmp = np.empty((B, M + 1), np.int32)
        tmp[:, 0] = i
        np.minimum(prev[:, 1:] + 1, prev[:, :-1] + cost, out=tmp[:, 1:])
        D[:, i, :] = np.minimum.accumulate(tmp - jj[None, :], axis=1) + jj
    return D


def _backtrace_counts(D: np.ndarray, n: int, m: int,
                      gold: np.ndarray, hyp: np.ndarray):
    """(substitutions, deletions, insertions) following the reference's
    tie-break order: match > substitution > deletion > insertion
    (``CTCErrorEvaluator.cpp`` ``stringAlignment`` backtrace). The match
    branch additionally requires the characters to be equal: a zero-cost
    diagonal tie with ``gold[i-1] != hyp[j-1]`` is NOT a match (it is
    reachable via a different path) and must fall through, or the
    sub/del/ins breakdown shifts relative to the reference."""
    i, j = n, m
    sub = dele = ins = 0
    while i and j:
        if D[i, j] == D[i - 1, j - 1] and gold[i - 1] == hyp[j - 1]:
            i -= 1
            j -= 1
        elif D[i, j] == D[i - 1, j - 1] + 1:
            sub += 1
            i -= 1
            j -= 1
        elif D[i, j] == D[i - 1, j] + 1:
            dele += 1
            i -= 1
        else:
            ins += 1
            j -= 1
    return sub, dele + i, ins + j


class CtcErrorEvaluator(Evaluator):
    """Sequence edit-distance error for CTC models (reference:
    ``CTCErrorEvaluator.cpp:318``, registered as ``ctc_edit_distance``).

    Per sequence: best-path decode the frame activations (argmax, collapse,
    blank = num_classes-1), align against the gold label sequence, and
    normalise distance and per-kind counts by ``max(len(gold), len(hyp))``.
    Reports error / deletion_error / insertion_error / substitution_error /
    sequence_error, all averaged over sequences, as the reference does.

    Expects ``outputs`` of shape [B, T, C] with frame lengths in
    ``batch['length']``, gold labels in ``batch['label']`` ([B, L], padded
    with -1) with lengths in ``batch['label_length']`` (defaults to counting
    non-negative labels).

    ``blank`` defaults to 0, matching this package's CTC stack
    (:func:`paddle_tpu.nn.ctc.ctc_loss`); the reference evaluator uses
    ``num_classes - 1`` — pass ``blank=-1`` to mean "last class".
    """

    def __init__(self, blank: int = 0, name="ctc_edit_distance"):
        self.name = name
        self.blank = blank
        self.reset()

    def batch_stats(self, outputs, batch):
        # argmax on device; everything else is small host work
        blank = self.blank if self.blank >= 0 else outputs.shape[-1] - 1
        return {"path": jnp.argmax(outputs, -1),
                "length": batch["length"],
                "label": batch["label"],
                "label_length": batch.get(
                    "label_length",
                    jnp.sum(batch["label"] >= 0, axis=-1)),
                "blank": jnp.asarray(blank)}

    def reset(self):
        self._score = self._del = self._ins = self._sub = 0.0
        self._seq_err = 0
        self._num_seq = 0

    def update(self, stats):
        paths = np.asarray(stats["path"])
        lengths = np.asarray(stats["length"])
        labels = np.asarray(stats["label"])
        label_lens = np.asarray(stats["label_length"])
        blank = int(stats["blank"])
        B = paths.shape[0]
        hyps = [_collapse_ctc_path(paths[b, :lengths[b]], blank)
                for b in range(B)]
        hyp_len = np.array([len(h) for h in hyps], np.int32)
        M = int(hyp_len.max(initial=0))
        hyp = np.zeros((B, max(M, 1)), labels.dtype)
        for b, h in enumerate(hyps):
            hyp[b, :len(h)] = h
        D = _edit_distance_matrix(labels, label_lens, hyp, hyp_len)
        for b in range(B):
            n, m = int(label_lens[b]), int(hyp_len[b])
            if n == 0:
                sub, dele, ins = 0, 0, m
            elif m == 0:
                sub, dele, ins = 0, n, 0
            else:
                sub, dele, ins = _backtrace_counts(D[b], n, m,
                                                   labels[b], hyp[b])
            dist = sub + dele + ins
            max_len = max(1, n, m)
            self._score += dist / max_len
            self._sub += sub / max_len
            self._del += dele / max_len
            self._ins += ins / max_len
            self._seq_err += int(dist != 0)
            self._num_seq += 1

    def result(self):
        d = max(1, self._num_seq)
        return {"error": self._score / d,
                "deletion_error": self._del / d,
                "insertion_error": self._ins / d,
                "substitution_error": self._sub / d,
                "sequence_error": self._seq_err / d}


class SumEvaluator(Evaluator):
    """Sum of an output column, optionally weighted (reference:
    ``SumEvaluator``, ``Evaluator.cpp:180``)."""

    def __init__(self, name="sum"):
        self.name = name
        self.reset()

    def batch_stats(self, outputs, batch):
        v = outputs[..., 0] if outputs.ndim > 1 else outputs
        w = batch.get("weight", jnp.ones(v.shape[0]))
        return {"sum": jnp.sum(v * w), "count": jnp.sum(w)}

    def reset(self):
        self._sum = self._count = 0.0

    def update(self, stats):
        self._sum += float(stats["sum"])
        self._count += float(stats["count"])

    def result(self):
        # weights may sum below 1, so guard only against zero (unlike the
        # integer-count evaluators above)
        return {self.name: self._sum / (self._count if self._count else 1.0)}


class ColumnSumEvaluator(Evaluator):
    """Per-column mean of an output matrix (reference: ``ColumnSumEvaluator``,
    ``Evaluator.cpp:276``)."""

    def __init__(self, name="column_sum"):
        self.name = name
        self.reset()

    def batch_stats(self, outputs, batch):
        w = batch.get("weight", jnp.ones(outputs.shape[0]))
        return {"sum": jnp.sum(outputs * w[:, None], axis=0),
                "count": jnp.sum(w)}

    def reset(self):
        self._sum = None
        self._count = 0.0

    def update(self, stats):
        s = np.asarray(stats["sum"], np.float64)
        self._sum = s if self._sum is None else self._sum + s
        self._count += float(stats["count"])

    def result(self):
        if self._sum is None:
            return {self.name: []}
        return {self.name:
                (self._sum / (self._count if self._count else 1.0)).tolist()}


class DetectionMAP(Evaluator):
    """Mean average precision for detection (reference:
    ``DetectionMAPEvaluator.cpp:306``, registered as ``detection_map``).

    Consumes fixed-shape detection output — ``outputs`` is the
    :class:`~paddle_tpu.nn.detection.DetectionOutput` tensor
    ``[B, K, 6]`` of ``(label, score, xmin, ymin, xmax, ymax)`` rows padded
    with label = -1 — and padded ground truth ``batch['gt_box'] [B, G, 4]``,
    ``batch['gt_label'] [B, G]`` (-1 padding), optional
    ``batch['gt_difficult'] [B, G]``. Matching, accumulation, and AP follow
    the reference exactly: per image/class greedy match by descending score
    with IoU strictly above ``overlap_threshold``, each gt creditable once;
    AP per class by '11point' (VOC2007) or 'Integral'; mAP averaged over
    classes with positives, scaled by 100.
    """

    def __init__(self, overlap_threshold: float = 0.5,
                 ap_type: str = "11point", evaluate_difficult: bool = False,
                 name="detection_map"):
        assert ap_type in ("11point", "Integral")
        self.name = name
        self.overlap_threshold = overlap_threshold
        self.ap_type = ap_type
        self.evaluate_difficult = evaluate_difficult
        self.reset()

    def batch_stats(self, outputs, batch):
        return {"det": outputs, "gt_box": batch["gt_box"],
                "gt_label": batch["gt_label"],
                "gt_difficult": batch.get(
                    "gt_difficult",
                    jnp.zeros(batch["gt_label"].shape, jnp.int32))}

    def reset(self):
        self._num_pos = {}       # class -> gt count (non-difficult)
        self._pairs = {}         # class -> list of (score, is_tp)

    @staticmethod
    def _iou_matrix(a, b):
        """Vectorized pairwise IoU, [N,4] x [M,4] -> [N,M] (numpy mirror of
        ``paddle_tpu.nn.detection.iou_matrix``)."""
        lt = np.maximum(a[:, None, :2], b[None, :, :2])
        rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = np.maximum(rb - lt, 0.0)
        inter = wh[..., 0] * wh[..., 1]
        area = lambda x: (np.maximum(x[:, 2] - x[:, 0], 0.0) *
                          np.maximum(x[:, 3] - x[:, 1], 0.0))
        union = area(a)[:, None] + area(b)[None, :] - inter
        return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)

    def update(self, stats):
        det = np.asarray(stats["det"])
        gt_box = np.asarray(stats["gt_box"])
        gt_label = np.asarray(stats["gt_label"])
        gt_diff = np.asarray(stats["gt_difficult"]).astype(bool)
        for b in range(det.shape[0]):
            valid_gt = gt_label[b] >= 0
            for c in np.unique(gt_label[b][valid_gt]):
                sel = valid_gt & (gt_label[b] == c)
                n = int(np.sum(sel & ~gt_diff[b])) if not \
                    self.evaluate_difficult else int(np.sum(sel))
                self._num_pos[int(c)] = self._num_pos.get(int(c), 0) + n
            dmask = det[b, :, 0] >= 0
            for c in np.unique(det[b][dmask, 0]).astype(int):
                rows = det[b][dmask & (det[b, :, 0] == c)]
                rows = rows[np.argsort(-rows[:, 1], kind="stable")]
                gsel = np.flatnonzero(valid_gt & (gt_label[b] == c))
                pairs = self._pairs.setdefault(int(c), [])
                visited = np.zeros(len(gsel), bool)
                iou_all = self._iou_matrix(rows[:, 2:6], gt_box[b][gsel]) \
                    if len(gsel) else None
                for r, score in enumerate(rows[:, 1]):
                    if len(gsel) == 0:
                        pairs.append((float(score), 0))
                        continue
                    ious = iou_all[r]
                    j = int(np.argmax(ious))
                    if ious[j] > self.overlap_threshold:
                        if not self.evaluate_difficult and \
                                gt_diff[b][gsel[j]]:
                            continue       # difficult match: ignored entirely
                        if not visited[j]:
                            pairs.append((float(score), 1))
                            visited[j] = True
                        else:
                            pairs.append((float(score), 0))
                    else:
                        pairs.append((float(score), 0))

    def result(self):
        aps = []
        for c, npos in self._num_pos.items():
            if npos == 0 or c not in self._pairs or not self._pairs[c]:
                continue
            pairs = sorted(self._pairs[c], key=lambda p: -p[0])
            tp = np.cumsum([p[1] for p in pairs])
            fp = np.cumsum([1 - p[1] for p in pairs])
            prec = tp / np.maximum(tp + fp, 1)
            rec = tp / npos
            if self.ap_type == "11point":
                ap = 0.0
                for t in np.arange(0.0, 1.01, 0.1):
                    mask = rec >= t
                    ap += (prec[mask].max() if mask.any() else 0.0) / 11.0
            else:
                dr = np.diff(np.concatenate([[0.0], rec]))
                ap = float(np.sum(prec * dr))
            aps.append(ap)
        return {self.name: 100.0 * float(np.mean(aps)) if aps else 0.0}


class _Printer(Evaluator):
    """Debug evaluators that log instead of scoring (reference printer
    family, ``Evaluator.cpp:1020-1357``). ``sink`` defaults to the module
    logger; pass e.g. ``print`` for tests."""

    def __init__(self, name, sink=None):
        self.name = name
        if sink is None:
            import logging
            sink = logging.getLogger("paddle_tpu.evaluators").info
        self._sink = sink

    def reset(self):
        pass

    def update(self, stats):
        self._sink("%s: %s" % (self.name, self._format(stats)))

    def result(self):
        return {}


class ValuePrinter(_Printer):
    """Logs output value summaries (reference: ``ValuePrinter``)."""

    def __init__(self, name="value_printer", sink=None):
        super().__init__(name, sink)

    def batch_stats(self, outputs, batch):
        return {"mean": jnp.mean(outputs), "abs_max": jnp.max(jnp.abs(outputs)),
                "shape": np.asarray(outputs.shape)}

    def _format(self, stats):
        return "shape=%s mean=%.6g abs_max=%.6g" % (
            tuple(np.asarray(stats["shape"]).tolist()),
            float(stats["mean"]), float(stats["abs_max"]))


class MaxIdPrinter(_Printer):
    """Logs argmax ids per row (reference: ``MaxIdPrinter``)."""

    def __init__(self, name="max_id_printer", sink=None, limit=16):
        super().__init__(name, sink)
        self.limit = limit

    def batch_stats(self, outputs, batch):
        return {"ids": jnp.argmax(outputs, -1)}

    def _format(self, stats):
        ids = np.asarray(stats["ids"]).ravel()[:self.limit]
        return "ids=%s" % (ids.tolist(),)


class SequenceTextPrinter(_Printer):
    """Maps id sequences through a vocabulary and logs the text (reference:
    ``SequenceTextPrinter``, ``Evaluator.cpp:1192``).

    Debugging tool, like the reference's whole printer family: ``_format``
    does per-token host-side Python string work on every batch it sees —
    attach it to small evaluation/inspection runs, never inside the hot
    training loop."""

    def __init__(self, vocab=None, name="seq_text_printer", sink=None):
        super().__init__(name, sink)
        self.vocab = vocab or {}

    def batch_stats(self, outputs, batch):
        return {"ids": outputs, "length": batch.get(
            "length", jnp.full(outputs.shape[0], outputs.shape[-1]))}

    def _format(self, stats):
        ids = np.asarray(stats["ids"])
        lengths = np.asarray(stats["length"])
        rows = []
        for b in range(ids.shape[0]):
            toks = [str(self.vocab.get(int(i), int(i)))
                    for i in ids[b, :lengths[b]]]
            rows.append(" ".join(toks))
        return " | ".join(rows)


class EvaluatorSet:
    """Bundle of evaluators sharing one device round-trip per batch."""

    def __init__(self, *evaluators: Evaluator):
        self.evaluators = list(evaluators)

    def batch_stats(self, outputs, batch):
        return {ev.name: ev.batch_stats(outputs, batch)
                for ev in self.evaluators}

    def reset(self):
        for ev in self.evaluators:
            ev.reset()

    def update(self, stats):
        for ev in self.evaluators:
            ev.update(stats[ev.name])

    def result(self):
        out = {}
        for ev in self.evaluators:
            out.update(ev.result())
        return out
