"""Evaluators — streaming metrics over batches.

Reference: ``/root/reference/paddle/gserver/evaluators/Evaluator.cpp`` registry
(classification_error, precision_recall, pnpair, rankauc, chunk, ctc_edit_
distance, detection_map, sum/column_sum + printers). Design: each evaluator has
a jit-safe ``batch(outputs, batch) -> dict[str, array]`` piece producing small
sufficient statistics on device, and host-side ``update``/``result`` that
accumulate across batches — so metrics ride inside the compiled train step and
only scalars cross to host (no HBM round-trips of activations).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np
import jax.numpy as jnp

__all__ = ["Evaluator", "ClassificationError", "PrecisionRecall", "Auc",
           "RankAuc", "PnPair", "ChunkEvaluator", "EvaluatorSet"]


class Evaluator:
    name = "evaluator"

    def batch_stats(self, outputs, batch) -> Dict[str, Any]:
        """Device-side sufficient statistics (runs under jit)."""
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def update(self, stats: Dict[str, np.ndarray]):
        raise NotImplementedError

    def result(self) -> Dict[str, float]:
        raise NotImplementedError


class ClassificationError(Evaluator):
    """top-1 error (reference: ``ClassificationErrorEvaluator``)."""

    def __init__(self, name="classification_error"):
        self.name = name
        self.reset()

    def batch_stats(self, outputs, batch):
        labels = batch["label"]
        pred = jnp.argmax(outputs, axis=-1)
        valid = (labels >= 0)
        wrong = jnp.sum((pred != labels) & valid)
        return {"wrong": wrong, "total": jnp.sum(valid)}

    def reset(self):
        self._wrong = 0
        self._total = 0

    def update(self, stats):
        self._wrong += int(stats["wrong"])
        self._total += int(stats["total"])

    def result(self):
        err = self._wrong / max(1, self._total)
        return {self.name: err, "accuracy": 1.0 - err}


class PrecisionRecall(Evaluator):
    """Binary/micro-averaged precision-recall-F1 (reference:
    ``PrecisionRecallEvaluator``)."""

    def __init__(self, threshold: float = 0.5, name="precision_recall"):
        self.name = name
        self.threshold = threshold
        self.reset()

    def batch_stats(self, outputs, batch):
        labels = batch["label"]
        if outputs.ndim > 1 and outputs.shape[-1] == 2:
            pred = jnp.argmax(outputs, -1)
        else:
            score = outputs[..., 0] if outputs.ndim > 1 else outputs
            pred = (score > self.threshold).astype(jnp.int32)
        labels = labels.astype(jnp.int32)
        tp = jnp.sum((pred == 1) & (labels == 1))
        fp = jnp.sum((pred == 1) & (labels == 0))
        fn = jnp.sum((pred == 0) & (labels == 1))
        return {"tp": tp, "fp": fp, "fn": fn}

    def reset(self):
        self._tp = self._fp = self._fn = 0

    def update(self, stats):
        self._tp += int(stats["tp"])
        self._fp += int(stats["fp"])
        self._fn += int(stats["fn"])

    def result(self):
        p = self._tp / max(1, self._tp + self._fp)
        r = self._tp / max(1, self._tp + self._fn)
        f1 = 2 * p * r / max(1e-9, p + r)
        return {"precision": p, "recall": r, "f1": f1}


class Auc(Evaluator):
    """ROC AUC via fixed-bin histogram (reference: ``AucEvaluator`` — same
    binned approach, Evaluator.cpp)."""

    def __init__(self, num_bins: int = 1024, from_logits: bool = False,
                 name="auc"):
        self.name = name
        self.num_bins = num_bins
        self.from_logits = from_logits
        self.reset()

    def batch_stats(self, outputs, batch):
        import jax
        labels = batch["label"].astype(jnp.int32)
        if outputs.ndim > 1 and outputs.shape[-1] == 2:
            score = jax.nn.softmax(outputs, -1)[..., 1]
        else:
            score = outputs[..., 0] if outputs.ndim > 1 else outputs
            if self.from_logits:      # map logits into [0,1) for binning
                score = jax.nn.sigmoid(score)
        idx = jnp.clip((score * self.num_bins).astype(jnp.int32), 0,
                       self.num_bins - 1)
        pos = jnp.zeros(self.num_bins).at[idx].add(labels == 1)
        neg = jnp.zeros(self.num_bins).at[idx].add(labels == 0)
        return {"pos": pos, "neg": neg}

    def reset(self):
        self._pos = np.zeros(self.num_bins)
        self._neg = np.zeros(self.num_bins)

    def update(self, stats):
        self._pos += np.asarray(stats["pos"])
        self._neg += np.asarray(stats["neg"])

    def result(self):
        # integrate from the highest bin down
        tp = np.cumsum(self._pos[::-1])
        fp = np.cumsum(self._neg[::-1])
        tot_p, tot_n = tp[-1], fp[-1]
        if tot_p == 0 or tot_n == 0:
            return {self.name: 0.5}
        tpr = np.concatenate([[0.0], tp / tot_p])
        fpr = np.concatenate([[0.0], fp / tot_n])
        auc = float(np.trapezoid(tpr, fpr))
        return {self.name: auc}


class RankAuc(Evaluator):
    """Exact ROC AUC from accumulated (score, label) pairs (reference:
    ``RankAucEvaluator``, ``gserver/evaluators/Evaluator.cpp`` — computes AUC
    over the full score column). Holds every score on the host until
    ``result`` — exact but O(N) memory; use the binned :class:`Auc` for
    unbounded streams."""

    def __init__(self, name="rankauc"):
        self.name = name
        self.reset()

    def batch_stats(self, outputs, batch):
        score = outputs[..., 0] if outputs.ndim > 1 else outputs
        return {"score": score, "label": batch["label"],
                "weight": batch.get("weight",
                                    jnp.ones(score.shape[0]))}

    def reset(self):
        self._scores, self._labels, self._weights = [], [], []

    def update(self, stats):
        lab = np.asarray(stats["label"])
        keep = lab >= 0                       # -1 = padding, as everywhere
        self._scores.append(np.asarray(stats["score"], np.float64)[keep])
        self._labels.append(lab[keep])
        self._weights.append(np.asarray(stats["weight"], np.float64)[keep])

    def result(self):
        if not self._scores:
            return {self.name: 0.5}
        s = np.concatenate(self._scores)
        y = np.concatenate(self._labels)
        w = np.concatenate(self._weights)
        order = np.argsort(s, kind="stable")
        s, y, w = s[order], y[order], w[order]
        # average rank per tied-score group (Mann-Whitney with ties)
        ranks = np.empty(len(s))
        i = 0
        cum = 0.0
        while i < len(s):
            j = i
            while j < len(s) and s[j] == s[i]:
                j += 1
            block_w = w[i:j].sum()
            # weighted average 1-based rank of the tied block
            ranks[i:j] = cum + (block_w + 1.0) / 2.0
            cum += block_w
            i = j
        pos = y == 1
        w_pos = w[pos].sum()
        w_neg = w[~pos].sum()
        if w_pos == 0 or w_neg == 0:
            return {self.name: 0.5}
        auc = ((ranks[pos] * w[pos]).sum() - w_pos * (w_pos + 1) / 2.0) / (
            w_pos * w_neg)
        return {self.name: float(auc)}


class PnPair(Evaluator):
    """Positive-negative pair ordering accuracy within query groups
    (reference: ``PnpairEvaluator``, ``gserver/evaluators/Evaluator.cpp`` —
    counts correctly-ordered / mis-ordered / tied (pos, neg) score pairs per
    query). ``batch['query']`` gives group ids (defaults to one global
    group); groups must not span batches."""

    def __init__(self, name="pnpair"):
        self.name = name
        self.reset()

    def batch_stats(self, outputs, batch):
        score = outputs[..., 0] if outputs.ndim > 1 else outputs
        return {"score": score, "label": batch["label"],
                "query": batch.get("query",
                                   jnp.zeros(score.shape[0], jnp.int32))}

    def reset(self):
        self._correct = self._wrong = self._tie = 0.0

    def update(self, stats):
        s = np.asarray(stats["score"], np.float64)
        y = np.asarray(stats["label"])
        q = np.asarray(stats["query"])
        keep = y >= 0
        s, y, q = s[keep], y[keep], q[keep]
        # One lexsort for the whole batch, then per-group rank counting —
        # O(B log B) instead of a full-batch mask per query id. Within each
        # group, Mann-Whitney: correct + tie/2 = sum(pos ranks) - npos(npos+1)/2;
        # ties counted per equal-score block.
        order = np.lexsort((s, q))
        s, y, q = s[order], y[order], q[order]
        starts = np.flatnonzero(np.r_[True, q[1:] != q[:-1]])
        bounds = np.r_[starts, len(q)]
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            sg, yg = s[lo:hi], y[lo:hi]
            npos = int((yg == 1).sum())
            nneg = int((yg == 0).sum())
            if not npos or not nneg:
                continue
            # average 1-based ranks over tied blocks (sg already sorted)
            blk = np.flatnonzero(np.r_[True, sg[1:] != sg[:-1]])
            blk = np.r_[blk, len(sg)]
            ranks = np.repeat((blk[:-1] + blk[1:] + 1) / 2.0, np.diff(blk))
            bp = np.add.reduceat((yg == 1).astype(np.float64), blk[:-1])
            bn = np.add.reduceat((yg == 0).astype(np.float64), blk[:-1])
            tie = float((bp * bn).sum())
            u = ranks[yg == 1].sum() - npos * (npos + 1) / 2.0
            correct = u - 0.5 * tie
            self._correct += correct
            self._tie += tie
            self._wrong += npos * nneg - correct - tie

    def result(self):
        total = self._correct + self._wrong + self._tie
        acc = ((self._correct + 0.5 * self._tie) / total) if total else 0.5
        return {self.name: acc, "pnpair_pairs": total}


class ChunkEvaluator(Evaluator):
    """Chunk (NER span) F1 with IOB labeling (reference:
    ``ChunkEvaluator.cpp:294`` — plain-IOB scheme). Host-side extraction from
    predicted/gold tag sequences; stats stay device-friendly (tag arrays)."""

    def __init__(self, num_tag_types: int, scheme: str = "IOB",
                 name="chunk"):
        assert scheme == "IOB"
        self.name = name
        self.num_tag_types = num_tag_types
        self.reset()

    def batch_stats(self, outputs, batch):
        # outputs: predicted tags [B, T] (already decoded); pass through
        return {"pred": outputs, "gold": batch["label"],
                "length": batch["length"]}

    def _chunk_codes(self, tags, lengths):
        """Vectorized span extraction over the whole [B, T] batch (the
        reference computes chunk stats per batch in C++,
        ChunkEvaluator.cpp:294; a per-token Python loop would dominate the
        host loop on real corpora). Tag encoding (plain IOB): B-k = 2k,
        I-k = 2k+1, O = 2*num_tag_types. A chunk begins on a B- tag OR on an
        I-k tag with no active k-span (after O or a different type — the
        reference's ``isChunkBegin``, ChunkEvaluator.cpp:236). Returns one
        int64 code per chunk encoding (flat_start, flat_end, type); codes are
        unique since starts are."""
        tags = np.asarray(tags)
        B, T = tags.shape
        o_tag = 2 * self.num_tag_types
        valid = np.arange(T)[None, :] < np.asarray(lengths)[:, None]
        tags = np.where(valid, tags, o_tag)
        is_o = tags >= o_tag
        typ = np.where(is_o, -1, tags // 2)
        is_b = (~is_o) & (tags % 2 == 0)
        prev_typ = np.concatenate([np.full((B, 1), -1), typ[:, :-1]], axis=1)
        prev_in = np.concatenate([np.zeros((B, 1), bool), ~is_o[:, :-1]],
                                 axis=1)
        # begin: B- tag, or any in-chunk tag not continuing the previous span
        begins = ~is_o & (is_b | ~(prev_in & (prev_typ == typ)))
        # a chunk ends at t unless t+1 continues it (in-chunk and not a begin)
        cont = ~is_o & ~begins
        next_cont = np.concatenate([cont[:, 1:], np.zeros((B, 1), bool)],
                                   axis=1)
        ends = ~is_o & ~next_cont
        flat_b = np.flatnonzero(begins)
        flat_e = np.flatnonzero(ends)
        # begins/ends interleave s1<=e1<s2<=e2… within each row and rows have
        # equal counts, so row-major flattening keeps the pairing aligned.
        types = typ.ravel()[flat_b]
        n = np.int64(B) * T + 1
        return (flat_b.astype(np.int64) * n + flat_e) * (
            self.num_tag_types + 1) + types

    def reset(self):
        self._correct = self._pred = self._gold = 0

    def update(self, stats):
        pred = np.asarray(stats["pred"])
        gold = np.asarray(stats["gold"])
        lengths = np.asarray(stats["length"])
        pc = self._chunk_codes(pred, lengths)
        gc = self._chunk_codes(gold, lengths)
        self._correct += len(np.intersect1d(pc, gc, assume_unique=True))
        self._pred += len(pc)
        self._gold += len(gc)

    def result(self):
        p = self._correct / max(1, self._pred)
        r = self._correct / max(1, self._gold)
        f1 = 2 * p * r / max(1e-9, p + r)
        return {"chunk_precision": p, "chunk_recall": r, "chunk_f1": f1}


class EvaluatorSet:
    """Bundle of evaluators sharing one device round-trip per batch."""

    def __init__(self, *evaluators: Evaluator):
        self.evaluators = list(evaluators)

    def batch_stats(self, outputs, batch):
        return {ev.name: ev.batch_stats(outputs, batch)
                for ev in self.evaluators}

    def reset(self):
        for ev in self.evaluators:
            ev.reset()

    def update(self, stats):
        for ev in self.evaluators:
            ev.update(stats[ev.name])

    def result(self):
        out = {}
        for ev in self.evaluators:
            out.update(ev.result())
        return out
