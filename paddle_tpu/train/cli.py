"""Config-driven training CLI — the ``paddle_trainer --config=...`` analog
(reference: ``trainer/TrainerMain.cpp:17`` + ``utils/Flags.cpp``: the v1
workflow where a run is fully described by config files, no user code).

    python -m paddle_tpu.train.cli --model_config model.json \
        --dataset mnist --optimizer adam --num_passes 3 --batch_size 64

The model config is the serialized model IR (``core/config.py`` — produce it
with ``paddle_tpu.inference.dump_config`` or an exported model directory);
datasets resolve from ``paddle_tpu.data.datasets`` by name; everything else
is :class:`~paddle_tpu.utils.flags.TrainerFlags`.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

import jax

from paddle_tpu import data
from paddle_tpu.core.config import build_module, config_from_json
from paddle_tpu.data import datasets as dataset_lib
from paddle_tpu.nn import costs
from paddle_tpu.train import Trainer
from paddle_tpu.train.evaluators import ClassificationError
from paddle_tpu.utils.flags import TrainerFlags, parse_flags

__all__ = ["TrainCliFlags", "run", "main"]


@dataclasses.dataclass
class TrainCliFlags(TrainerFlags):
    model_config: str = ""           # IR json file, or an export()ed dir
    config: str = ""                 # v1-style DSL config SCRIPT (.py)
    dataset: str = "mnist"           # name in paddle_tpu.data.datasets
    optimizer: str = "adam"          # name in paddle_tpu.optim
    loss: str = "softmax_ce"         # softmax_ce | mse
    trusted_config: bool = False     # allow non-registry classes in the IR


def _load_model(path: str, trusted: bool):
    if os.path.isdir(path):          # an export()/merge_model() directory
        path = os.path.join(path, "model.json")
    with open(path) as f:
        return build_module(config_from_json(f.read()), trusted=trusted)


def _make_reader(name: str, batch_size: int, split: str = "train"):
    maker = getattr(dataset_lib, name)
    raw = maker(split)
    sample = next(iter(raw()))
    if isinstance(sample, tuple) and len(sample) == 2:
        r = data.map_readers(lambda s: {"x": s[0], "label": s[1]}, raw)
    else:
        raise SystemExit(
            f"dataset {name!r} yields {type(sample)}; the CLI drives "
            f"(input, label) datasets — write a custom loop for others")
    return data.batched(r, batch_size)


def _make_optimizer(name: str, lr: float):
    from paddle_tpu import optim
    maker = getattr(optim, name, None)
    if maker is None:
        raise SystemExit(f"unknown optimizer {name!r}")
    return maker(lr)


def _make_loss(name: str):
    if name == "softmax_ce":
        return lambda out, b: costs.softmax_cross_entropy(out, b["label"])
    if name == "mse":
        return lambda out, b: costs.mse(out, b["label"])
    raise SystemExit(f"unknown loss {name!r}")


def _make_evaluator(name):
    from paddle_tpu.train import evaluators as ev
    table = {"classification_error": ev.ClassificationError,
             "auc": ev.Auc, "chunk": ev.ChunkEvaluator}
    if name in (None, "", "none"):
        return None
    if name not in table:
        raise SystemExit(f"unknown evaluator {name!r}")
    return table[name]()


def run_config_script(flags: TrainCliFlags) -> dict:
    """Execute a v1-style DSL config SCRIPT and train it — the
    ``paddle_trainer --config=trainer_config.py`` workflow (reference:
    ``TrainerMain.cpp:17`` embedding CPython to run ``parse_config``).

    The script (see ``configs/``) uses ``paddle_tpu.config_helpers``:
    ``settings(...)`` for run knobs, the layer DSL for the model, and
    ``outputs(cost_node)``; it defines ``train_reader`` (and optionally
    ``test_reader``) callables yielding dict batches keyed by data-layer
    names — the ``@provider`` analog living next to the config, exactly as
    the reference paired config scripts with dataprovider scripts.
    """
    import contextlib

    from paddle_tpu import config_helpers as H
    from paddle_tpu.core import dtypes

    ns = {"__name__": "__paddle_tpu_config__",
          "__file__": os.path.abspath(flags.config)}
    with open(flags.config) as f:
        code = compile(f.read(), flags.config, "exec")
    H.get_run_config(reset=True)       # drop any stale state
    exec(code, ns)                     # the config IS a program (v1 semantics)
    cfg = H.get_run_config(reset=True)
    if cfg.network is None:
        raise SystemExit(f"{flags.config} never called outputs(...)")
    if "train_reader" not in ns:
        raise SystemExit(f"{flags.config} must define train_reader()")
    net = cfg.network
    s = cfg.settings
    input_names = [n for n in net.data_names if n is not None]

    # Precedence: an explicitly-passed flag (CLI/env/json) beats the
    # script's settings(); otherwise the script wins over the flag default
    # (the reference's gflags-beat-config ordering, utils/Flags.cpp).
    explicit = getattr(flags, "_explicit", frozenset())

    def pick(key, flag_val):
        if key in explicit:
            return flag_val
        return s.get(key, flag_val)

    def net_forward(model, variables, batch, train, rngs):
        args = [batch[n] for n in input_names]
        if train:
            out, new = model.apply(variables, *args, train=True,
                                   mutable=("state",), rngs=rngs)
            return out, new.get("state", {})
        return model.apply(variables, *args), variables.get("state", {})

    batch_size = int(pick("batch_size", flags.batch_size))
    reader = ns["train_reader"](batch_size)
    trainer = Trainer(
        model=net,
        loss_fn=lambda out, b: out,    # cost layers return per-example costs
        optimizer=_make_optimizer(
            pick("optimizer", flags.optimizer),
            float(pick("learning_rate", flags.learning_rate))),
        forward=net_forward,
        evaluator=_make_evaluator(s.get("evaluator")),
        nan_check=flags.nan_check,
        param_stats_period=flags.param_stats_period or None)
    last = {}

    def handler(e):
        from paddle_tpu.train import events as ev
        if isinstance(e, ev.EndPass):
            last.update(e.metrics)

    policy = (dtypes.use_policy(dtypes.bfloat16_compute)
              if flags.use_bf16 else contextlib.nullcontext())
    num_passes = int(pick("num_passes", flags.num_passes))
    with policy:
        trainer.init(jax.random.PRNGKey(flags.seed), next(iter(reader())))
        trainer.train(
            reader, num_passes=num_passes, event_handler=handler,
            checkpoint_dir=flags.checkpoint_dir or None,
            checkpoint_keep=flags.checkpoint_keep,
            saving_period=flags.saving_period or None,
            log_period=flags.log_period, resume=flags.resume)
    return last


def run(flags: TrainCliFlags) -> dict:
    """Build everything from config and train; returns final pass metrics."""
    import contextlib

    from paddle_tpu.core import dtypes

    if flags.config:
        return run_config_script(flags)
    if not flags.model_config:
        raise SystemExit("--model_config or --config is required")
    model = _load_model(flags.model_config, flags.trusted_config)
    reader = _make_reader(flags.dataset, flags.batch_size)
    trainer = Trainer(
        model=model,
        loss_fn=_make_loss(flags.loss),
        optimizer=_make_optimizer(flags.optimizer, flags.learning_rate),
        evaluator=ClassificationError() if flags.loss == "softmax_ce"
        else None,
        nan_check=flags.nan_check,
        param_stats_period=flags.param_stats_period or None)
    last = {}

    def handler(e):
        from paddle_tpu.train import events as ev
        if isinstance(e, ev.EndPass):
            last.update(e.metrics)

    policy = (dtypes.use_policy(dtypes.bfloat16_compute)
              if flags.use_bf16 else contextlib.nullcontext())
    with policy:
        trainer.init(jax.random.PRNGKey(flags.seed), next(iter(reader())))
        trainer.train(
            reader, num_passes=flags.num_passes, event_handler=handler,
            checkpoint_dir=flags.checkpoint_dir or None,
            checkpoint_keep=flags.checkpoint_keep,
            saving_period=flags.saving_period or None,
            log_period=flags.log_period, resume=flags.resume)
    return last


def main(argv: Optional[list] = None) -> None:
    flags = parse_flags(TrainCliFlags, argv)
    metrics = run(flags)
    print(json.dumps({k: (round(v, 6) if isinstance(v, float) else v)
                      for k, v in metrics.items()}))


if __name__ == "__main__":
    main()
