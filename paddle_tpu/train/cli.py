"""Config-driven training CLI — the ``paddle_trainer --config=...`` analog
(reference: ``trainer/TrainerMain.cpp:17`` + ``utils/Flags.cpp``: the v1
workflow where a run is fully described by config files, no user code).

    python -m paddle_tpu.train.cli --model_config model.json \
        --dataset mnist --optimizer adam --num_passes 3 --batch_size 64

The model config is the serialized model IR (``core/config.py`` — produce it
with ``paddle_tpu.inference.dump_config`` or an exported model directory);
datasets resolve from ``paddle_tpu.data.datasets`` by name; everything else
is :class:`~paddle_tpu.utils.flags.TrainerFlags`.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

import jax

from paddle_tpu import data
from paddle_tpu.core.config import build_module, config_from_json
from paddle_tpu.data import datasets as dataset_lib
from paddle_tpu.nn import costs
from paddle_tpu.train import Trainer
from paddle_tpu.train.evaluators import ClassificationError
from paddle_tpu.utils.flags import TrainerFlags, parse_flags

__all__ = ["TrainCliFlags", "run", "main"]


@dataclasses.dataclass
class TrainCliFlags(TrainerFlags):
    model_config: str = ""           # IR json file, or an export()ed dir
    config: str = ""                 # v1-style DSL config SCRIPT (.py)
    dataset: str = "mnist"           # name in paddle_tpu.data.datasets
    optimizer: str = "adam"          # name in paddle_tpu.optim
    loss: str = "softmax_ce"         # softmax_ce | mse
    trusted_config: bool = False     # allow non-registry classes in the IR
    # job: train | test | checkgrad | time — the reference trainer's --job
    # modes (TrainerMain.cpp:25: train / test / checkgrad; TrainerBenchmark
    # --job=time)
    job: str = "train"
    time_batches: int = 10           # batches timed by --job time


def _load_model(path: str, trusted: bool):
    if os.path.isdir(path):          # an export()/merge_model() directory
        path = os.path.join(path, "model.json")
    with open(path) as f:
        return build_module(config_from_json(f.read()), trusted=trusted)


def _make_reader(name: str, batch_size: int, split: str = "train"):
    maker = getattr(dataset_lib, name)
    raw = maker(split)
    sample = next(iter(raw()))
    if isinstance(sample, tuple) and len(sample) == 2:
        r = data.map_readers(lambda s: {"x": s[0], "label": s[1]}, raw)
    else:
        raise SystemExit(
            f"dataset {name!r} yields {type(sample)}; the CLI drives "
            f"(input, label) datasets — write a custom loop for others")
    return data.batched(r, batch_size)


def _make_optimizer(name: str, lr: float):
    from paddle_tpu import optim
    maker = getattr(optim, name, None)
    if maker is None:
        raise SystemExit(f"unknown optimizer {name!r}")
    return maker(lr)


def _make_loss(name: str):
    if name == "softmax_ce":
        return lambda out, b: costs.softmax_cross_entropy(out, b["label"])
    if name == "mse":
        return lambda out, b: costs.mse(out, b["label"])
    raise SystemExit(f"unknown loss {name!r}")


def _make_evaluator(name):
    from paddle_tpu.train import evaluators as ev
    table = {"classification_error": ev.ClassificationError,
             "auc": ev.Auc, "chunk": ev.ChunkEvaluator}
    if name in (None, "", "none"):
        return None
    if name not in table:
        raise SystemExit(f"unknown evaluator {name!r}")
    return table[name]()


def run_config_script(flags: TrainCliFlags) -> dict:
    """Execute a v1-style DSL config SCRIPT and train it — the
    ``paddle_trainer --config=trainer_config.py`` workflow (reference:
    ``TrainerMain.cpp:17`` embedding CPython to run ``parse_config``).

    The script (see ``configs/``) uses ``paddle_tpu.config_helpers``:
    ``settings(...)`` for run knobs, the layer DSL for the model, and
    ``outputs(cost_node)``; it defines ``train_reader`` (and optionally
    ``test_reader``) callables yielding dict batches keyed by data-layer
    names — the ``@provider`` analog living next to the config, exactly as
    the reference paired config scripts with dataprovider scripts.
    """
    import contextlib

    from paddle_tpu import config_helpers as H
    from paddle_tpu.core import dtypes

    ns = {"__name__": "__paddle_tpu_config__",
          "__file__": os.path.abspath(flags.config)}
    with open(flags.config) as f:
        code = compile(f.read(), flags.config, "exec")
    H.get_run_config(reset=True)       # drop any stale state
    exec(code, ns)                     # the config IS a program (v1 semantics)
    cfg = H.get_run_config(reset=True)
    if cfg.network is None:
        raise SystemExit(f"{flags.config} never called outputs(...)")
    if "train_reader" not in ns:
        raise SystemExit(f"{flags.config} must define train_reader()")
    net = cfg.network
    s = cfg.settings
    input_names = [n for n in net.data_names if n is not None]

    # Precedence: an explicitly-passed flag (CLI/env/json) beats the
    # script's settings(); otherwise the script wins over the flag default
    # (the reference's gflags-beat-config ordering, utils/Flags.cpp).
    explicit = getattr(flags, "_explicit", frozenset())

    def pick(key, flag_val):
        if key in explicit:
            return flag_val
        return s.get(key, flag_val)

    def net_forward(model, variables, batch, train, rngs):
        args = [batch[n] for n in input_names]
        if train:
            out, new = model.apply(variables, *args, train=True,
                                   mutable=("state",), rngs=rngs)
            return out, new.get("state", {})
        return model.apply(variables, *args), variables.get("state", {})

    batch_size = int(pick("batch_size", flags.batch_size))
    reader = ns["train_reader"](batch_size)

    # Output contract (v1 `Outputs(...)`): outputs[0] is the per-example
    # cost; an optional second output (e.g. logits) feeds the evaluator —
    # the role of the reference's evaluator layers attached to specific
    # layer outputs.
    def script_loss(out, b):
        return out[0] if isinstance(out, tuple) else out

    evaluator = _make_evaluator(s.get("evaluator"))
    if evaluator is not None:
        inner = evaluator

        class _SecondOutput:
            def reset(self):
                inner.reset()

            def batch_stats(self, out, batch):
                o = out[1] if isinstance(out, tuple) else out
                return inner.batch_stats(o, batch)

            def update(self, stats):
                inner.update(stats)

            def result(self):
                return inner.result()

        evaluator = _SecondOutput()

    trainer = Trainer(
        model=net,
        loss_fn=script_loss,           # cost layers return per-example costs
        optimizer=_make_optimizer(
            pick("optimizer", flags.optimizer),
            float(pick("learning_rate", flags.learning_rate))),
        forward=net_forward,
        evaluator=evaluator,
        nan_check=flags.nan_check,
        param_stats_period=flags.param_stats_period or None)
    last = {}

    def handler(e):
        from paddle_tpu.train import events as ev
        if isinstance(e, ev.EndPass):
            last.update(e.metrics)

    policy = (dtypes.use_policy(dtypes.bfloat16_compute)
              if flags.use_bf16 else contextlib.nullcontext())
    num_passes = int(pick("num_passes", flags.num_passes))
    test_reader = (ns["test_reader"](batch_size)
                   if "test_reader" in ns else None)
    with policy:
        trainer.init(jax.random.PRNGKey(flags.seed), next(iter(reader())))
        if flags.job != "train":
            return _run_alt_job(flags, trainer, reader, test_reader)
        trainer.train(
            reader, num_passes=num_passes, event_handler=handler,
            checkpoint_dir=flags.checkpoint_dir or None,
            checkpoint_keep=flags.checkpoint_keep,
            saving_period=flags.saving_period or None,
            log_period=flags.log_period, resume=flags.resume)
    return last


def _run_alt_job(flags: TrainCliFlags, trainer: Trainer, reader,
                 test_reader=None) -> dict:
    """The reference trainer's non-train --job modes
    (``TrainerMain.cpp:25``: test / checkgrad; ``TrainerBenchmark.cpp``:
    --job=time). Shared by the IR and config-script paths (trainer already
    initialized)."""
    import time as _time

    import jax.numpy as jnp
    import numpy as np

    if flags.job == "test":
        # load latest checkpoint if available, evaluate the test stream
        if flags.checkpoint_dir:
            from . import checkpoint as ckpt_mod
            if ckpt_mod.latest_pass(flags.checkpoint_dir) is not None:
                trainer.restore(flags.checkpoint_dir)
        cost, metrics = trainer.evaluate(test_reader or reader)
        return {"test_cost": cost, **{f"test_{k}": v
                                      for k, v in metrics.items()}}

    if flags.job == "checkgrad":
        # whole-model numeric gradient check on one batch
        # (Trainer::checkGradient, --job=checkgrad)
        from paddle_tpu.utils.gradcheck import check_gradients
        batch = jax.tree_util.tree_map(jnp.asarray, next(iter(reader())))
        state = trainer.train_state.state
        fwd = trainer._forward
        loss_fn = trainer.loss_fn
        model = trainer.model

        def loss_of(p):
            out, _ = fwd(model, {"params": p, "state": state}, batch, True,
                         {"dropout": jax.random.PRNGKey(0)})
            return jnp.mean(loss_fn(out, batch))

        # smoke-level whole-model check (rigorous per-layer checks live in
        # tests/): f32 central differences over a full model need headroom
        worst = check_gradients(loss_of, trainer.train_state.params,
                                num_directions=3, rtol=6e-2)
        return {"checkgrad_worst_rel_err": float(worst), "checkgrad_ok": 1}

    if flags.job == "time":
        # --job=time: ms/batch over time_batches (TrainerBenchmark.cpp)
        trainer._build_train_step()
        ts = trainer.train_state
        batches = []
        for i, b in enumerate(reader()):
            if i >= flags.time_batches:
                break
            batches.append(trainer._shard(b))
        if not batches:
            raise SystemExit("--job time: reader yielded no batches")
        params, state, opt_state, step = (ts.params, ts.state, ts.opt_state,
                                          ts.step)
        key = jax.random.PRNGKey(1)
        # warmup (compile)
        params, state, opt_state, step, loss, _ = trainer._train_step(
            params, state, opt_state, step, batches[0], key)
        float(np.asarray(jax.device_get(loss)))
        t0 = _time.perf_counter()
        for b in batches:
            params, state, opt_state, step, loss, _ = trainer._train_step(
                params, state, opt_state, step, b, key)
        float(np.asarray(jax.device_get(loss)))
        ms = (_time.perf_counter() - t0) / len(batches) * 1e3
        return {"ms_per_batch": ms, "batches": len(batches)}

    raise SystemExit(f"unknown --job {flags.job!r} "
                     "(train | test | checkgrad | time)")


def run(flags: TrainCliFlags) -> dict:
    """Build everything from config and train; returns final pass metrics."""
    import contextlib

    from paddle_tpu.core import dtypes

    if flags.config:
        return run_config_script(flags)
    if not flags.model_config:
        raise SystemExit("--model_config or --config is required")
    model = _load_model(flags.model_config, flags.trusted_config)
    reader = _make_reader(flags.dataset, flags.batch_size)
    trainer = Trainer(
        model=model,
        loss_fn=_make_loss(flags.loss),
        optimizer=_make_optimizer(flags.optimizer, flags.learning_rate),
        evaluator=ClassificationError() if flags.loss == "softmax_ce"
        else None,
        nan_check=flags.nan_check,
        param_stats_period=flags.param_stats_period or None)
    last = {}

    def handler(e):
        from paddle_tpu.train import events as ev
        if isinstance(e, ev.EndPass):
            last.update(e.metrics)

    policy = (dtypes.use_policy(dtypes.bfloat16_compute)
              if flags.use_bf16 else contextlib.nullcontext())
    with policy:
        trainer.init(jax.random.PRNGKey(flags.seed), next(iter(reader())))
        if flags.job != "train":
            test_reader = _make_reader(flags.dataset, flags.batch_size,
                                       split="test")
            return _run_alt_job(flags, trainer, reader, test_reader)
        trainer.train(
            reader, num_passes=flags.num_passes, event_handler=handler,
            checkpoint_dir=flags.checkpoint_dir or None,
            checkpoint_keep=flags.checkpoint_keep,
            saving_period=flags.saving_period or None,
            log_period=flags.log_period, resume=flags.resume)
    return last


def main(argv: Optional[list] = None) -> None:
    flags = parse_flags(TrainCliFlags, argv)
    metrics = run(flags)
    print(json.dumps({k: (round(v, 6) if isinstance(v, float) else v)
                      for k, v in metrics.items()}))


if __name__ == "__main__":
    main()
