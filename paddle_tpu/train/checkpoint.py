"""Checkpoint / resume.

Reference UX: per-pass param dirs ``pass-00000/`` with save/load + optimizer
state apply/restore (``/root/reference/paddle/trainer/ParamUtil.cpp:50-71``,
``Parameter::save/load`` ``parameter/Parameter.h:310``); Go pserver adds CRC'd
periodic checkpoints + atomic temp-file renames (``go/pserver/service.go:119``).

TPU-native: the checkpoint is the whole training pytree (params, module state,
optimizer state, step, and data-iterator position), saved as one ``.npz`` per
collection with flattened ``a/b/c`` keys + a JSON manifest carrying a CRC per
file and the pytree structure. Writes are atomic (temp dir + rename), restore is
sharding-aware (arrays are device_put against the current mesh after load).
Multi-host: only process 0 writes (single-controller pattern); all hosts read.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

import numpy as np
import jax

__all__ = ["save_checkpoint", "load_checkpoint", "latest_pass", "pass_dir",
           "atomic_dir", "write_manifest", "verify_manifest",
           "AsyncCheckpointer"]

_MANIFEST = "manifest.json"


@contextlib.contextmanager
def atomic_dir(path: str):
    """Write into ``path + '.tmp'``; swap into place when the block
    succeeds. CRASH-ATOMIC: the live ``path`` is renamed aside to
    ``path + '.old'`` before the new dir renames in, so every window of a
    crash leaves at least one COMPLETE dir — ``path`` itself, or (between
    the two renames) both the finished ``.tmp`` and the previous ``.old``,
    which readers resolve via ``_resolve_pass_dir``. The prior
    ``rmtree(path); rename(tmp)`` recipe had a window with neither (the
    Go pserver writes aside then renames over, go/pserver/service.go:346)."""
    tmp = path + ".tmp"
    if os.path.exists(tmp):
        # A manifest-complete .tmp with NO live dir is the only surviving
        # copy of this pass (crash between the two renames; readers are
        # resolving it right now) — demote it to .old instead of deleting,
        # keeping the at-least-one-complete-copy invariant through the
        # rewrite. Anything else is half-written garbage.
        if (not os.path.exists(path)
                and os.path.exists(os.path.join(tmp, _MANIFEST))):
            old = path + ".old"
            if os.path.exists(old):
                shutil.rmtree(old)
            os.rename(tmp, old)
        else:
            shutil.rmtree(tmp)
    os.makedirs(tmp, exist_ok=True)
    yield tmp
    old = path + ".old"
    if os.path.exists(old):
        shutil.rmtree(old)
    if os.path.exists(path):
        os.rename(path, old)
    os.rename(tmp, path)
    if os.path.exists(old):
        shutil.rmtree(old)


def _is_pass_dir(name: str) -> bool:
    return (name.startswith("pass-")
            and not name.endswith((".tmp", ".old")))


def _base_pass_id(name: str) -> Optional[int]:
    """Pass id of a live dir OR a ``.tmp``/``.old`` crash leftover."""
    base = name[:-4] if name.endswith((".tmp", ".old")) else name
    if not base.startswith("pass-"):
        return None
    try:
        return int(base.split("-")[1])
    except (IndexError, ValueError):
        return None


def _resolve_pass_dir(root: str, pass_id: int) -> str:
    """Directory to READ pass ``pass_id`` from: the live dir, else a
    manifest-complete ``.tmp`` (newer) or ``.old`` left by a crash between
    ``atomic_dir``'s two renames. Resolution is a PURE READ — no renames —
    so concurrent readers on every host always agree and can never race an
    in-flight writer; the writer path alone mutates the root (it rebuilds
    or garbage-collects leftovers on its next save)."""
    base = pass_dir(root, pass_id)
    for d in (base, base + ".tmp", base + ".old"):
        if os.path.exists(os.path.join(d, _MANIFEST)):
            return d
    return base        # fails downstream with the usual missing-file error


def _file_crc(path: str) -> int:
    with open(path, "rb") as f:
        return zlib.crc32(f.read())


def write_manifest(d: str, meta: Optional[Dict[str, Any]] = None) -> None:
    """CRC every file in ``d`` into ``manifest.json`` (plus ``meta``)."""
    files = {f: {"crc32": _file_crc(os.path.join(d, f))}
             for f in sorted(os.listdir(d)) if f != _MANIFEST}
    with open(os.path.join(d, _MANIFEST), "w") as f:
        json.dump({**(meta or {}), "files": files}, f, indent=2)


def verify_manifest(d: str, verify_crc: bool = True) -> Dict[str, Any]:
    """Load ``manifest.json``; raise on CRC mismatch (the Go pserver's
    integrity check, ``go/pserver/service.go:346``)."""
    with open(os.path.join(d, _MANIFEST)) as f:
        manifest = json.load(f)
    # Early manifests keyed entries by collection name ('params') rather than
    # filename ('params.npz'); normalise so both generations load.
    normalized: Dict[str, Any] = {}
    for f, info in manifest["files"].items():
        if os.path.exists(os.path.join(d, f)):
            resolved = f
        elif os.path.exists(os.path.join(d, f + ".npz")):
            resolved = f + ".npz"
        else:
            raise IOError(
                f"checkpoint {d} is missing file for manifest entry {f!r} "
                f"(neither {f!r} nor {f + '.npz'!r} exists)")
        if resolved in normalized:
            raise IOError(
                f"checkpoint {d}: manifest entries collide on {resolved!r} "
                f"after legacy-name normalisation")
        normalized[resolved] = info
    manifest["files"] = normalized
    if verify_crc:
        for fname, info in manifest["files"].items():
            if _file_crc(os.path.join(d, fname)) != info["crc32"]:
                raise IOError(f"crc mismatch in {os.path.join(d, fname)}")
    return manifest


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        tag = "T" if isinstance(tree, tuple) else "L"
        out[f"{prefix}__len_{tag}__"] = np.asarray(len(tree))
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    elif tree is None:
        out[f"{prefix}__none__"] = np.asarray(0)
    else:
        # Root-level leaves (e.g. the bare `step` scalar) need a non-empty key.
        out[prefix.rstrip("/") or "__value__"] = np.asarray(tree)
    return out


def _unflatten(flat: Dict[str, np.ndarray]):
    # Rebuild nested dict/list/tuple structure from path keys.
    if list(flat.keys()) == ["__none__"]:
        return None
    if list(flat.keys()) == ["__value__"]:
        return flat["__value__"]
    if not flat:
        return {}
    # sequence marker?
    for tag, ctor in (("T", tuple), ("L", list)):
        key = f"__len_{tag}__"
        if key in flat:
            n = int(flat[key])
            items = []
            for i in range(n):
                sub = {k[len(f"{i}/"):]: v for k, v in flat.items()
                       if k.startswith(f"{i}/")}
                if not sub and str(i) in flat:
                    items.append(flat[str(i)])  # bare array element
                else:
                    items.append(_unflatten(sub))
            return ctor(items)
    out: Dict[str, Any] = {}
    leaves = {}
    children: Dict[str, Dict[str, np.ndarray]] = {}
    for k, v in flat.items():
        if "/" in k:
            head, rest = k.split("/", 1)
            children.setdefault(head, {})[rest] = v
        else:
            leaves[k] = v
    for k, v in leaves.items():
        out[k] = v
    for k, sub in children.items():
        out[k] = _unflatten(sub)
    return out


def pass_dir(root: str, pass_id: int) -> str:
    return os.path.join(root, f"pass-{pass_id:05d}")


def _snapshot_host(tree: Dict[str, Any]) -> Dict[str, Any]:
    """Fetch every collection to host numpy (ONE device_get per leaf —
    the only part of a save that must see a consistent device state)."""
    return {coll: jax.tree_util.tree_map(lambda x: np.asarray(x), sub)
            for coll, sub in tree.items()}


def _write_pass_dir(root: str, pass_id: int, tree: Dict[str, Any],
                    keep_last: Optional[int] = None) -> str:
    """The disk half of a save (CRC + npz write + swap + gc). Snapshots
    each collection to host right before writing it, so the sync path holds
    at most ONE collection in host memory at a time; the async path passes
    pre-snapshotted numpy (``np.asarray`` is then a no-op)."""
    final = pass_dir(root, pass_id)
    with atomic_dir(final) as tmp:
        for coll, sub in tree.items():
            host = jax.tree_util.tree_map(lambda x: np.asarray(x), sub)
            np.savez(os.path.join(tmp, f"{coll}.npz"), **_flatten(host))
        write_manifest(tmp, {"pass_id": pass_id})
    if keep_last:
        _gc(root, keep_last)
    return final


def save_checkpoint(root: str, pass_id: int, tree: Dict[str, Any],
                    keep_last: Optional[int] = None) -> str:
    """Atomically write ``tree`` (a dict of collections) to pass-NNNNN/."""
    if jax.process_index() != 0:
        return pass_dir(root, pass_id)
    return _write_pass_dir(root, pass_id, tree, keep_last)


class AsyncCheckpointer:
    """Off-critical-path checkpointing (SURVEY §5 "Orbax-style async").

    The reference keeps checkpoint work off the training hot path — the Go
    pserver checkpoints on its own ticker goroutine
    (``go/pserver/service.go:119-174``) and
    ``ConcurrentRemoteParameterUpdater`` overlaps parameter traffic with
    compute (``paddle/trainer/RemoteParameterUpdater.cpp:244``). Here
    ``save()`` snapshots device arrays to host synchronously (the one part
    that must observe a consistent training state), then hands the CRC +
    npz write + atomic swap to a single background thread. The next
    ``save()`` — or ``wait()`` / context exit — fences the in-flight write;
    a background failure re-raises at that fence."""

    def __init__(self):
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="ckpt")
        self._pending = None

    def wait(self) -> None:
        """Block until the in-flight write (if any) lands; re-raise its
        error."""
        if self._pending is not None:
            fut, self._pending = self._pending, None
            fut.result()

    def save(self, root: str, pass_id: int, tree: Dict[str, Any],
             keep_last: Optional[int] = None) -> str:
        if jax.process_index() != 0:
            return pass_dir(root, pass_id)
        self.wait()                        # fence the previous save
        host = _snapshot_host(tree)
        self._pending = self._pool.submit(_write_pass_dir, root, pass_id,
                                          host, keep_last)
        return pass_dir(root, pass_id)

    def close(self) -> None:
        try:
            self.wait()
        finally:
            self._pool.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _gc(root: str, keep_last: int):
    """Retention: keep the newest ``keep_last`` READABLE passes — live dirs
    and manifest-complete crash leftovers count equally (a crashed latest
    save may be the only copy of its pass and must survive) — then delete
    every pass-* entry (live, ``.tmp`` or ``.old``) whose pass id fell out
    of that set, so a stale leftover can never outlive and later shadow a
    pass the retention policy deleted. Entries with unparsable ids are
    left alone."""
    readable = set()
    for d in os.listdir(root):
        pid = _base_pass_id(d)
        if pid is not None and \
                os.path.exists(os.path.join(root, d, _MANIFEST)):
            readable.add(pid)
    keep = set(sorted(readable)[-keep_last:])
    for d in os.listdir(root):
        pid = _base_pass_id(d)
        if pid is not None and pid not in keep:
            shutil.rmtree(os.path.join(root, d))


def latest_pass(root: str) -> Optional[int]:
    if not os.path.isdir(root):
        return None
    ids = [pid for d in os.listdir(root)
           if (pid := _base_pass_id(d)) is not None
           and os.path.exists(os.path.join(root, d, _MANIFEST))]
    return max(ids) if ids else None


def load_checkpoint(root: str, pass_id: Optional[int] = None,
                    verify_crc: bool = True) -> Dict[str, Any]:
    """Load a checkpoint dict; raises on CRC mismatch (the Go pserver's
    integrity check, ``go/pserver/service.go:346``)."""
    if pass_id is None:
        pass_id = latest_pass(root)
        if pass_id is None:
            raise FileNotFoundError(f"no checkpoints under {root}")
    d = _resolve_pass_dir(root, pass_id)
    manifest = verify_manifest(d, verify_crc=verify_crc)
    out = {}
    for fname in manifest["files"]:
        if not fname.endswith(".npz"):
            continue
        with np.load(os.path.join(d, fname), allow_pickle=False) as z:
            out[fname[:-len(".npz")]] = _unflatten({k: z[k] for k in z.files})
    out["pass_id"] = manifest["pass_id"]
    return out
