"""Checkpoint / resume.

Reference UX: per-pass param dirs ``pass-00000/`` with save/load + optimizer
state apply/restore (``/root/reference/paddle/trainer/ParamUtil.cpp:50-71``,
``Parameter::save/load`` ``parameter/Parameter.h:310``); Go pserver adds CRC'd
periodic checkpoints + atomic temp-file renames (``go/pserver/service.go:119``).

TPU-native: the checkpoint is the whole training pytree (params, module state,
optimizer state, step, and data-iterator position), saved as one ``.npz`` per
collection with flattened ``a/b/c`` keys + a JSON manifest carrying a CRC per
file and the pytree structure. Writes are atomic (temp dir + rename), restore is
sharding-aware (arrays are device_put against the current mesh after load).
Multi-host: only process 0 writes (single-controller pattern); all hosts read.
"""

from __future__ import annotations

import json
import os
import shutil
import zlib
from typing import Any, Dict, Optional

import numpy as np
import jax

__all__ = ["save_checkpoint", "load_checkpoint", "latest_pass", "pass_dir"]


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        tag = "T" if isinstance(tree, tuple) else "L"
        out[f"{prefix}__len_{tag}__"] = np.asarray(len(tree))
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    elif tree is None:
        out[f"{prefix}__none__"] = np.asarray(0)
    else:
        # Root-level leaves (e.g. the bare `step` scalar) need a non-empty key.
        out[prefix.rstrip("/") or "__value__"] = np.asarray(tree)
    return out


def _unflatten(flat: Dict[str, np.ndarray]):
    # Rebuild nested dict/list/tuple structure from path keys.
    if list(flat.keys()) == ["__none__"]:
        return None
    if list(flat.keys()) == ["__value__"]:
        return flat["__value__"]
    if not flat:
        return {}
    # sequence marker?
    for tag, ctor in (("T", tuple), ("L", list)):
        key = f"__len_{tag}__"
        if key in flat:
            n = int(flat[key])
            items = []
            for i in range(n):
                sub = {k[len(f"{i}/"):]: v for k, v in flat.items()
                       if k.startswith(f"{i}/")}
                if not sub and str(i) in flat:
                    items.append(flat[str(i)])  # bare array element
                else:
                    items.append(_unflatten(sub))
            return ctor(items)
    out: Dict[str, Any] = {}
    leaves = {}
    children: Dict[str, Dict[str, np.ndarray]] = {}
    for k, v in flat.items():
        if "/" in k:
            head, rest = k.split("/", 1)
            children.setdefault(head, {})[rest] = v
        else:
            leaves[k] = v
    for k, v in leaves.items():
        out[k] = v
    for k, sub in children.items():
        out[k] = _unflatten(sub)
    return out


def pass_dir(root: str, pass_id: int) -> str:
    return os.path.join(root, f"pass-{pass_id:05d}")


def save_checkpoint(root: str, pass_id: int, tree: Dict[str, Any],
                    keep_last: Optional[int] = None) -> str:
    """Atomically write ``tree`` (a dict of collections) to pass-NNNNN/."""
    if jax.process_index() != 0:
        return pass_dir(root, pass_id)
    final = pass_dir(root, pass_id)
    tmp = final + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp, exist_ok=True)
    manifest = {"pass_id": pass_id, "files": {}}
    for coll, sub in tree.items():
        host_tree = jax.tree_util.tree_map(lambda x: np.asarray(x), sub)
        flat = _flatten(host_tree)
        path = os.path.join(tmp, f"{coll}.npz")
        np.savez(path, **flat)
        with open(path, "rb") as f:
            crc = zlib.crc32(f.read())
        manifest["files"][coll] = {"crc32": crc}
    with open(os.path.join(tmp, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    if os.path.exists(final):
        shutil.rmtree(final)
    os.rename(tmp, final)
    if keep_last:
        _gc(root, keep_last)
    return final


def _gc(root: str, keep_last: int):
    passes = sorted(d for d in os.listdir(root) if d.startswith("pass-")
                    and not d.endswith(".tmp"))
    for d in passes[:-keep_last]:
        shutil.rmtree(os.path.join(root, d))


def latest_pass(root: str) -> Optional[int]:
    if not os.path.isdir(root):
        return None
    ids = [int(d.split("-")[1]) for d in os.listdir(root)
           if d.startswith("pass-") and not d.endswith(".tmp")
           and os.path.exists(os.path.join(root, d, "manifest.json"))]
    return max(ids) if ids else None


def load_checkpoint(root: str, pass_id: Optional[int] = None,
                    verify_crc: bool = True) -> Dict[str, Any]:
    """Load a checkpoint dict; raises on CRC mismatch (the Go pserver's
    integrity check, ``go/pserver/service.go:346``)."""
    if pass_id is None:
        pass_id = latest_pass(root)
        if pass_id is None:
            raise FileNotFoundError(f"no checkpoints under {root}")
    d = pass_dir(root, pass_id)
    with open(os.path.join(d, "manifest.json")) as f:
        manifest = json.load(f)
    out = {}
    for coll, meta in manifest["files"].items():
        path = os.path.join(d, f"{coll}.npz")
        if verify_crc:
            with open(path, "rb") as f:
                crc = zlib.crc32(f.read())
            if crc != meta["crc32"]:
                raise IOError(f"checkpoint corrupt: crc mismatch in {path}")
        with np.load(path, allow_pickle=False) as z:
            out[coll] = _unflatten({k: z[k] for k in z.files})
    out["pass_id"] = manifest["pass_id"]
    return out
