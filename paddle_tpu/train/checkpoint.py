"""Checkpoint / resume.

Reference UX: per-pass param dirs ``pass-00000/`` with save/load + optimizer
state apply/restore (``/root/reference/paddle/trainer/ParamUtil.cpp:50-71``,
``Parameter::save/load`` ``parameter/Parameter.h:310``); Go pserver adds CRC'd
periodic checkpoints + atomic temp-file renames (``go/pserver/service.go:119``).

TPU-native: the checkpoint is the whole training pytree (params, module state,
optimizer state, step, and data-iterator position), saved as one ``.npz`` per
collection with flattened ``a/b/c`` keys + a JSON manifest carrying a CRC per
file and the pytree structure. Writes are atomic (temp dir + rename), restore is
sharding-aware (arrays are device_put against the current mesh after load).
Multi-host: only process 0 writes (single-controller pattern); all hosts read.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import logging
import os
import shutil
import time
import zipfile
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

import numpy as np
import jax

__all__ = ["save_checkpoint", "load_checkpoint", "load_latest_valid",
           "latest_pass", "pass_dir", "atomic_dir", "write_manifest",
           "verify_manifest", "quarantine_pass_dir",
           "CorruptCheckpointError", "AsyncCheckpointer"]

_log = logging.getLogger("paddle_tpu.checkpoint")

_MANIFEST = "manifest.json"


class CorruptCheckpointError(IOError):
    """A pass dir whose manifest/CRC integrity check failed (missing
    file, CRC mismatch, colliding entries). An ``IOError`` subclass so
    pre-fallback callers that caught ``IOError`` still do; the fallback
    chain (:func:`load_latest_valid`) and the resilience supervisor
    catch it specifically to quarantine-and-fall-back instead of dying."""


@contextlib.contextmanager
def atomic_dir(path: str):
    """Write into ``path + '.tmp'``; swap into place when the block
    succeeds. CRASH-ATOMIC: the live ``path`` is renamed aside to
    ``path + '.old'`` before the new dir renames in, so every window of a
    crash leaves at least one COMPLETE dir — ``path`` itself, or (between
    the two renames) both the finished ``.tmp`` and the previous ``.old``,
    which readers resolve via ``_resolve_pass_dir``. The prior
    ``rmtree(path); rename(tmp)`` recipe had a window with neither (the
    Go pserver writes aside then renames over, go/pserver/service.go:346)."""
    tmp = path + ".tmp"
    if os.path.exists(tmp):
        # A manifest-complete .tmp with NO live dir is the only surviving
        # copy of this pass (crash between the two renames; readers are
        # resolving it right now) — demote it to .old instead of deleting,
        # keeping the at-least-one-complete-copy invariant through the
        # rewrite. Anything else is half-written garbage.
        if (not os.path.exists(path)
                and os.path.exists(os.path.join(tmp, _MANIFEST))):
            old = path + ".old"
            if os.path.exists(old):
                shutil.rmtree(old)
            os.rename(tmp, old)
        else:
            shutil.rmtree(tmp)
    os.makedirs(tmp, exist_ok=True)
    yield tmp
    old = path + ".old"
    if os.path.exists(old):
        shutil.rmtree(old)
    if os.path.exists(path):
        os.rename(path, old)
    os.rename(tmp, path)
    if os.path.exists(old):
        shutil.rmtree(old)


def _is_pass_dir(name: str) -> bool:
    return (name.startswith("pass-")
            and not name.endswith((".tmp", ".old")))


def _base_pass_id(name: str) -> Optional[int]:
    """Pass id of a live dir OR a ``.tmp``/``.old`` crash leftover."""
    base = name[:-4] if name.endswith((".tmp", ".old")) else name
    if not base.startswith("pass-"):
        return None
    try:
        return int(base.split("-")[1])
    except (IndexError, ValueError):
        return None


def _resolve_pass_dir(root: str, pass_id: int) -> str:
    """Directory to READ pass ``pass_id`` from: the live dir, else a
    manifest-complete ``.tmp`` (newer) or ``.old`` left by a crash between
    ``atomic_dir``'s two renames. Resolution is a PURE READ — no renames —
    so concurrent readers on every host always agree and can never race an
    in-flight writer; the writer path alone mutates the root (it rebuilds
    or garbage-collects leftovers on its next save)."""
    base = pass_dir(root, pass_id)
    for d in (base, base + ".tmp", base + ".old"):
        if os.path.exists(os.path.join(d, _MANIFEST)):
            return d
    return base        # fails downstream with the usual missing-file error


def _file_crc(path: str) -> int:
    with open(path, "rb") as f:
        return zlib.crc32(f.read())


def write_manifest(d: str, meta: Optional[Dict[str, Any]] = None) -> None:
    """CRC every file in ``d`` into ``manifest.json`` (plus ``meta``)."""
    files = {f: {"crc32": _file_crc(os.path.join(d, f))}
             for f in sorted(os.listdir(d)) if f != _MANIFEST}
    with open(os.path.join(d, _MANIFEST), "w") as f:
        json.dump({**(meta or {}), "files": files}, f, indent=2)


def verify_manifest(d: str, verify_crc: bool = True) -> Dict[str, Any]:
    """Load ``manifest.json``; raise on CRC mismatch (the Go pserver's
    integrity check, ``go/pserver/service.go:346``)."""
    with open(os.path.join(d, _MANIFEST)) as f:
        manifest = json.load(f)
    # Early manifests keyed entries by collection name ('params') rather than
    # filename ('params.npz'); normalise so both generations load.
    normalized: Dict[str, Any] = {}
    for f, info in manifest["files"].items():
        if os.path.exists(os.path.join(d, f)):
            resolved = f
        elif os.path.exists(os.path.join(d, f + ".npz")):
            resolved = f + ".npz"
        else:
            raise CorruptCheckpointError(
                f"checkpoint {d} is missing file for manifest entry {f!r} "
                f"(neither {f!r} nor {f + '.npz'!r} exists)")
        if resolved in normalized:
            raise CorruptCheckpointError(
                f"checkpoint {d}: manifest entries collide on {resolved!r} "
                f"after legacy-name normalisation")
        normalized[resolved] = info
    manifest["files"] = normalized
    if verify_crc:
        for fname, info in manifest["files"].items():
            if _file_crc(os.path.join(d, fname)) != info["crc32"]:
                raise CorruptCheckpointError(
                    f"crc mismatch in {os.path.join(d, fname)}")
    return manifest


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        tag = "T" if isinstance(tree, tuple) else "L"
        out[f"{prefix}__len_{tag}__"] = np.asarray(len(tree))
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    elif tree is None:
        out[f"{prefix}__none__"] = np.asarray(0)
    else:
        # Root-level leaves (e.g. the bare `step` scalar) need a non-empty key.
        out[prefix.rstrip("/") or "__value__"] = np.asarray(tree)
    return out


def _unflatten(flat: Dict[str, np.ndarray]):
    # Rebuild nested dict/list/tuple structure from path keys.
    if list(flat.keys()) == ["__none__"]:
        return None
    if list(flat.keys()) == ["__value__"]:
        return flat["__value__"]
    if not flat:
        return {}
    # sequence marker?
    for tag, ctor in (("T", tuple), ("L", list)):
        key = f"__len_{tag}__"
        if key in flat:
            n = int(flat[key])
            items = []
            for i in range(n):
                sub = {k[len(f"{i}/"):]: v for k, v in flat.items()
                       if k.startswith(f"{i}/")}
                if not sub and str(i) in flat:
                    items.append(flat[str(i)])  # bare array element
                else:
                    items.append(_unflatten(sub))
            return ctor(items)
    out: Dict[str, Any] = {}
    leaves = {}
    children: Dict[str, Dict[str, np.ndarray]] = {}
    for k, v in flat.items():
        if "/" in k:
            head, rest = k.split("/", 1)
            children.setdefault(head, {})[rest] = v
        else:
            leaves[k] = v
    for k, v in leaves.items():
        out[k] = v
    for k, sub in children.items():
        out[k] = _unflatten(sub)
    return out


def pass_dir(root: str, pass_id: int) -> str:
    return os.path.join(root, f"pass-{pass_id:05d}")


def _snapshot_host(tree: Dict[str, Any]) -> Dict[str, Any]:
    """Fetch every collection to host numpy (ONE device_get per leaf —
    the only part of a save that must see a consistent device state)."""
    return {coll: jax.tree_util.tree_map(lambda x: np.asarray(x), sub)
            for coll, sub in tree.items()}


def _write_pass_dir(root: str, pass_id: int, tree: Dict[str, Any],
                    keep_last: Optional[int] = None, faults=None) -> str:
    """The disk half of a save (CRC + npz write + swap + gc). Snapshots
    each collection to host right before writing it, so the sync path holds
    at most ONE collection in host memory at a time; the async path passes
    pre-snapshotted numpy (``np.asarray`` is then a no-op).

    ``faults``: optional :class:`~paddle_tpu.train.faults.FaultSchedule` —
    the checkpoint writer's two injection points (``fail_save_at`` before
    any byte is written; ``slow_save``/``corrupt_checkpoint_file`` after
    the atomic swap). None (the default) is the exact pre-faults path."""
    fault_idx = faults.on_write_begin(pass_id) if faults is not None else None
    final = pass_dir(root, pass_id)
    with atomic_dir(final) as tmp:
        for coll, sub in tree.items():
            host = jax.tree_util.tree_map(lambda x: np.asarray(x), sub)
            np.savez(os.path.join(tmp, f"{coll}.npz"), **_flatten(host))
        write_manifest(tmp, {"pass_id": pass_id})
    if faults is not None:
        faults.on_write_complete(final, pass_id, fault_idx)
    if keep_last:
        _gc(root, keep_last)
    return final


def save_checkpoint(root: str, pass_id: int, tree: Dict[str, Any],
                    keep_last: Optional[int] = None, faults=None) -> str:
    """Atomically write ``tree`` (a dict of collections) to pass-NNNNN/."""
    if jax.process_index() != 0:
        return pass_dir(root, pass_id)
    return _write_pass_dir(root, pass_id, tree, keep_last, faults=faults)


def _dir_bytes(d: str) -> int:
    try:
        return sum(os.path.getsize(os.path.join(d, f))
                   for f in os.listdir(d)
                   if os.path.isfile(os.path.join(d, f)))
    except OSError:
        return 0


class AsyncCheckpointer:
    """Off-critical-path checkpointing (SURVEY §5 "Orbax-style async").

    The reference keeps checkpoint work off the training hot path — the Go
    pserver checkpoints on its own ticker goroutine
    (``go/pserver/service.go:119-174``) and
    ``ConcurrentRemoteParameterUpdater`` overlaps parameter traffic with
    compute (``paddle/trainer/RemoteParameterUpdater.cpp:244``). Here
    ``save()`` snapshots device arrays to host synchronously (the one part
    that must observe a consistent training state), then hands the CRC +
    npz write + atomic swap to a single background thread. The next
    ``save()`` — or ``wait()`` / context exit — fences the in-flight write;
    a background failure re-raises at that fence.

    Args:
      telemetry: optional :class:`paddle_tpu.obs.Telemetry`. Each landed
        save emits one ``kind="checkpoint"`` record (pass_id, host
        snapshot ms, background write ms, bytes on disk, and the backlog
        ms the save spent fenced behind the previous in-flight write) —
        emitted from the worker thread, which the PR-4 sink
        thread-safety contract allows. A background write failure
        increments ``telemetry.background_failures`` (surfaced in
        ``Telemetry.summary()``) before re-raising at the fence.
      faults: optional :class:`~paddle_tpu.train.faults.FaultSchedule`,
        threaded into the background ``_write_pass_dir`` so save-path
        faults (fail/slow/corrupt) fire where real ones would — in the
        worker, surfacing at the next fence.

    Shutdown safety: construction registers an ``atexit`` final
    ``wait()`` (unregistered by ``close()``), so an interpreter exit
    that skipped ``close()`` still fences the in-flight write instead of
    truncating it — and surfaces its error in the log rather than
    swallowing it with the process."""

    def __init__(self, telemetry=None, faults=None):
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="ckpt")
        self._pending = None
        self.telemetry = telemetry
        self.faults = faults
        atexit.register(self._atexit_wait)

    def _atexit_wait(self) -> None:
        """Interpreter-exit safety net: fence the in-flight write; log
        (never raise) its failure — there is no caller left to catch."""
        try:
            self.wait()
        except Exception:
            _log.exception("async checkpoint write failed at interpreter "
                           "exit (the checkpoint did not land)")

    def wait(self) -> None:
        """Block until the in-flight write (if any) lands; re-raise its
        error."""
        if self._pending is not None:
            fut, self._pending = self._pending, None
            fut.result()

    def _write_job(self, root: str, pass_id: int, host: Dict[str, Any],
                   keep_last: Optional[int], snapshot_s: float,
                   backlog_s: float) -> str:
        """The background half: write + telemetry record; a failure
        bumps the background-failure counter, then re-raises into the
        future (surfacing at the caller's next fence)."""
        t0 = time.perf_counter()
        try:
            final = _write_pass_dir(root, pass_id, host, keep_last,
                                    faults=self.faults)
        except BaseException:
            if self.telemetry is not None:
                self.telemetry.background_failures += 1
            raise
        if self.telemetry is not None:
            self.telemetry.emit_event({
                "kind": "checkpoint", "pass_id": pass_id,
                "snapshot_ms": round(snapshot_s * 1e3, 3),
                "write_ms": round((time.perf_counter() - t0) * 1e3, 3),
                "bytes": _dir_bytes(final),
                "backlog_ms": round(backlog_s * 1e3, 3),
                "async": True})
        return final

    def save(self, root: str, pass_id: int, tree: Dict[str, Any],
             keep_last: Optional[int] = None) -> str:
        if jax.process_index() != 0:
            return pass_dir(root, pass_id)
        t0 = time.perf_counter()
        self.wait()                        # fence the previous save
        backlog_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        host = _snapshot_host(tree)
        snapshot_s = time.perf_counter() - t1
        self._pending = self._pool.submit(
            self._write_job, root, pass_id, host, keep_last, snapshot_s,
            backlog_s)
        return pass_dir(root, pass_id)

    def close(self) -> None:
        try:
            self.wait()
        finally:
            try:
                atexit.unregister(self._atexit_wait)
            except Exception:       # pragma: no cover - interpreter teardown
                pass
            self._pool.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _gc(root: str, keep_last: int):
    """Retention: keep the newest ``keep_last`` READABLE passes — live dirs
    and manifest-complete crash leftovers count equally (a crashed latest
    save may be the only copy of its pass and must survive) — then delete
    every pass-* entry (live, ``.tmp`` or ``.old``) whose pass id fell out
    of that set, so a stale leftover can never outlive and later shadow a
    pass the retention policy deleted. Entries with unparsable ids are
    left alone."""
    readable = set()
    for d in os.listdir(root):
        pid = _base_pass_id(d)
        if pid is not None and \
                os.path.exists(os.path.join(root, d, _MANIFEST)):
            readable.add(pid)
    keep = set(sorted(readable)[-keep_last:])
    for d in os.listdir(root):
        pid = _base_pass_id(d)
        if pid is not None and pid not in keep:
            shutil.rmtree(os.path.join(root, d))


def latest_pass(root: str) -> Optional[int]:
    if not os.path.isdir(root):
        return None
    ids = [pid for d in os.listdir(root)
           if (pid := _base_pass_id(d)) is not None
           and os.path.exists(os.path.join(root, d, _MANIFEST))]
    return max(ids) if ids else None


def load_checkpoint(root: str, pass_id: Optional[int] = None,
                    verify_crc: bool = True) -> Dict[str, Any]:
    """Load a checkpoint dict; raises on CRC mismatch (the Go pserver's
    integrity check, ``go/pserver/service.go:346``)."""
    if pass_id is None:
        pass_id = latest_pass(root)
        if pass_id is None:
            raise FileNotFoundError(f"no checkpoints under {root}")
    d = _resolve_pass_dir(root, pass_id)
    manifest = verify_manifest(d, verify_crc=verify_crc)
    out = {}
    for fname in manifest["files"]:
        if not fname.endswith(".npz"):
            continue
        with np.load(os.path.join(d, fname), allow_pickle=False) as z:
            out[fname[:-len(".npz")]] = _unflatten({k: z[k] for k in z.files})
    out["pass_id"] = manifest["pass_id"]
    return out


# -- fallback chain (ISSUE 10) ----------------------------------------------

def quarantine_pass_dir(d: str) -> str:
    """Move a bad checkpoint dir aside to ``d + '.corrupt'`` — NEVER
    delete it (the bytes are forensic evidence, and deletion would turn a
    bad checksum into an unexplainable gap). The ``.corrupt`` name parses
    to no pass id, so quarantined dirs are invisible to
    :func:`latest_pass`, :func:`_resolve_pass_dir`, and retention
    (``_gc`` leaves unparsable names alone). Returns the quarantine
    path."""
    target, k = d + ".corrupt", 1
    while os.path.exists(target):
        k += 1
        target = f"{d}.corrupt{k}"
    os.rename(d, target)
    _log.warning(
        "quarantined corrupt checkpoint dir %s -> %s; falling back to the "
        "previous readable pass (inspect or delete the quarantined copy "
        "manually)", d, target)
    return target


# what a poisoned pass dir can raise at load: integrity failures
# (CorruptCheckpointError is an OSError), truncated/garbled npz (BadZipFile,
# ValueError), a garbled manifest (json errors are ValueError), or a
# manifest missing its keys (KeyError)
_LOAD_ERRORS = (OSError, ValueError, KeyError, zipfile.BadZipFile)


def load_latest_valid(root: str, verify_crc: bool = True) -> Dict[str, Any]:
    """Load the NEWEST READABLE checkpoint, quarantining every poisoned
    pass dir met on the way (the recovery half of the Go pserver's CRC
    story: it *wrote* checksums; this is what a reader does when one
    fails). Each failing dir is renamed aside via
    :func:`quarantine_pass_dir` — never silently deleted — and the chain
    falls back to the previous readable pass. Raises
    ``FileNotFoundError`` when nothing readable remains.

    The returned dict is ``load_checkpoint``'s, plus ``_quarantined``:
    the list of quarantine paths created (empty on the clean path) —
    ``Trainer.restore`` pops it into ``trainer.last_quarantined`` so the
    supervisor can count fallbacks.

    Multi-reader safe: on a shared checkpoint root, another host may
    quarantine (or retention may delete) the dir between our
    ``latest_pass`` probe and the load — a VANISHED dir is re-scanned,
    not treated as corruption, so every host racing the same poison
    converges on the same fallback pass instead of one of them dying
    (or restarting from scratch) on the other's rename."""
    quarantined: List[str] = []
    # every iteration either quarantines a dir, or re-scans after one
    # vanished — both strictly shrink the candidate set, but bound the
    # loop anyway so a pathological writer can't spin us forever
    for _ in range(10000):
        pid = latest_pass(root)
        if pid is None:
            err = FileNotFoundError(
                f"no readable checkpoints under {root}"
                + (f" ({len(quarantined)} quarantined: {quarantined})"
                   if quarantined else ""))
            err.quarantined = quarantined     # the ledger survives the raise
            raise err
        d = _resolve_pass_dir(root, pid)
        try:
            out = load_checkpoint(root, pid, verify_crc=verify_crc)
            out["_quarantined"] = quarantined
            return out
        except _LOAD_ERRORS as e:
            if not os.path.isdir(d):
                # a concurrent actor moved it (another host's quarantine,
                # retention gc) — the next probe sees the new state
                _log.warning(
                    "checkpoint pass %d vanished mid-read (%s: %s) — "
                    "re-scanning %s", pid, type(e).__name__, e, root)
                continue
            _log.warning("checkpoint pass %d failed to load (%s: %s)",
                         pid, type(e).__name__, e)
            quarantined.append(quarantine_pass_dir(d))
    raise RuntimeError(
        f"load_latest_valid({root!r}) did not converge after 10000 "
        f"attempts — a writer is racing the reader pathologically")
