"""Checkpoint / resume.

Reference UX: per-pass param dirs ``pass-00000/`` with save/load + optimizer
state apply/restore (``/root/reference/paddle/trainer/ParamUtil.cpp:50-71``,
``Parameter::save/load`` ``parameter/Parameter.h:310``); Go pserver adds CRC'd
periodic checkpoints + atomic temp-file renames (``go/pserver/service.go:119``).

TPU-native: the checkpoint is the whole training pytree (params, module state,
optimizer state, step, and data-iterator position), saved as one ``.npz`` per
collection with flattened ``a/b/c`` keys + a JSON manifest carrying a CRC per
file and the pytree structure. Writes are atomic (temp dir + rename), restore is
sharding-aware (arrays are device_put against the current mesh after load).
Multi-host: only process 0 writes (single-controller pattern); all hosts read.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import zlib
from typing import Any, Dict, Optional

import numpy as np
import jax

__all__ = ["save_checkpoint", "load_checkpoint", "latest_pass", "pass_dir",
           "atomic_dir", "write_manifest", "verify_manifest"]

_MANIFEST = "manifest.json"


@contextlib.contextmanager
def atomic_dir(path: str):
    """Write into ``path + '.tmp'``; atomically rename over ``path`` when the
    block succeeds (the Go pserver's temp-file + rename recipe)."""
    tmp = path + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp, exist_ok=True)
    yield tmp
    if os.path.exists(path):
        shutil.rmtree(path)
    os.rename(tmp, path)


def _file_crc(path: str) -> int:
    with open(path, "rb") as f:
        return zlib.crc32(f.read())


def write_manifest(d: str, meta: Optional[Dict[str, Any]] = None) -> None:
    """CRC every file in ``d`` into ``manifest.json`` (plus ``meta``)."""
    files = {f: {"crc32": _file_crc(os.path.join(d, f))}
             for f in sorted(os.listdir(d)) if f != _MANIFEST}
    with open(os.path.join(d, _MANIFEST), "w") as f:
        json.dump({**(meta or {}), "files": files}, f, indent=2)


def verify_manifest(d: str, verify_crc: bool = True) -> Dict[str, Any]:
    """Load ``manifest.json``; raise on CRC mismatch (the Go pserver's
    integrity check, ``go/pserver/service.go:346``)."""
    with open(os.path.join(d, _MANIFEST)) as f:
        manifest = json.load(f)
    # Early manifests keyed entries by collection name ('params') rather than
    # filename ('params.npz'); normalise so both generations load.
    normalized: Dict[str, Any] = {}
    for f, info in manifest["files"].items():
        if os.path.exists(os.path.join(d, f)):
            resolved = f
        elif os.path.exists(os.path.join(d, f + ".npz")):
            resolved = f + ".npz"
        else:
            raise IOError(
                f"checkpoint {d} is missing file for manifest entry {f!r} "
                f"(neither {f!r} nor {f + '.npz'!r} exists)")
        if resolved in normalized:
            raise IOError(
                f"checkpoint {d}: manifest entries collide on {resolved!r} "
                f"after legacy-name normalisation")
        normalized[resolved] = info
    manifest["files"] = normalized
    if verify_crc:
        for fname, info in manifest["files"].items():
            if _file_crc(os.path.join(d, fname)) != info["crc32"]:
                raise IOError(f"crc mismatch in {os.path.join(d, fname)}")
    return manifest


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        tag = "T" if isinstance(tree, tuple) else "L"
        out[f"{prefix}__len_{tag}__"] = np.asarray(len(tree))
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    elif tree is None:
        out[f"{prefix}__none__"] = np.asarray(0)
    else:
        # Root-level leaves (e.g. the bare `step` scalar) need a non-empty key.
        out[prefix.rstrip("/") or "__value__"] = np.asarray(tree)
    return out


def _unflatten(flat: Dict[str, np.ndarray]):
    # Rebuild nested dict/list/tuple structure from path keys.
    if list(flat.keys()) == ["__none__"]:
        return None
    if list(flat.keys()) == ["__value__"]:
        return flat["__value__"]
    if not flat:
        return {}
    # sequence marker?
    for tag, ctor in (("T", tuple), ("L", list)):
        key = f"__len_{tag}__"
        if key in flat:
            n = int(flat[key])
            items = []
            for i in range(n):
                sub = {k[len(f"{i}/"):]: v for k, v in flat.items()
                       if k.startswith(f"{i}/")}
                if not sub and str(i) in flat:
                    items.append(flat[str(i)])  # bare array element
                else:
                    items.append(_unflatten(sub))
            return ctor(items)
    out: Dict[str, Any] = {}
    leaves = {}
    children: Dict[str, Dict[str, np.ndarray]] = {}
    for k, v in flat.items():
        if "/" in k:
            head, rest = k.split("/", 1)
            children.setdefault(head, {})[rest] = v
        else:
            leaves[k] = v
    for k, v in leaves.items():
        out[k] = v
    for k, sub in children.items():
        out[k] = _unflatten(sub)
    return out


def pass_dir(root: str, pass_id: int) -> str:
    return os.path.join(root, f"pass-{pass_id:05d}")


def save_checkpoint(root: str, pass_id: int, tree: Dict[str, Any],
                    keep_last: Optional[int] = None) -> str:
    """Atomically write ``tree`` (a dict of collections) to pass-NNNNN/."""
    if jax.process_index() != 0:
        return pass_dir(root, pass_id)
    final = pass_dir(root, pass_id)
    with atomic_dir(final) as tmp:
        for coll, sub in tree.items():
            host_tree = jax.tree_util.tree_map(lambda x: np.asarray(x), sub)
            np.savez(os.path.join(tmp, f"{coll}.npz"), **_flatten(host_tree))
        write_manifest(tmp, {"pass_id": pass_id})
    if keep_last:
        _gc(root, keep_last)
    return final


def _gc(root: str, keep_last: int):
    passes = sorted(d for d in os.listdir(root) if d.startswith("pass-")
                    and not d.endswith(".tmp"))
    for d in passes[:-keep_last]:
        shutil.rmtree(os.path.join(root, d))


def latest_pass(root: str) -> Optional[int]:
    if not os.path.isdir(root):
        return None
    ids = [int(d.split("-")[1]) for d in os.listdir(root)
           if d.startswith("pass-") and not d.endswith(".tmp")
           and os.path.exists(os.path.join(root, d, "manifest.json"))]
    return max(ids) if ids else None


def load_checkpoint(root: str, pass_id: Optional[int] = None,
                    verify_crc: bool = True) -> Dict[str, Any]:
    """Load a checkpoint dict; raises on CRC mismatch (the Go pserver's
    integrity check, ``go/pserver/service.go:346``)."""
    if pass_id is None:
        pass_id = latest_pass(root)
        if pass_id is None:
            raise FileNotFoundError(f"no checkpoints under {root}")
    d = pass_dir(root, pass_id)
    manifest = verify_manifest(d, verify_crc=verify_crc)
    out = {}
    for fname in manifest["files"]:
        if not fname.endswith(".npz"):
            continue
        with np.load(os.path.join(d, fname), allow_pickle=False) as z:
            out[fname[:-len(".npz")]] = _unflatten({k: z[k] for k in z.files})
    out["pass_id"] = manifest["pass_id"]
    return out
