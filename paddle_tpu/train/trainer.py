"""The training driver — successor of paddle/trainer + the v2 SGD event loop.

Reference call stack (SURVEY.md §3.1/§3.2): ``Trainer::train`` → ``trainOnePass``
→ ``TrainerInternal::trainOneBatch`` (forwardBackward; updater; evaluators;
events), with data-parallelism delegated to ``MultiGradientMachine`` threads and
remote updaters talking to parameter servers.

TPU-native design: ONE jit-compiled ``train_step`` closed over model+optimizer,
executed over a device mesh. Data parallelism is a sharding annotation, not a
thread pool: the batch arrives sharded over the ``data`` axis, parameters are
replicated, and XLA inserts the gradient all-reduce (the entire pserver tier of
the reference collapses into this). Evaluator statistics ride in the same
compiled step. The host loop only feeds data, fires events, logs, and
checkpoints — mirroring the v2 ``SGD.train`` surface
(``python/paddle/v2/trainer.py:124``).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

_log = logging.getLogger("paddle_tpu.trainer")

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import mesh as mesh_lib
from ..core.module import Module
from ..obs.trace import tspan
from ..optim.optimizers import Optimizer, apply_updates
from ..utils.stats import StatSet
from . import checkpoint as ckpt_lib
from . import events as ev
from .faults import Preempted

__all__ = ["Trainer", "TrainState"]


def _batch_fingerprint(host_batch) -> int:
    """CRC32 of a host batch's raw bytes — recorded in mid-pass checkpoints
    so a resume can detect a nondeterministic reader (a shuffled/buffered
    reader replayed from scratch yields a different batch at the same
    index, silently training on a different remainder otherwise)."""
    import zlib
    crc = 0
    leaves = jax.tree_util.tree_leaves(host_batch)
    for leaf in leaves:
        arr = np.ascontiguousarray(np.asarray(leaf))
        crc = zlib.crc32(arr.tobytes(), crc)
    return crc


def _batch_shapes(host_batch):
    """Leaf shapes of a host batch — fused groups must stack uniformly."""
    return tuple(np.shape(leaf)
                 for leaf in jax.tree_util.tree_leaves(host_batch))


def _step_fingerprint(batch_tree) -> tuple:
    """The trainer's step fingerprint: per-leaf (shape, dtype) of the batch
    pytree feeding one dispatch. A dispatch whose fingerprint was never
    seen before will trace + compile (a jit cache miss); the telemetry
    retrace counter is keyed by exactly this tuple. For fused groups the
    stacked leaves carry [K, M, ...], so K/M changes fingerprint too.

    Metadata-only on purpose: leaves may be DEVICE arrays (the pipelined
    path fingerprints the staged group) and an ``np.asarray`` here would
    download them."""
    def leaf_dtype(leaf):
        dt = getattr(leaf, "dtype", None)
        return str(dt) if dt is not None else str(np.asarray(leaf).dtype)

    return tuple((tuple(np.shape(leaf)), leaf_dtype(leaf))
                 for leaf in jax.tree_util.tree_leaves(batch_tree))


class TrainState:
    """The complete training pytree: params, module state, optimizer state, step."""

    def __init__(self, params, state, opt_state, step):
        self.params = params
        self.state = state
        self.opt_state = opt_state
        self.step = step

    def as_dict(self):
        return {"params": self.params, "state": self.state,
                "opt_state": self.opt_state,
                "step": self.step}


class Trainer:
    """Single-controller training driver.

    Args:
      model: the Module.
      loss_fn: ``(outputs, batch) -> per-example losses`` (reduced by mean,
        masked by ``batch['weight']`` if present).
      optimizer: an ``optim.Optimizer``.
      mesh: device mesh; defaults to all devices on the ``data`` axis.
      forward: optional ``(model, variables, batch, train, rngs) -> (out, new_state)``
        override for models with non-standard inputs (default feeds
        ``batch['x']``).
      evaluator: optional EvaluatorSet/Evaluator whose stats are computed
        inside the compiled step.
      param_sharding: optional model-parallel layout — either a
        ``parallel.ShardingRules`` or a pytree of PartitionSpecs matching the
        params tree. Params are materialized in that layout at init;
        optimizer state inherits each param's layout (eager
        ``optimizer.init`` on committed params — eager zeros_like
        propagates sharding); XLA inserts the collectives. Default fully
        replicated.
      steps_per_call: K > 1 fuses K optimizer steps into ONE device dispatch
        (a donated ``lax.scan`` over K pre-stacked host batches) — amortizes
        the per-call Python->device dispatch (~5 ms/call on the remote-TPU
        tunnel, experiments/PERF.md exp 2). The compiled program returns the
        stacked per-step losses/evaluator stats; host events, logging, and
        ``saving_period`` checkpoints replay per step after each call (so
        BeginIteration/EndIteration both fire post-dispatch, and mid-pass
        saves land on call boundaries). Numerically identical to K plain
        steps (same traced step body).
      grad_accum: M > 1 accumulates gradients over M consecutive host
        batches (microbatches) per optimizer step, in a donated-accumulator
        inner ``lax.scan`` — large effective batches beyond what HBM fits in
        one forward/backward. Loss/grads are the mean over the M microbatch
        means, each microbatch weight-normalized by its own ``weight`` field
        (mean-of-means; mask/weight-correct within each microbatch). The
        optimizer update — and with ``param_sharding`` the gradient
        all-reduce the partitioner hoists out of the accumulation loop —
        fires once per accumulated step, not per microbatch.
      grad_sync: None (default) leaves the data-parallel gradient
        all-reduce to the SPMD partitioner (one implicit collective per
        gradient tensor, typically combined+scheduled by the backend as a
        monolithic post-backward sync). ``"bucketed"`` takes explicit
        ownership (:mod:`paddle_tpu.parallel.overlap`): each microbatch's
        forward+backward runs in a manual-dp ``shard_map`` region (other
        mesh axes stay GSPMD-auto, so tensor-parallel ``param_sharding``
        composes), parameters are partitioned into byte-budgeted buckets
        in reverse layer order, and a ``custom_vjp`` marker all-reduces
        each bucket's cotangents as ONE flat psum the moment that
        bucket's backward slice completes — the scheduler can float each
        bucket's collective under the remaining backward compute. Models
        with a remat scan-over-layers stack sync the per-layer slice
        inside the scan transpose (``TransformerLM.grad_sync_scan_paths``
        protocol). ``"fused"`` is the single-bucket baseline: one flat
        post-backward all-reduce — bit-exact vs bucketed in f32 (same
        elementwise reduction, different granularity). With
        ``grad_accum > 1`` local gradients accumulate across microbatches
        and sync once per optimizer step, never per microbatch. Degrades
        gracefully (one warning, implicit sync) when the mesh has no
        multi-device dp axis or ``param_sharding`` shards params over the
        dp axis. Semantic deltas of the explicit modes: module-state
        updates (BN running stats) happen per dp shard — torch-DDP
        semantics, warned once when state is non-empty — and dropout
        draws per shard from a dp-coordinate-folded key (independent
        masks, but a different sample stream than the implicit path).
        Forward outputs must be batch-led (the ``shard_batch`` contract):
        a non-batch-led output leaf is either rejected at trace time
        (leading dim not divisible by dp) or would be mis-assembled —
        use ``grad_sync=None`` for such models.
      bucket_mb: bucket byte budget in MiB for ``grad_sync="bucketed"``
        (default 4.0). Smaller buckets start syncing earlier but pay more
        per-collective latency; see README "Gradient-sync overlap".
      pipeline_depth: W > 1 turns on the async host pipeline
        (``train/host_pipeline.py``): a background stager thread stacks and
        ``device_put``-shards group N+1 (double-buffered) while call N runs
        on device, and up to W fused calls stay in flight with their host
        replay (events, costs, evaluator updates, logging, telemetry)
        deferred until drained — removing the per-group host staging and
        the eager per-group loss fetch from the critical path. Draining is
        FIFO (the serial event order is preserved exactly), forced at
        every ``saving_period`` checkpoint boundary (saves observe a
        quiesced ``train_state``; ``nan_check``'s skip-the-poisoned-save
        rule still holds) and at pass end. Bit-identical math to the
        serial loop — same dispatches, same order, same rng. The plain
        (K=1, M=1) loop gets the same deferred-fetch treatment when
        ``nan_check`` is off (with it on, plain mode must trap each loss
        before the next dispatch, so it stays serial). The default W=1 is
        today's serial loop, byte-identical. With telemetry attached,
        pipelined calls skip the per-call device fence (it would serialize
        the pipeline) and record ``stage_ms`` / ``drain_wait_ms`` /
        ``overlap_frac`` instead. CONTRACT CHANGE for event handlers that
        read ``trainer.train_state``: at replay time the state may already
        include up to W later dispatched groups (only ``saving_period``
        saves are quiesced, via the forced boundary drain) — handlers
        that snapshot state per iteration need ``pipeline_depth=1``.
      telemetry: optional :class:`paddle_tpu.obs.Telemetry`. When attached,
        the trainer records a per-call step-time breakdown (host stack /
        shard / dispatch / fenced device / events-replay), tracks jit
        retraces by step fingerprint (with per-compile wall time and an
        HLO cost-analysis FLOPs estimate feeding MFU/tokens-per-sec),
        samples device memory, and — when ``telemetry.health`` — traces
        the training-health scalars (grad/param/update norms, NaN
        sentinel) INTO the compiled step, returned alongside the losses.
        With ``telemetry=None`` (default) the hot loop is unchanged: same
        traced step function, same dispatch count, same donation, and
        zero extra device fetches or fences.
      tracer: optional :class:`paddle_tpu.obs.Tracer`. When attached, the
        trainer records thread-aware timeline spans (plan / stack /
        device_put / dispatch / fence / drain-wait / events-replay /
        checkpoint-save / eval on the main thread; stack + shard on the
        stager thread, flow-linked to the later dispatch and drain) and
        serializes them as Chrome Trace Event JSON
        (``tracer.save(path)`` — open in Perfetto), so host/device
        overlap is visually auditable. Spans are host-side wall clocks
        only: no extra dispatch, no fence, and with ``tracer=None`` the
        hot loop is byte-identical (pinned by tests/test_trace.py
        alongside tests/test_obs.py's telemetry-off invariant).
      anomaly: optional :class:`paddle_tpu.obs.AnomalyDetector`. Consumes
        every telemetry step record (requires ``telemetry``); on a
        detected anomaly (slow-step outlier, retrace burst, drain stall,
        memory high-water, NaN sentinel) it dumps a one-shot forensics
        bundle — telemetry ring + recent trace spans + config/env/mesh
        snapshot + verdict — and can arm a ``jax.profiler`` capture for
        the next fused call. Observation only: training continues, and a
        detector failure is logged, never raised.
      faults: optional :class:`paddle_tpu.train.faults.FaultSchedule` —
        the deterministic fault-injection plane (ISSUE 10). When
        attached, the named injection points fire at their scheduled
        step/save/group: ``crash_at_step``/``preempt_at_step`` after
        that optimizer step's host replay, the save-path points inside
        the checkpoint writer (sync or async), and
        ``stager_error_at_group`` in the host-pipeline stager thread.
        With ``faults=None`` (default) the hot loop is the exact
        pre-faults build: same traced step, dispatch count, donation,
        zero extra fences (pinned by tests/test_resilience.py).

    Preemption: ``request_stop(reason)`` (typically from a SIGTERM/SIGINT
    handler — see :func:`paddle_tpu.train.resilience.
    install_preemption_handler`) asks ``train()`` to stop gracefully at
    the NEXT GROUP BOUNDARY: the host pipeline and deferred-fetch window
    are drained, a final quiesced checkpoint (with the data-iterator
    position) is written through the active save path, the async
    checkpointer is fenced, and ``train()`` raises
    :class:`~paddle_tpu.train.faults.Preempted` — a distinct CLEAN
    status the resilience supervisor returns instead of retrying.
    """

    def __init__(self, model: Module, loss_fn: Callable, optimizer: Optimizer,
                 mesh=None, forward: Optional[Callable] = None,
                 evaluator=None, param_sharding=None, donate: bool = True,
                 nan_check: bool = False,
                 param_stats_period: Optional[int] = None,
                 steps_per_call: int = 1, grad_accum: int = 1,
                 grad_sync: Optional[str] = None, bucket_mb: float = 4.0,
                 pipeline_depth: int = 1, telemetry=None, tracer=None,
                 anomaly=None, faults=None, metrics=None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh or mesh_lib.default_mesh()
        self.evaluator = evaluator
        self.stats = StatSet("trainer")
        self._forward = forward or self._default_forward
        self._param_sharding = param_sharding
        self._param_specs = None
        self._train_step = None
        self._eval_step = None
        self._donate = donate
        # nan_check: host-side finiteness trap on the per-step loss (the
        # reference's feenableexcept analog, TrainerMain.cpp:36); on trip it
        # names the non-finite param/state leaves before raising.
        self._nan_check = nan_check
        # param_stats_period: per-param scale telemetry every N batches (the
        # reference's --show_parameter_stats_period, TrainerInternal.cpp:81).
        self._param_stats_period = param_stats_period
        if steps_per_call < 1 or grad_accum < 1:
            raise ValueError("steps_per_call and grad_accum must be >= 1")
        if pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        self.steps_per_call = int(steps_per_call)
        self.grad_accum = int(grad_accum)
        # grad_sync: explicit dp gradient synchronization (bucketed
        # overlap / fused baseline) — validated eagerly, resolved against
        # the mesh lazily at step build (parallel.overlap).
        from ..parallel import overlap as overlap_lib
        if grad_sync not in overlap_lib.GRAD_SYNC_MODES:
            raise ValueError(
                f"grad_sync must be one of "
                f"{overlap_lib.GRAD_SYNC_MODES}, got {grad_sync!r}")
        if bucket_mb <= 0:
            raise ValueError(f"bucket_mb must be > 0, got {bucket_mb}")
        self.grad_sync = grad_sync
        self.bucket_mb = float(bucket_mb)
        self._grad_sync_warned = False
        self._state_sync_warned = False
        # pipeline_depth: bounded in-flight dispatch window (1 = serial).
        self.pipeline_depth = int(pipeline_depth)
        # host-side optimizer-step mirror: lets the fused replay number its
        # steps without fetching the device step scalar (a sync that would
        # defeat the pipeline); re-anchored from train_state at pass start.
        self._host_step = 0
        # telemetry: None = the untelemetered hot loop, byte-identical to
        # the pre-obs build (no health outputs in the traced step, no
        # fencing, no extra fetches — pinned by tests/test_obs.py).
        self.telemetry = telemetry
        # tracer/anomaly: same rule — None means the hot loop is the
        # pre-obs build exactly (tspan(None, ...) is a shared no-op
        # context; anomaly observation only ever follows a telemetry
        # emit, which telemetry=None already gates).
        self.tracer = tracer
        if anomaly is not None and telemetry is None:
            raise ValueError(
                "AnomalyDetector consumes telemetry step records — pass "
                "telemetry=Telemetry(...) alongside anomaly=")
        self.anomaly = anomaly
        # metrics: optional MetricsHub / scoped view (ISSUE 19) — the
        # trainer publishes a step-time histogram and a tokens/sec
        # gauge from each finalized step record. Same None doctrine as
        # telemetry/tracer: off means the hot loop is untouched.
        self.metrics = metrics
        # faults: None = the exact pre-faults hot loop (every injection
        # point is behind a host-side `is not None` check — no traced-step
        # or dispatch-count change; pinned by tests/test_resilience.py).
        self.faults = faults
        # graceful-stop request (SIGTERM handler / injected preemption /
        # request_stop()); a bare attribute write, so it is safe from
        # signal handlers and other threads. Consumed at group boundaries.
        self._stop_requested: Optional[str] = None
        self._fused_step = None
        self.train_state: Optional[TrainState] = None
        self._last_iter_state: Optional[Dict[str, Any]] = None
        # fallback-chain bookkeeping from the last restore (ISSUE 10)
        self.last_quarantined: list = []
        self._last_restored_pass: Optional[int] = None

    def _health_on(self) -> bool:
        return self.telemetry is not None and self.telemetry.health

    # -- preemption + fault injection (ISSUE 10) -----------------------------

    def request_stop(self, reason: str = "requested") -> None:
        """Ask the training loop to stop gracefully at the next group
        boundary (drain the pipeline, write a quiesced checkpoint, raise
        :class:`~paddle_tpu.train.faults.Preempted`). Safe to call from a
        signal handler or another thread — it only writes an attribute;
        all the work happens on the training thread at the boundary."""
        if self._stop_requested is None:
            self._stop_requested = reason
            _log.warning("graceful stop requested (%s): will quiesce at "
                         "the next group boundary", reason)

    def _fire_step_faults(self, step: int) -> None:
        """One optimizer step's host replay just finished: fire any
        scheduled crash (raises) or preemption (requests a graceful
        stop) keyed to that step. Callers gate on ``faults is not
        None``, so the off path never even makes this call."""
        fs = self.faults
        fs.maybe_crash_step(step)
        if fs.should_preempt(step):
            self.request_stop(f"injected preemption at step {step}")

    def _maybe_stop(self, pipe, pending, pass_id, next_batch, handler,
                    costs, log_period, checkpoint_dir, checkpoint_keep,
                    save_fn, last_batch=None) -> None:
        """Group-boundary graceful-stop check. When a stop is pending:
        drain the in-flight window (pipelined fused / deferred plain) so
        ``train_state`` quiesces at exactly ``next_batch`` consumed
        batches, write a final mid-pass checkpoint carrying the iterator
        position — with the last consumed batch's fingerprint
        (``last_batch``), so the resume-time nondeterministic-reader
        check guards the preempt path like every other mid-pass save —
        and exit via :class:`Preempted`. The async checkpointer (when
        active) is fenced by ``train()``'s finally — the preempt save is
        on disk before ``train()`` unwinds."""
        if self._stop_requested is None:
            return
        reason = self._stop_requested
        if pipe is not None:
            pipe.flush()           # FIFO drain: replay order preserved
        while pending:
            self._replay_plain(pending.pop(0), pass_id, handler, costs,
                               log_period, checkpoint_dir, checkpoint_keep,
                               save_fn)
        if checkpoint_dir:
            it = {"pass": pass_id, "next_batch": next_batch,
                  "completed": 0, "preempted": 1}
            if last_batch is not None:
                it["batch_crc"] = _batch_fingerprint(last_batch)
            with tspan(self.tracer, "checkpoint_save",
                       preempt_next_batch=next_batch):
                save_fn(
                    checkpoint_dir, pass_id,
                    {**self.train_state.as_dict(), "iter": it},
                    keep_last=checkpoint_keep)
        raise Preempted(pass_id=pass_id, next_batch=next_batch,
                        reason=reason)

    # -- anomaly plumbing ----------------------------------------------------

    def _anomaly_observe(self, rec) -> None:
        """Feed one finalized telemetry record to the anomaly detector.
        Detection is observation: a detector crash must never kill the
        run it watches, so failures log and training continues. Verdicts
        are echoed into the telemetry stream as ``kind="anomaly"``
        records (ISSUE 6: the run's JSONL is self-contained — the report
        CLI counts anomalies without reading bundle directories). The
        metrics registry (ISSUE 19) feeds from the same finalized
        records — every emit_step site already flows through here."""
        if (self.metrics is not None and rec is not None
                and rec.get("kind") == "step"):
            m = self.metrics
            k = rec.get("k_steps") or 1
            m.counter("train_steps", "optimizer steps completed").inc(k)
            total_ms = ((rec.get("device_ms") or 0.0)
                        + (rec.get("dispatch_ms") or 0.0))
            if total_ms > 0:
                m.histogram("train_step_ms",
                            "per-step wall (device+dispatch) ms"
                            ).observe(total_ms / k)
            if rec.get("tokens_per_sec") is not None:
                m.gauge("train_tokens_per_sec",
                        "training token throughput"
                        ).set(rec["tokens_per_sec"])
            if rec.get("loss") is not None:
                m.gauge("train_loss", "last step loss").set(rec["loss"])
        if self.anomaly is None or rec is None:
            return
        try:
            verdicts = self.anomaly.observe(rec)
        except Exception:
            _log.exception("anomaly detector failed (training continues)")
            return
        if verdicts and self.telemetry is not None:
            for v in verdicts:
                try:
                    vd = v.to_dict()
                    vd["anomaly_kind"] = vd.pop("kind")
                    self.telemetry.emit_event({"kind": "anomaly", **vd})
                except Exception:
                    _log.exception("anomaly telemetry emit failed")

    def _maybe_profiled_call(self, fn, *args):
        """Run ONE compiled dispatch, wrapped in an anomaly-armed
        ``jax.profiler`` capture when one is pending (every dispatch path
        — fused, serial plain, deferred plain — polls here, so
        ``arm_profiler`` is never a silent no-op). Returns ``(out,
        profiled)``; a profiled call fences inside the capture so the
        device compute lands in it — its record is stamped ``profiled``
        and excluded from rates/wall statistics."""
        prof_dir = (self.anomaly.take_profiler_request()
                    if self.anomaly is not None else None)
        if prof_dir is None:
            return fn(*args), False
        from ..obs.trace import jax_profile
        with jax_profile(prof_dir):
            out = fn(*args)
            jax.block_until_ready(out[:5])   # capture the compute,
        return out, True                     # not just the enqueue

    def _anomaly_context(self) -> Dict[str, Any]:
        """The config/env/mesh snapshot frozen into a forensics bundle."""
        import os
        mesh = self.mesh
        return {
            "model": type(self.model).__name__,
            "optimizer": type(self.optimizer).__name__,
            "steps_per_call": self.steps_per_call,
            "grad_accum": self.grad_accum,
            "grad_sync": self.grad_sync,
            "pipeline_depth": self.pipeline_depth,
            "donate": self._donate,
            "nan_check": self._nan_check,
            "param_sharding": self._param_sharding is not None,
            "host_step": self._host_step,
            "mesh_axes": dict(zip(mesh.axis_names, mesh.devices.shape)),
            "device_count": jax.device_count(),
            "device_kind": jax.devices()[0].device_kind,
            "backend": jax.default_backend(),
            "jax_version": jax.__version__,
            "env": {k: v for k, v in os.environ.items()
                    if k.startswith(("JAX_", "XLA_", "TPU_", "LIBTPU"))},
        }

    # -- setup ---------------------------------------------------------------

    @staticmethod
    def _default_forward(model, variables, batch, train, rngs):
        if train:
            out, new = model.apply(variables, batch["x"], train=True,
                                   mutable=("state",), rngs=rngs)
            return out, new["state"]
        return model.apply(variables, batch["x"]), variables["state"]

    def init(self, rng, sample_batch: Dict[str, Any]) -> TrainState:
        """Initialize params/state/optimizer from one (host) batch. Models with
        non-standard inputs (custom ``forward=`` arg) implement
        ``init_variables(rng, batch)``."""
        batch = jax.tree_util.tree_map(jnp.asarray, sample_batch)
        if self._param_sharding is not None:
            from ..parallel import sharding as shard_lib
            if hasattr(self.model, "init_variables"):
                variables = self.model.init_variables(rng, batch)
                specs = self._param_sharding
                if isinstance(specs, shard_lib.ShardingRules):
                    specs = specs(variables["params"])
                params = shard_lib.shard_tree(self.mesh, variables["params"],
                                              specs)
                state = shard_lib.shard_tree(self.mesh,
                                             variables.get("state", {}))
            else:
                # Materialize params directly in their sharded layout — no
                # full replicated copy on one device first.
                variables, specs = shard_lib.sharded_init(
                    self.model, rng, batch["x"], mesh=self.mesh,
                    rules=self._param_sharding, train=True)
                params = variables["params"]
                state = variables.get("state", {})
            self._param_specs = specs
        else:
            if hasattr(self.model, "init_variables"):
                variables = self.model.init_variables(rng, batch)
            else:
                variables = self.model.init(rng, batch["x"], train=True)
            params = variables["params"]
            state = variables.get("state", {})
        # Param-shaped optimizer slots inherit each param's committed layout:
        # eager zeros_like/ops on sharded arrays propagate sharding (under
        # jit they would be value-independent constants and land on one
        # device).
        opt_state = self.optimizer.init(params)
        self.train_state = TrainState(params, state, opt_state,
                                      jnp.zeros((), jnp.int32))
        return self.train_state

    # -- explicit gradient sync (ISSUE 8) ------------------------------------

    def _resolve_grad_sync(self) -> Optional[str]:
        """Resolve the requested ``grad_sync`` mode against the mesh and
        committed param layout; on degrade, warn ONCE and return None
        (the implicit partitioner sync — never crash a config that
        trains fine without the overlap)."""
        from ..parallel import overlap as overlap_lib
        mode, reason = overlap_lib.resolve_grad_sync(
            self.grad_sync, self.mesh, mesh_lib.DATA_AXIS,
            self._param_specs)
        if self.grad_sync is not None and mode is None and \
                not self._grad_sync_warned:
            self._grad_sync_warned = True
            _log.warning(
                "grad_sync=%r requested but cannot engage: %s — degrading "
                "to the implicit partitioner gradient sync (no-op marker)",
                self.grad_sync, reason)
        return mode

    def _make_synced_grads(self, mode: str):
        """Build the explicit-sync gradient path for one microbatch
        (:mod:`paddle_tpu.parallel.overlap`): the forward+backward runs in
        a ``shard_map`` manual over the dp axis (all other mesh axes stay
        GSPMD-auto, so tensor-parallel ``param_sharding`` composes), each
        device differentiates its LOCAL loss sum, and the only dp
        gradient communication is ours — one flat psum per bucket,
        anchored in the backward by the ``sync_tangent`` markers.

        Returns ``(grads_fn, accum_sync)``:

        - ``grads_fn(params, state, mb, rngs, sync_now)`` matches
          ``microbatch_grads``' return contract
          ``((loss, (new_state, out)), grads)``. ``sync_now=True`` (the
          ``M == 1`` path) applies the bucket markers — grads leave the
          region globally reduced, with each bucket's all-reduce placed
          as-you-go inside the backward. ``sync_now=False`` (the
          accumulation path) returns LOCAL per-device grads.
        - ``accum_sync(grads)`` bucket-syncs an accumulated local
          gradient tree — called once per optimizer step after the
          microbatch scan, never per microbatch.

        The loss is the same weight-normalized global mean as the
        implicit path: local (weighted) sums are psum'd and divided by
        the global weight/count, and the gradient is post-scaled by the
        same denominator — mathematically the mean's gradient, with
        bucketed-vs-fused bit-exactness guaranteed by construction (the
        two modes differ only in all-reduce granularity, and all-reduce
        is an elementwise sum)."""
        from ..parallel import overlap as overlap_lib
        assert self.train_state is not None, "call init() first"
        mesh = self.mesh
        axis = mesh_lib.DATA_AXIS
        model, loss_fn, forward = self.model, self.loss_fn, self._forward
        auto = frozenset(set(mesh.axis_names) - {axis})
        params0 = self.train_state.params
        if jax.tree_util.tree_leaves(self.train_state.state) and \
                not self._state_sync_warned:
            self._state_sync_warned = True
            _log.warning(
                "grad_sync=%r runs the forward per dp shard: module-state "
                "updates (e.g. BN running stats) use each device's LOCAL "
                "batch statistics (torch-DDP semantics), not global-batch "
                "statistics", mode)
        # The in-scan protocol: a model may declare param paths it syncs
        # per-layer inside its scan-over-layers stack (the remat'd
        # transformer) — those leaves leave the top-level buckets, and the
        # scan hook engages only on the sync-now path (accumulation syncs
        # once per step, so in-scan per-microbatch psums must stay off).
        scan_paths: tuple = ()
        if mode == "bucketed":
            hook = getattr(model, "grad_sync_scan_paths", None)
            if callable(hook):
                scan_paths = tuple(hook() or ())
        # "fused" = one bucket (per dtype — flat buffers cannot mix)
        budget = self.bucket_mb if mode == "bucketed" else 1e9
        buckets_now = overlap_lib.partition_buckets(
            params0, budget, exclude=scan_paths)
        buckets_accum = overlap_lib.partition_buckets(params0, budget)
        in_scan = bool(scan_paths)

        def _sm(fn, in_specs, out_specs):
            kw = {"auto": auto} if auto else {}
            return overlap_lib.shard_map_compat(
                fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                **kw)

        def batch_spec(x):
            return P() if np.ndim(x) == 0 else P(mesh_lib.DATA_AXIS)

        repl_of = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)

        def grads_fn(params, state, mb, rngs, sync_now: bool):
            weighted = mb.get("weight") is not None

            def per_device(params, state, mb, rngs):
                # per-shard rng: fold the dp coordinate into every key so
                # shards draw INDEPENDENT dropout masks (a replicated key
                # would give every shard the same mask over its local
                # batch — an undisclosed 1/dp cut in mask diversity)
                rngs = jax.tree_util.tree_map(
                    lambda k: jax.random.fold_in(k, lax.axis_index(axis)),
                    rngs)

                def local_loss(p):
                    if sync_now:
                        p = overlap_lib.mark_buckets(p, buckets_now, axis)
                    with overlap_lib.scan_sync_scope(
                            axis if (sync_now and in_scan) else None):
                        out, new_state = forward(
                            model, {"params": p, "state": state}, mb,
                            True, rngs)
                    per_ex = loss_fn(out, mb)
                    w = mb.get("weight")
                    if w is not None:
                        lsum = jnp.sum(per_ex * w)
                        denom_local = jnp.sum(w)
                    else:
                        lsum = jnp.sum(per_ex)
                        # static local element count: psum'd into the
                        # exact global count the implicit mean divides by
                        denom_local = jnp.asarray(per_ex.size, jnp.float32)
                    return lsum, (new_state, out, denom_local)

                (lsum, (new_state, out, denom_local)), grads = \
                    jax.value_and_grad(local_loss, has_aux=True)(params)
                tot = lax.psum(
                    jnp.stack([lsum.astype(jnp.float32),
                               denom_local.astype(jnp.float32)]), axis)
                denom = (jnp.maximum(tot[1], 1e-9) if weighted else tot[1])
                loss = tot[0] / denom
                grads = jax.tree_util.tree_map(
                    lambda g: g / denom.astype(g.dtype), grads)
                return loss, new_state, out, grads

            # structure-only abstract pass: out_specs need the forward's
            # output tree (batch-led leaves rejoin the global batch
            # layout — the same batch-led contract shard_batch applies
            # to inputs; a non-divisible leading dim gets an actionable
            # error instead of shard_map's shape mismatch)
            out_s, state_s = jax.eval_shape(
                lambda p, s, b, r: forward(
                    model, {"params": p, "state": s}, b, True, r),
                params, state, mb, rngs)
            dp = dict(zip(mesh.axis_names,
                          mesh.devices.shape))[mesh_lib.DATA_AXIS]

            def out_spec(s):
                if not getattr(s, "ndim", 0):
                    return P()
                if s.shape[0] % dp:
                    raise ValueError(
                        f"grad_sync={mode!r} requires batch-led forward "
                        f"outputs: got an output leaf of shape {s.shape} "
                        f"whose leading dim does not divide the dp axis "
                        f"size {dp} (use grad_sync=None for this model, "
                        f"or make its outputs batch-led)")
                return P(mesh_lib.DATA_AXIS)

            sm = _sm(
                per_device,
                in_specs=(repl_of(params), repl_of(state),
                          jax.tree_util.tree_map(batch_spec, mb),
                          repl_of(rngs)),
                out_specs=(P(), repl_of(state_s),
                           jax.tree_util.tree_map(out_spec, out_s),
                           repl_of(params)))
            loss, new_state, out, grads = sm(params, state, mb, rngs)
            return (loss, (new_state, out)), grads

        def accum_sync(grads):
            def per_device(gs):
                return overlap_lib.apply_bucket_sync(gs, buckets_accum,
                                                     axis)
            return _sm(per_device, in_specs=(repl_of(grads),),
                       out_specs=repl_of(grads))(grads)

        return grads_fn, accum_sync

    # -- compiled steps ------------------------------------------------------

    def _make_step_fn(self, accum_axis: bool):
        """Build the one-optimizer-step function shared by the plain and
        fused paths.

        ``accum_axis=False``: ``batch`` is a single microbatch pytree — the
        plain step body, math unchanged from the single-dispatch trainer.

        ``accum_axis=True``: ``batch`` leaves carry a leading ``[M, ...]``
        microbatch axis. ``M == 1`` squeezes the axis and runs the identical
        plain body (so ``steps_per_call``-only fusion is bit-for-bit the
        plain step). ``M > 1`` runs a donated-accumulator ``lax.scan`` over
        the M microbatches: each microbatch's loss is its own weight-
        normalized mean, loss/grads are the mean of the M microbatch means
        (mean-of-means — mask/weight-correct within each microbatch), the
        module state threads sequentially, and the optimizer update fires
        once on the accumulated gradient.

        With health telemetry on, the step returns a 7th element: the
        per-step health-scalar dict (obs.health.health_scalars) — a few
        fused reduces over grads/updates/params that XLA folds into the
        step program, so monitoring never adds a dispatch."""
        health_on = self._health_on()
        opt = self.optimizer
        model = self.model
        loss_fn = self.loss_fn
        forward = self._forward
        evaluator = self.evaluator

        def microbatch_grads(params, state, mb, rngs):
            def compute_loss(p):
                out, new_state = forward(model, {"params": p, "state": state},
                                         mb, True, rngs)
                per_ex = loss_fn(out, mb)
                w = mb.get("weight")
                if w is not None:
                    loss = jnp.sum(per_ex * w) / jnp.maximum(jnp.sum(w), 1e-9)
                else:
                    loss = jnp.mean(per_ex)
                return loss, (new_state, out)

            return jax.value_and_grad(compute_loss, has_aux=True)(params)

        # Explicit dp gradient sync (ISSUE 8): swap the implicit-GSPMD
        # microbatch_grads for the manual-dp bucketed/fused path. With
        # grad_accum the markers stay OFF per microbatch (sync_now=False:
        # local grads accumulate) and accum_sync fires once per step.
        sync_mode = self._resolve_grad_sync()
        synced_grads = accum_sync = None
        if sync_mode is not None:
            synced_grads, accum_sync = self._make_synced_grads(sync_mode)

        def grads_of(params, state, mb, rngs, sync_now):
            if synced_grads is not None:
                return synced_grads(params, state, mb, rngs, sync_now)
            return microbatch_grads(params, state, mb, rngs)

        def step_fn(params, state, opt_state, step, batch, rng):
            M = (jax.tree_util.tree_leaves(batch)[0].shape[0]
                 if accum_axis else 1)
            if accum_axis and M == 1:
                batch = jax.tree_util.tree_map(lambda x: x[0], batch)
            if M == 1:
                rngs = {"dropout": jax.random.fold_in(rng, step)}
                (loss, (new_state, out)), grads = grads_of(
                    params, state, batch, rngs, True)
                stats = (evaluator.batch_stats(out, batch)
                         if evaluator is not None else {})
            else:
                step_key = jax.random.fold_in(rng, step)

                def micro(carry, xs):
                    st, gacc, lacc = carry
                    mb, midx = xs
                    rngs = {"dropout": jax.random.fold_in(step_key, midx)}
                    (l, (new_st, out)), g = grads_of(
                        params, st, mb, rngs, False)
                    s = (evaluator.batch_stats(out, mb)
                         if evaluator is not None else {})
                    gacc = jax.tree_util.tree_map(jnp.add, gacc, g)
                    return (new_st, gacc, lacc + l), s

                g0 = jax.tree_util.tree_map(jnp.zeros_like, params)
                (new_state, gacc, lacc), stats = lax.scan(
                    micro, (state, g0, jnp.zeros((), jnp.float32)),
                    (batch, jnp.arange(M)))
                grads = jax.tree_util.tree_map(lambda g: g / M, gacc)
                loss = lacc / M
                if accum_sync is not None:
                    # sync the ACCUMULATED gradient once per optimizer
                    # step — never per microbatch (the microbatch grads
                    # above were local per-device sums)
                    grads = accum_sync(grads)
            updates, new_opt = opt.update(grads, opt_state, params, step)
            new_params = apply_updates(params, updates)
            if health_on:
                from ..obs.health import health_scalars
                health = health_scalars(grads, updates, new_params, loss)
                return (new_params, new_state, new_opt, step + 1, loss,
                        stats, health)
            return new_params, new_state, new_opt, step + 1, loss, stats

        return step_fn

    def _build_train_step(self):
        step_fn = self._make_step_fn(accum_axis=False)
        # Shardings: batch sharded over the data axis, params replicated
        # (default) or committed to the user's model-parallel layout at
        # init — in that case shardings are taken from the committed inputs
        # and SPMD propagation lays out the rest. XLA inserts the gradient
        # all-reduce over ICI — the entire pserver tier collapses here.
        mesh = self.mesh
        donate = (0, 1, 2) if self._donate else ()
        if self._param_sharding is None:
            repl = NamedSharding(mesh, P())
            data = NamedSharding(mesh, P(mesh_lib.DATA_AXIS))
            self._train_step = jax.jit(
                step_fn,
                in_shardings=(repl, repl, repl, repl, data, repl),
                donate_argnums=donate)
        else:
            self._train_step = jax.jit(step_fn, donate_argnums=donate)

    def _build_fused_step(self, sample_batches):
        """The fused hot loop: ONE jit-compiled dispatch = a donated
        ``lax.scan`` over K optimizer steps (each itself scanning M
        microbatches when ``grad_accum > 1``). ``sample_batches`` (host
        leaves ``[K, M, batch, ...]``) fixes the batch-tree structure for the
        per-leaf data shardings; distinct (K, M) tail shapes retrace through
        the same jit cache. Returns the stacked per-step losses ``[K]`` and
        evaluator stats with leading ``[K, M]`` (``[K]`` when the microbatch
        axis was squeezed)."""
        step_fn = self._make_step_fn(accum_axis=True)
        mesh = self.mesh

        def fused_fn(params, state, opt_state, step, batches, rng):
            def body(carry, kbatch):
                p, st, o, s = carry
                out = step_fn(p, st, o, s, kbatch, rng)
                # ys = (loss, stats) or (loss, stats, health) — the scan
                # stacks each over the K steps
                return out[:4], out[4:]

            (params, state, opt_state, step), ys = lax.scan(
                body, (params, state, opt_state, step), batches)
            return (params, state, opt_state, step) + tuple(ys)

        donate = (0, 1, 2) if self._donate else ()
        if self._param_sharding is None:
            repl = NamedSharding(mesh, P())
            bshard = jax.tree_util.tree_map(self._fused_leaf_sharding,
                                            sample_batches)
            self._fused_step = jax.jit(
                fused_fn,
                in_shardings=(repl, repl, repl, repl, bshard, repl),
                donate_argnums=donate)
        else:
            self._fused_step = jax.jit(fused_fn, donate_argnums=donate)

    def _build_eval_step(self):
        model = self.model
        loss_fn = self.loss_fn
        forward = self._forward
        evaluator = self.evaluator

        def eval_fn(params, state, batch):
            out, _ = forward(model, {"params": params, "state": state},
                             batch, False, None)
            per_ex = loss_fn(out, batch)
            stats = (evaluator.batch_stats(out, batch)
                     if evaluator is not None else {})
            return jnp.mean(per_ex), stats

        self._eval_step = jax.jit(eval_fn)

    # -- loops ---------------------------------------------------------------

    def _shard(self, host_batch):
        return mesh_lib.shard_batch(self.mesh, host_batch)

    def train(self, reader: Callable, num_passes: int = 1,
              event_handler: Optional[Callable] = None,
              test_reader: Optional[Callable] = None,
              checkpoint_dir: Optional[str] = None,
              checkpoint_keep: int = 3,
              checkpoint_async: bool = False,
              saving_period: Optional[int] = None,
              log_period: int = 100, rng: Optional[jax.Array] = None,
              resume: bool = False) -> TrainState:
        """The pass/batch loop (v2 ``SGD.train`` surface + v1 pass checkpoints).

        ``checkpoint_async=True`` moves each checkpoint's CRC + disk write
        to a background thread (the device-state snapshot stays on the hot
        path; see :class:`~paddle_tpu.train.checkpoint.AsyncCheckpointer`) —
        the analog of the reference's off-critical-path checkpoint/commit
        work. The final save is fenced before ``train`` returns.

        ``saving_period``: also checkpoint every N batches *within* a pass
        (the reference's ``--saving_period_by_batches``,
        ``trainer/Trainer.cpp``), recording the data-iterator position.
        ``resume=True`` then continues mid-pass: with a deterministic
        ``reader`` the already-consumed batches of the interrupted pass are
        skipped, reproducing the uninterrupted run (the Go master's
        task-queue recovery, ``go/master/service.go:313``, done the
        single-controller way — deterministic data + iterator state in the
        checkpoint). Evaluator state is not checkpointed, so the resumed
        pass's metrics cover only its remaining batches.
        """
        assert self.train_state is not None, "call init() first"
        # a stop request is scoped to ONE train() call: a prior run's
        # consumed-or-unconsumed flag must not instantly preempt this one
        # (the handler can re-request once this run is live)
        self._stop_requested = None
        if self.anomaly is not None:
            # the flight recorder needs the trace ring and a lazy
            # config/env/mesh snapshot source for its bundles
            self.anomaly.bind(tracer=self.tracer,
                              context_fn=self._anomaly_context)
        fused = self.steps_per_call > 1 or self.grad_accum > 1
        if not fused and self._train_step is None:
            self._build_train_step()    # fused step builds lazily per group
        handler = event_handler or (lambda e: None)
        rng = rng if rng is not None else jax.random.PRNGKey(0)

        start_pass, skip_batches = 0, 0
        if resume and checkpoint_dir:
            if ckpt_lib.latest_pass(checkpoint_dir) is not None:
                try:
                    # latest VALID pass: poisoned dirs are quarantined
                    # (renamed .corrupt, never deleted) and the chain
                    # falls back one pass — resume survives a corrupt
                    # latest checkpoint instead of dying on its CRC
                    self.restore(checkpoint_dir)
                except FileNotFoundError as e:
                    self.last_quarantined = list(
                        getattr(e, "quarantined", []))
                    _log.warning(
                        "resume: no readable checkpoint remains under %s "
                        "after quarantine — starting from scratch",
                        checkpoint_dir)
                else:
                    last = self._last_restored_pass
                    it = self._last_iter_state
                    if it is not None and not int(it.get("completed", 1)):
                        start_pass = int(it["pass"])
                        skip_batches = int(it["next_batch"])
                    else:
                        start_pass = last + 1

        saver = None
        if checkpoint_async:
            saver = ckpt_lib.AsyncCheckpointer(telemetry=self.telemetry,
                                               faults=self.faults)
            save_fn = saver.save
        elif self.faults is not None:
            import functools
            save_fn = functools.partial(ckpt_lib.save_checkpoint,
                                        faults=self.faults)
        else:
            save_fn = ckpt_lib.save_checkpoint
        try:
            return self._train_loop(reader, num_passes, handler, test_reader,
                                    checkpoint_dir, checkpoint_keep,
                                    saving_period, log_period, rng,
                                    start_pass, skip_batches, save_fn)
        finally:
            if saver is not None:
                saver.close()          # fence the in-flight write

    def _train_loop(self, reader, num_passes, handler, test_reader,
                    checkpoint_dir, checkpoint_keep, saving_period,
                    log_period, rng, start_pass, skip_batches, save_fn):
        fused = self.steps_per_call > 1 or self.grad_accum > 1
        tel = self.telemetry
        for pass_id in range(start_pass, num_passes):
            handler(ev.BeginPass(pass_id))
            if tel is not None:
                tel.begin_pass(pass_id)   # reset the per-pass memory peak
            if self.evaluator is not None:
                self.evaluator.reset()
            # re-anchor the host-side step mirror (train_state is quiesced
            # at pass boundaries: serial mode syncs per group, pipelined
            # mode drained at the previous pass end)
            self._host_step = int(jax.device_get(self.train_state.step))
            costs = []
            pipe = None
            if fused and self.pipeline_depth > 1:
                from .host_pipeline import FusedPipeline
                pipe = FusedPipeline(
                    self, pass_id, rng, handler, costs, log_period,
                    saving_period, checkpoint_dir, checkpoint_keep, save_fn,
                    depth=self.pipeline_depth)
            try:
                self._run_pass(
                    reader, pass_id, start_pass, skip_batches, pipe,
                    handler, costs, log_period, saving_period,
                    checkpoint_dir, checkpoint_keep, save_fn, rng)
            finally:
                if pipe is not None:
                    pipe.close()
            pass_metrics = (self.evaluator.result()
                            if self.evaluator is not None else {})
            pass_metrics["mean_cost"] = float(np.mean(costs)) if costs else 0.0
            if test_reader is not None:
                tc, tm = self.evaluate(test_reader)
                pass_metrics.update({f"test_{k}": v for k, v in tm.items()})
                pass_metrics["test_cost"] = tc
            if checkpoint_dir:
                with tspan(self.tracer, "checkpoint_save", pass_end=pass_id):
                    save_fn(
                        checkpoint_dir, pass_id,
                        {**self.train_state.as_dict(),
                         "iter": {"pass": pass_id, "next_batch": 0,
                                  "completed": 1}},
                        keep_last=checkpoint_keep)
            handler(ev.EndPass(pass_id, pass_metrics))
            if self._stop_requested is not None:
                # a stop that arrived too late for a group boundary (or
                # during eval / the pass-end save) exits here: the pass
                # checkpoint above already recorded completed=1, so the
                # resume position is the next pass's first batch
                raise Preempted(pass_id=pass_id + 1, next_batch=0,
                                reason=self._stop_requested)
        return self.train_state

    def _run_pass(self, reader, pass_id, start_pass, skip_batches, pipe,
                  handler, costs, log_period, saving_period, checkpoint_dir,
                  checkpoint_keep, save_fn, rng):
        """One pass's batch loop (split out of ``_train_loop`` so the fused
        pipeline's stager thread is always closed via try/finally). The
        serial paths are byte-identical to the pre-pipeline loop."""
        tel = self.telemetry
        fused = self.steps_per_call > 1 or self.grad_accum > 1
        group = self.steps_per_call * self.grad_accum
        # The plain loop defers its loss fetch only with nan_check off: the
        # finiteness trap's contract is raise-before-the-next-dispatch.
        plain_deferred = (not fused and self.pipeline_depth > 1
                          and not self._nan_check)
        ts = self.train_state
        params, state, opt_state, step = (ts.params, ts.state, ts.opt_state,
                                          ts.step)
        buf, buf_start = [], 0
        pending = []              # plain deferred-fetch in-flight window
        for batch_id, host_batch in enumerate(reader()):
            if pass_id == start_pass and batch_id < skip_batches:
                # Deterministic replay skip on resume. On the last
                # skipped batch, compare against the fingerprint the
                # checkpoint recorded for it — a mismatch means the
                # reader is not deterministic and the resumed pass
                # would train on a different batch remainder.
                if batch_id == skip_batches - 1:
                    want = (self._last_iter_state or {}).get("batch_crc")
                    if want is not None and \
                            _batch_fingerprint(host_batch) != int(want):
                        _log.warning(
                            "resume: reader replay diverged from the "
                            "checkpointed batch fingerprint at batch %d "
                            "— the reader is nondeterministic (shuffle/"
                            "buffered?); the resumed pass trains on a "
                            "different batch remainder than the "
                            "interrupted run", batch_id)
                continue
            if fused:
                # Buffer K*M host batches, then ONE device dispatch for
                # K optimizer steps; host bookkeeping replays after. A
                # shape change mid-group (ragged final reader batch)
                # flushes the buffer early — groups must stack. With a
                # pipe (pipeline_depth > 1) the group goes to the stager
                # thread instead of being stacked/dispatched serially.
                if buf and _batch_shapes(host_batch) != \
                        _batch_shapes(buf[0]):
                    if pipe is not None:
                        pipe.submit(buf, buf_start)
                    else:
                        self._run_fused_group(
                            buf, buf_start, pass_id, rng, handler, costs,
                            log_period, saving_period, checkpoint_dir,
                            checkpoint_keep, save_fn)
                    buf = []
                if not buf:
                    buf_start = batch_id
                buf.append(host_batch)
                if len(buf) == group:
                    if pipe is not None:
                        pipe.submit(buf, buf_start)
                    else:
                        self._run_fused_group(
                            buf, buf_start, pass_id, rng, handler, costs,
                            log_period, saving_period, checkpoint_dir,
                            checkpoint_keep, save_fn)
                    buf = []
                    # graceful stop lands on exactly this boundary: the
                    # group's batches are all dispatched (buf empty), so
                    # after the drain inside _maybe_stop the state is
                    # quiesced at batch_id + 1 consumed batches
                    self._maybe_stop(pipe, pending, pass_id, batch_id + 1,
                                     handler, costs, log_period,
                                     checkpoint_dir, checkpoint_keep,
                                     save_fn, last_batch=host_batch)
                continue
            if plain_deferred:
                # The plain loop's deferred-fetch window: dispatch now,
                # replay the host bookkeeping (Begin/EndIteration both —
                # like fused mode) when the window drains. nan_check off
                # by construction. Make room BEFORE dispatching (like
                # FusedPipeline) so at most pipeline_depth calls are ever
                # in flight.
                while len(pending) >= self.pipeline_depth:
                    self._replay_plain(
                        pending.pop(0), pass_id, handler, costs,
                        log_period, checkpoint_dir, checkpoint_keep,
                        save_fn)
                params, state, opt_state, step = self._plain_dispatch(
                    host_batch, pass_id, batch_id, params, state,
                    opt_state, step, rng, tel, pending, saving_period,
                    checkpoint_dir)
                if pending[-1]["boundary"]:
                    # checkpoint boundary: the save needs train_state
                    # quiesced at exactly this batch — drain everything
                    # before the next dispatch advances it
                    while pending:
                        self._replay_plain(
                            pending.pop(0), pass_id, handler, costs,
                            log_period, checkpoint_dir, checkpoint_keep,
                            save_fn)
                self._maybe_stop(None, pending, pass_id, batch_id + 1,
                                 handler, costs, log_period,
                                 checkpoint_dir, checkpoint_keep, save_fn,
                                 last_batch=host_batch)
                continue
            # SERIAL plain step. _plain_dispatch/_replay_plain mirror this
            # body for the deferred-fetch window (divergences are the
            # point: BeginIteration pre-dispatch here, the per-call fence,
            # int(step) fetches) — a bookkeeping change here must be
            # mirrored there.
            handler(ev.BeginIteration(pass_id, batch_id))
            is_new, fp = False, None
            if tel is not None:
                fp = ((1, 1),) + _step_fingerprint(host_batch)
                is_new = tel.observe_fingerprint(fp)
            t0 = time.perf_counter()
            with self.stats.time("shard_batch"), \
                    tspan(self.tracer, "device_put", batch=batch_id):
                batch = self._shard(host_batch)
            t1 = time.perf_counter()
            hlo_flops = None
            if is_new:
                from ..obs.telemetry import lowered_hlo_flops
                try:
                    hlo_flops = lowered_hlo_flops(self._train_step.lower(
                        params, state, opt_state, step, batch, rng))
                except Exception:
                    hlo_flops = None
            # dispatch timing starts AFTER the FLOPs lowering — the
            # measurement layer must not bill its own extra trace to
            # the step it measures (the fused path does the same)
            t_disp = time.perf_counter()
            with self.stats.time("train_step"), \
                    tspan(self.tracer, "dispatch", batch=batch_id,
                          new_compile=is_new):
                out, profiled = self._maybe_profiled_call(
                    self._train_step, params, state, opt_state, step,
                    batch, rng)
            params, state, opt_state, step = out[:4]
            loss, stats = out[4], out[5]
            health = out[6] if len(out) > 6 else None
            t2 = time.perf_counter()
            device_s = None
            if tel is not None and tel.fence:
                # the fencing rule: the dispatch above returned as soon
                # as the program was enqueued — device time needs a sync
                with tspan(self.tracer, "fence", batch=batch_id):
                    jax.block_until_ready((params, loss))
                device_s = time.perf_counter() - t2
                self.stats.add("device_wait", device_s)
            if is_new:
                tel.record_compile(
                    fp, wall_s=(t2 - t_disp) + (device_s or 0.0),
                    hlo_flops=hlo_flops, meta={"k_steps": 1, "m": 1})
            # Refresh train_state every step: with buffer donation the
            # previous arrays are invalidated, and event handlers may read
            # trainer.train_state (e.g. to save) mid-pass.
            self.train_state = TrainState(params, state, opt_state, step)
            self._host_step += 1
            cost = float(loss)
            if tel is not None:
                if health is not None:
                    tel.update_health(jax.device_get(health))
                rec = tel.emit_step(
                    {"pass": pass_id, "step": int(step),
                     "k_steps": 1, "m": 1, "loss": cost,
                     "profiled": profiled,
                     "host_stack_ms": None,
                     "shard_ms": round((t1 - t0) * 1e3, 3),
                     "dispatch_ms": round((t2 - t_disp) * 1e3, 3),
                     "device_ms": (round(device_s * 1e3, 3)
                                   if device_s is not None else None),
                     "replay_ms": None})
                handler(ev.TelemetryRecord(record=rec))
                self._anomaly_observe(rec)
            if self._nan_check and not np.isfinite(cost):
                from ..utils import debug as dbg
                bad = dbg.nonfinite_leaves(
                    {"params": params, "state": state})
                raise FloatingPointError(
                    f"non-finite loss {cost} at pass {pass_id} batch "
                    f"{batch_id} (step {int(step)}); non-finite leaves: "
                    f"{bad[:8] or 'none (loss only)'}")
            costs.append(cost)
            metrics = {}
            if self.evaluator is not None:
                self.evaluator.update(jax.device_get(stats))
                metrics = self.evaluator.result()
            if log_period and (batch_id + 1) % log_period == 0:
                msg = " ".join(f"{k}={v:.4f}" for k, v in metrics.items())
                if tel is not None and tel.last_health:
                    # health monitors are fetched per call (riding the
                    # same sync as the loss) but LOGGED only here
                    msg += " " + " ".join(
                        f"{k}={v:.3g}"
                        for k, v in tel.last_health.items())
                _log.info("pass %d batch %d cost=%.4f %s",
                          pass_id, batch_id + 1, cost, msg)
                self._log_stat_report()
            if self._param_stats_period and \
                    (batch_id + 1) % self._param_stats_period == 0:
                self._log_param_stats(pass_id, batch_id)
            if saving_period and checkpoint_dir and \
                    (batch_id + 1) % saving_period == 0:
                with tspan(self.tracer, "checkpoint_save",
                           next_batch=batch_id + 1):
                    save_fn(
                        checkpoint_dir, pass_id,
                        {**self.train_state.as_dict(),
                         "iter": {"pass": pass_id,
                                  "next_batch": batch_id + 1,
                                  "completed": 0,
                                  "batch_crc":
                                      _batch_fingerprint(host_batch)}},
                        keep_last=checkpoint_keep)
            handler(ev.EndIteration(pass_id, batch_id, int(step), cost,
                                    metrics))
            if self.faults is not None:
                self._fire_step_faults(self._host_step)
            self._maybe_stop(None, pending, pass_id, batch_id + 1, handler,
                             costs, log_period, checkpoint_dir,
                             checkpoint_keep, save_fn,
                             last_batch=host_batch)
        if fused and buf:
            # Pass tail smaller than K*M: flush what's buffered (the
            # final optimizer step may accumulate < M microbatches;
            # its loss/grads average over the actual count).
            if pipe is not None:
                pipe.submit(buf, buf_start)
            else:
                self._run_fused_group(
                    buf, buf_start, pass_id, rng, handler, costs,
                    log_period, saving_period, checkpoint_dir,
                    checkpoint_keep, save_fn)
        if pipe is not None:
            pipe.flush()          # pass end drains the whole window (FIFO)
        while pending:
            self._replay_plain(pending.pop(0), pass_id, handler, costs,
                               log_period, checkpoint_dir, checkpoint_keep,
                               save_fn)

    # -- plain deferred-fetch (pipeline_depth > 1, K=1, M=1) -----------------

    def _plain_dispatch(self, host_batch, pass_id, batch_id, params, state,
                        opt_state, step, rng, tel, pending, saving_period,
                        checkpoint_dir):
        """Dispatch ONE plain step without fetching anything; append a
        pending entry for the deferred replay. Returns the new device-side
        carry. No per-call fence even with telemetry on (it would serialize
        the window); the drain records ``drain_wait_ms``.

        This + ``_replay_plain`` mirror the SERIAL plain body in
        ``_run_pass`` minus every host sync (fence, ``float(loss)``,
        ``int(step)`` — replaced by the ``_host_step`` mirror) — keep the
        bookkeeping in lockstep when editing either."""
        is_new, fp = False, None
        if tel is not None:
            fp = ((1, 1),) + _step_fingerprint(host_batch)
            is_new = tel.observe_fingerprint(fp)
        t0 = time.perf_counter()
        with self.stats.time("shard_batch"), \
                tspan(self.tracer, "device_put", batch=batch_id):
            batch = self._shard(host_batch)
        t1 = time.perf_counter()
        hlo_flops = None
        if is_new:
            from ..obs.telemetry import lowered_hlo_flops
            try:
                hlo_flops = lowered_hlo_flops(self._train_step.lower(
                    params, state, opt_state, step, batch, rng))
            except Exception:
                hlo_flops = None
        t_disp = time.perf_counter()
        with self.stats.time("train_step"), \
                tspan(self.tracer, "dispatch", batch=batch_id,
                      new_compile=is_new):
            out, profiled = self._maybe_profiled_call(
                self._train_step, params, state, opt_state, step, batch,
                rng)
        params, state, opt_state, step = out[:4]
        t2 = time.perf_counter()
        if is_new:
            tel.record_compile(fp, wall_s=t2 - t_disp, hlo_flops=hlo_flops,
                               meta={"k_steps": 1, "m": 1})
        self.train_state = TrainState(params, state, opt_state, step)
        self._host_step += 1
        boundary = bool(saving_period and checkpoint_dir
                        and (batch_id + 1) % saving_period == 0)
        rec = None
        if tel is not None:
            rec = {"pass": pass_id, "step": self._host_step,
                   "k_steps": 1, "m": 1, "profiled": profiled,
                   "host_stack_ms": None,
                   "shard_ms": round((t1 - t0) * 1e3, 3),
                   "dispatch_ms": round((t2 - t_disp) * 1e3, 3),
                   "device_ms": None, "replay_ms": None}
        pending.append({
            "batch_id": batch_id, "step": self._host_step,
            "loss": out[4], "stats": out[5],
            "health": out[6] if len(out) > 6 else None,
            "rec": rec, "boundary": boundary,
            "crc": _batch_fingerprint(host_batch) if boundary else None})
        return params, state, opt_state, step

    def _replay_plain(self, entry, pass_id, handler, costs, log_period,
                      checkpoint_dir, checkpoint_keep, save_fn):
        """Deferred host bookkeeping for one plain step, replayed at drain
        in dispatch order — the plain-loop analog of ``_post_fused``
        (Begin/EndIteration both fire here, post-dispatch, like fused
        mode; the observable event sequence matches the serial loop
        exactly). ``nan_check`` is off by construction on this path."""
        tel = self.telemetry
        batch_id = entry["batch_id"]
        handler(ev.BeginIteration(pass_id, batch_id))
        t0 = time.perf_counter()
        with tspan(self.tracer, "drain_wait", batch=batch_id):
            cost = float(np.asarray(jax.device_get(entry["loss"])))
        drain_wait = time.perf_counter() - t0
        self.stats.add("drain_wait", drain_wait)
        if tel is not None:
            if entry["health"] is not None:
                tel.update_health(jax.device_get(entry["health"]))
            rec = entry["rec"]
            rec["loss"] = cost
            rec["drain_wait_ms"] = round(drain_wait * 1e3, 3)
            rec = tel.emit_step(rec)
            handler(ev.TelemetryRecord(record=rec))
            self._anomaly_observe(rec)
        costs.append(cost)
        metrics = {}
        if self.evaluator is not None:
            self.evaluator.update(jax.device_get(entry["stats"]))
            metrics = self.evaluator.result()
        if log_period and (batch_id + 1) % log_period == 0:
            msg = " ".join(f"{k}={v:.4f}" for k, v in metrics.items())
            if tel is not None and tel.last_health:
                msg += " " + " ".join(f"{k}={v:.3g}"
                                      for k, v in tel.last_health.items())
            _log.info("pass %d batch %d cost=%.4f %s",
                      pass_id, batch_id + 1, cost, msg)
            self._log_stat_report()
        if self._param_stats_period and \
                (batch_id + 1) % self._param_stats_period == 0:
            self._log_param_stats(pass_id, batch_id)
        if entry["boundary"]:
            # the boundary forced a full drain right after this batch's
            # dispatch, so train_state is quiesced at exactly this step
            with tspan(self.tracer, "checkpoint_save",
                       next_batch=batch_id + 1):
                save_fn(
                    checkpoint_dir, pass_id,
                    {**self.train_state.as_dict(),
                     "iter": {"pass": pass_id, "next_batch": batch_id + 1,
                              "completed": 0, "batch_crc": entry["crc"]}},
                    keep_last=checkpoint_keep)
        handler(ev.EndIteration(pass_id, batch_id, entry["step"], cost,
                                metrics))
        if self.faults is not None:
            self._fire_step_faults(entry["step"])

    # -- fused dispatch ------------------------------------------------------

    @staticmethod
    def _plan_group(n: int, m: int):
        """Split an n-batch group buffer into dispatch slices: the full
        KxM part first, then the tail (whose final optimizer step may
        accumulate < M microbatches). Returns [(offset, take, m_eff)] —
        shared by the serial loop and the stager thread so pipelined
        grouping is always in lockstep with serial grouping."""
        plans, done = [], 0
        while done < n:
            rem = n - done
            take = (rem // m) * m or rem
            plans.append((done, take, m if take >= m else take))
            done += take
        return plans

    def _stage_group_work(self, work):
        """Stage one raw group buffer — stack + device_put every dispatch
        slice via the shared ``_fused_leaf_sharding`` rule. RUNS IN THE
        STAGER THREAD: touches no trainer mutable state (StatSet is
        locked), so it can overlap the in-flight device calls."""
        from .host_pipeline import StagedGroup, StagedUnit
        buf, buf_start, boundary = work
        if self.faults is not None:
            # the stager injection point: raises IN THE WORKER THREAD, so
            # the failure travels GroupStager's producer-error path and
            # surfaces in the training thread at the next submit/get
            self.faults.maybe_stager_error(buf_start)
        tracer = self.tracer
        # the group's flow id links THIS thread's staging span to the main
        # thread's later dispatch + drain spans in the trace viewer
        flow = tracer.new_flow() if tracer is not None else None
        units = []
        with tspan(tracer, "stage", flow_start=flow, group=buf_start,
                   batches=len(buf)):
            for off, take, m_eff in self._plan_group(len(buf),
                                                     self.grad_accum):
                t0 = time.perf_counter()
                with tspan(tracer, "stack", group=buf_start, offset=off):
                    stacked = self._stack_group(buf[off:off + take],
                                                take // m_eff, m_eff)
                t1 = time.perf_counter()
                with tspan(tracer, "shard", group=buf_start, offset=off):
                    staged = self._shard_fused(stacked)
                t2 = time.perf_counter()
                self.stats.add("stage_stack", t1 - t0)
                self.stats.add("stage_shard", t2 - t1)
                units.append(StagedUnit(offset=off, m_eff=m_eff,
                                        batches=staged,
                                        stack_s=t1 - t0, shard_s=t2 - t1))
            crc = _batch_fingerprint(buf[-1]) if boundary else None
        return StagedGroup(buf_start=buf_start, buf_len=len(buf),
                           units=units, boundary=boundary, crc=crc,
                           flow=flow)

    def _stack_group(self, sub, k: int, m: int):
        """Stack k*m host batches into one pytree with leaves
        ``[k, m, batch, ...]`` (the compiled fused step's input layout)."""
        hosts = [jax.tree_util.tree_map(np.asarray, b) for b in sub]

        def stack(*xs):
            arr = np.stack(xs)
            return arr.reshape((k, m) + arr.shape[1:])

        return jax.tree_util.tree_map(stack, *hosts)

    def compile_fused(self, host_batches):
        """Public harness hook: stack ``steps_per_call * grad_accum`` host
        batches into the fused [K, M, batch, ...] group, build the compiled
        fused step if needed, and return ``(fused_step, device_batches)``.

        ``fused_step(params, state, opt_state, step, device_batches, rng)``
        returns ``(params, state, opt_state, step, losses[K], stats)`` —
        plus a trailing health pytree when health telemetry is attached —
        the stable surface benchmarks drive for repeated dispatch of one
        resident group (bench.py's ``transformer_fused`` metric) without
        depending on the Trainer's private stacking/sharding layout."""
        K, M = self.steps_per_call, self.grad_accum
        if len(host_batches) != K * M:
            raise ValueError(
                f"compile_fused needs steps_per_call*grad_accum = {K * M} "
                f"host batches, got {len(host_batches)}")
        stacked = self._stack_group(host_batches, K, M)
        if self._fused_step is None:
            self._build_fused_step(stacked)
        return self._fused_step, self._shard_fused(stacked)

    def _fused_leaf_sharding(self, x):
        """The ONE per-leaf layout rule for stacked [K, M, batch, ...] group
        leaves — shared by the compiled step's in_shardings and the host
        device_put so the dispatch never resharding-copies its input:
        microbatch dim sharded over the data axis, [K, M] leading dims (and
        per-batch scalars) replicated."""
        if np.ndim(x) <= 2:               # [K, M] scalars: replicated
            return NamedSharding(self.mesh, P())
        return NamedSharding(self.mesh, P(None, None, mesh_lib.DATA_AXIS))

    def _shard_fused(self, stacked):
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self._fused_leaf_sharding(x)),
            stacked)

    def _dispatch_fused(self, stacked, rng, stack_s=None, staged=None,
                        defer=False, flow=None):
        """One fused device call; refreshes train_state (donation invalidates
        the previous buffers). Returns ``(losses [K], stats [K(, M), ...],
        health_or_None, record_or_None)`` — ``health`` is the device-side
        [K]-stacked health pytree (no fetch here), ``record`` the partial
        telemetry step record (breakdown fields filled; the events-replay
        time is appended by the caller).

        ``staged`` (a :class:`host_pipeline.StagedUnit`) supplies a group
        already stacked and device_put by the stager thread — the main
        thread skips both. ``defer=True`` (pipelined mode) additionally
        skips the per-call telemetry fence: a ``block_until_ready`` here
        would serialize exactly the window the pipeline keeps in flight;
        the drain records ``drain_wait_ms`` instead (``device_ms`` stays
        None, ``fenced`` False).

        Telemetry-off takes the exact pre-obs path: no fingerprinting, no
        fencing, no extra fetches — the dispatch count and donation
        behavior are byte-identical (tests/test_obs.py pins this)."""
        if staged is not None:
            stacked = staged.batches     # metadata-only uses below
            stack_s = staged.stack_s
        if self._fused_step is None:
            self._build_fused_step(stacked)
        tel = self.telemetry
        is_new, fp, hlo_flops = False, None, None
        if tel is not None:
            fp = _step_fingerprint(stacked)
            is_new = tel.observe_fingerprint(fp)
        if staged is not None:
            batches, shard_s = staged.batches, staged.shard_s
        else:
            with self.stats.time("shard_batch"), \
                    tspan(self.tracer, "device_put"):
                t_sh = time.perf_counter()
                batches = self._shard_fused(stacked)
                shard_s = time.perf_counter() - t_sh
        ts = self.train_state
        args = (ts.params, ts.state, ts.opt_state, ts.step, batches, rng)
        if is_new:
            # HLO cost-analysis FLOPs from the UN-compiled Lowered: one
            # extra trace (cheap), not a second compile; feeds MFU.
            from ..obs.telemetry import lowered_hlo_flops
            try:
                hlo_flops = lowered_hlo_flops(self._fused_step.lower(*args))
            except Exception:
                hlo_flops = None
        t_disp = time.perf_counter()
        with self.stats.time("train_step"), \
                tspan(self.tracer, "dispatch", flow_step=flow,
                      step=self._host_step, new_compile=is_new):
            out, profiled = self._maybe_profiled_call(self._fused_step,
                                                      *args)
        dispatch_s = time.perf_counter() - t_disp
        params, state, opt_state, step = out[:4]
        losses, stats = out[4], out[5]
        health = out[6] if len(out) > 6 else None
        device_s = None
        if tel is not None and tel.fence and not defer:
            # The fencing rule: the jit call above returns once XLA has
            # ENQUEUED the program (async dispatch) — a wall timer around
            # it measures dispatch, not compute. True device time is the
            # extra wait until the outputs are ready. Telemetry owns this
            # sync; without telemetry the loop never fences.
            with tspan(self.tracer, "fence"):
                jax.block_until_ready((params, losses))
            device_s = time.perf_counter() - t_disp - dispatch_s
            self.stats.add("device_wait", device_s)
        k_eff = int(losses.shape[0])
        self._host_step += k_eff       # host mirror of the device step
        if is_new:
            tel.record_compile(
                fp, wall_s=dispatch_s + (device_s or 0.0),
                hlo_flops=hlo_flops,
                meta={"k_steps": k_eff,
                      "m": int(jax.tree_util.tree_leaves(stacked)[0]
                               .shape[1])})
        rec = None
        if tel is not None:
            rec = {"k_steps": k_eff,
                   "m": int(jax.tree_util.tree_leaves(stacked)[0].shape[1]),
                   # profiled calls carry a block_until_ready INSIDE the
                   # dispatch window (the capture must include compute) —
                   # their dispatch_ms is not comparable, so telemetry
                   # suppresses throughput and the anomaly detector skips
                   # the record (the flight recorder must not trigger the
                   # detector that armed it)
                   "profiled": profiled,
                   "host_stack_ms": (round(stack_s * 1e3, 3)
                                     if stack_s is not None else None),
                   "shard_ms": round(shard_s * 1e3, 3),
                   "dispatch_ms": round(dispatch_s * 1e3, 3),
                   "device_ms": (round(device_s * 1e3, 3)
                                 if device_s is not None else None)}
            if staged is not None:
                # background staging wall (stack + device_put, off the
                # critical path); drain_wait_ms/overlap_frac land at drain.
                # NOTE the semantic shift: in this record host_stack_ms/
                # shard_ms were measured on the STAGER thread (their sum
                # is stage_ms — already-hidden cost), unlike serial
                # records where they are main-thread critical-path time;
                # the exposed-cost signal for pipelined runs is
                # drain_wait_ms.
                rec["stage_ms"] = round(
                    (staged.stack_s + staged.shard_s) * 1e3, 3)
        self.train_state = TrainState(params, state, opt_state, step)
        return losses, stats, health, rec

    def _run_fused_group(self, buf, buf_start, pass_id, rng, handler, costs,
                         log_period, saving_period, checkpoint_dir,
                         checkpoint_keep, save_fn):
        """Dispatch a buffered host-batch group as fused device calls, then
        replay the per-optimizer-step host bookkeeping (events, costs,
        evaluator updates, logging) and checkpoint at the call boundary.

        Events fire with ``batch_id`` = the index of the step's LAST host
        batch, so host-batch-denominated periods (``log_period``,
        ``saving_period``) keep their plain-mode meaning. Because the K
        steps run inside one dispatch, Begin/EndIteration both fire after
        the call, and mid-pass checkpoints land on call boundaries (a
        ``saving_period`` crossed mid-call saves once, at the boundary, with
        the true ``next_batch`` position — so resume replay stays aligned
        with the fused grouping)."""
        results = []
        with tspan(self.tracer, "plan", group=buf_start, batches=len(buf)):
            plans = self._plan_group(len(buf), self.grad_accum)
        for off, take, m_eff in plans:
            t_stack = time.perf_counter()
            with tspan(self.tracer, "stack", group=buf_start, offset=off):
                stacked = self._stack_group(buf[off:off + take],
                                            take // m_eff, m_eff)
            stack_s = time.perf_counter() - t_stack
            self.stats.add("stack_group", stack_s)
            losses, stats, health, rec = self._dispatch_fused(
                stacked, rng, stack_s=stack_s)
            # record THIS dispatch's post-call step count: a group split
            # into several dispatches (tail not a multiple of M) must not
            # number earlier dispatches' steps off the later ones' state
            results.append((buf_start + off, m_eff, losses, stats,
                            self._host_step, health, rec))
        self._finalize_group(pass_id, buf_start, len(buf), results, handler,
                             costs, log_period, saving_period,
                             checkpoint_dir, checkpoint_keep, save_fn,
                             crc_fn=lambda: _batch_fingerprint(buf[-1]))

    def _finalize_group(self, pass_id, buf_start, buf_len, results, handler,
                        costs, log_period, saving_period, checkpoint_dir,
                        checkpoint_keep, save_fn, crc_fn,
                        drain_timing=False, overlap_frac=None):
        """The bottom half of a group: boundary checkpoint, then the FIFO
        event replay — shared verbatim by the serial loop (immediately
        after the dispatches) and the pipelined drain (deferred until the
        window releases the group). ``crc_fn`` supplies the group's last
        host-batch fingerprint (serial: computed lazily at save; pipelined:
        precomputed in the stager). ``drain_timing=True`` times the first
        blocking loss fetch per dispatch into ``drain_wait_ms`` and stamps
        ``overlap_frac`` — the pipelined replacements for the per-call
        device fence the pipeline cannot afford."""
        tel = self.telemetry
        if drain_timing:
            timed = []
            for i, (start, m_eff, losses, stats, step_after, health,
                    rec) in enumerate(results):
                t0 = time.perf_counter()
                with tspan(self.tracer, "drain_wait", step=step_after):
                    losses = np.asarray(jax.device_get(losses))
                wait = time.perf_counter() - t0
                self.stats.add("drain_wait", wait)
                if rec is not None:
                    rec["drain_wait_ms"] = round(wait * 1e3, 3)
                    if overlap_frac is not None:
                        rec["overlap_frac"] = round(overlap_frac, 4)
                timed.append((start, m_eff, losses, stats, step_after,
                              health, rec))
            results = timed
        # The boundary checkpoint lands BEFORE the replayed events, matching
        # the plain loop's save-then-EndIteration order (handlers that kill
        # training after a period save — the kill/resume pattern — observe
        # the same sequence). With nan_check on, a non-finite loss anywhere
        # in the group SKIPS the save (plain mode raises before reaching its
        # save) — never persist a poisoned train_state that resume would
        # restore.
        end = buf_start + buf_len
        group_finite = (not self._nan_check) or all(
            np.isfinite(np.asarray(jax.device_get(losses))).all()
            for _, _, losses, _, _, _, _ in results)
        if saving_period and checkpoint_dir and group_finite and \
                (end // saving_period) > (buf_start // saving_period):
            with tspan(self.tracer, "checkpoint_save", next_batch=end):
                save_fn(
                    checkpoint_dir, pass_id,
                    {**self.train_state.as_dict(),
                     "iter": {"pass": pass_id, "next_batch": end,
                              "completed": 0,
                              "batch_crc": crc_fn()}},
                    keep_last=checkpoint_keep)
        for start, m_eff, losses, stats, step_after, health, rec in results:
            # Health scalars are device-side [K] stacks; fetching them here
            # rides the same per-call host sync that already fetches the
            # losses — no extra dispatch. The human-readable log still
            # fires only at log_period (inside _post_fused).
            health_np = (jax.device_get(health)
                         if (tel is not None and health is not None)
                         else None)
            t_replay = time.perf_counter()
            replay_ok = False
            try:
                with tspan(self.tracer, "events_replay", step=step_after):
                    self._post_fused(pass_id, start, m_eff, losses, stats,
                                     step_after, handler, costs, log_period,
                                     health_np=health_np)
                replay_ok = True
            finally:
                # the record is emitted (and the anomaly detector fed) even
                # when nan_check's FloatingPointError unwinds _post_fused —
                # the plain loop observes before its raise, and a poisoned
                # run is EXACTLY when the flight recorder must fire. While
                # unwinding, a secondary failure here (a raising handler,
                # a dead transport under the loss fetch) must NOT mask the
                # original error and its nonfinite-leaves diagnostic.
                if tel is not None and rec is not None:
                    # success sentinel, NOT sys.exc_info(): the latter is
                    # non-None for the whole call when train() itself runs
                    # inside a caller's except block, which would silently
                    # swallow healthy-path handler bugs
                    unwinding = not replay_ok
                    try:
                        if health_np is not None:
                            tel.update_health(
                                {k: v[-1] for k, v in health_np.items()})
                        rec["pass"] = pass_id
                        rec["step"] = step_after
                        rec["loss"] = float(np.asarray(
                            jax.device_get(losses)).ravel()[-1])
                        rec["replay_ms"] = round(
                            (time.perf_counter() - t_replay) * 1e3, 3)
                        rec = tel.emit_step(rec)
                        handler(ev.TelemetryRecord(record=rec))
                        self._anomaly_observe(rec)
                    except Exception:
                        if not unwinding:
                            raise
                        _log.exception(
                            "telemetry emit failed during exception unwind "
                            "(the original error propagates)")

    def _post_fused(self, pass_id, start_index, m_eff, losses, stats,
                    step_after, handler, costs, log_period, health_np=None):
        """Replay one dispatch's host bookkeeping; ``step_after`` is the
        global optimizer-step count right after THAT dispatch.
        ``health_np``: host-fetched dict of [K] health scalars (telemetry
        on) — logged at log_period crossings."""
        losses_np = np.asarray(jax.device_get(losses))
        stats_np = (jax.device_get(stats)
                    if self.evaluator is not None else None)
        K = int(losses_np.shape[0])
        for k in range(K):
            last_id = start_index + (k + 1) * m_eff - 1
            handler(ev.BeginIteration(pass_id, last_id))
            cost = float(losses_np[k])
            if self._nan_check and not np.isfinite(cost):
                from ..utils import debug as dbg
                ts = self.train_state
                bad = dbg.nonfinite_leaves(
                    {"params": ts.params, "state": ts.state})
                raise FloatingPointError(
                    f"non-finite loss {cost} at pass {pass_id} batch "
                    f"{last_id} (step {step_after - (K - 1 - k)}); "
                    f"non-finite leaves (post-call state): "
                    f"{bad[:8] or 'none (loss only)'}")
            costs.append(cost)
            metrics = {}
            if self.evaluator is not None:
                for m in range(m_eff):
                    self.evaluator.update(jax.tree_util.tree_map(
                        lambda x: x[k] if m_eff == 1 else x[k][m], stats_np))
                metrics = self.evaluator.result()
            # Period checks use boundary CROSSING, not exact modulo: a step
            # consumes m_eff host batches, so (last_id + 1) only lands on
            # multiples of m_eff and an exact-modulo period not divisible
            # by grad_accum would (mostly) never fire.
            step_first = start_index + k * m_eff
            if log_period and \
                    (last_id + 1) // log_period > step_first // log_period:
                msg = " ".join(f"{k_}={v:.4f}" for k_, v in metrics.items())
                if health_np is not None:
                    msg += " " + " ".join(
                        f"{hk}={float(hv[k]):.3g}"
                        for hk, hv in health_np.items())
                _log.info("pass %d batch %d cost=%.4f %s",
                          pass_id, last_id + 1, cost, msg)
                self._log_stat_report()
            psp = self._param_stats_period
            if psp and (last_id + 1) // psp > step_first // psp:
                self._log_param_stats(pass_id, last_id)
            handler(ev.EndIteration(pass_id, last_id,
                                    step_after - (K - 1 - k), cost, metrics))
            if self.faults is not None:
                self._fire_step_faults(step_after - (K - 1 - k))

    def _log_stat_report(self, top_n: int = 8):
        """Periodic StatSet summary at log_period — the reference's
        ``printAllStatus`` analog (``utils/Stat.h``). INFO when telemetry
        is attached (the operator asked for visibility), DEBUG otherwise
        (no new log noise for untelemetered runs)."""
        lvl = logging.INFO if self.telemetry is not None else logging.DEBUG
        if _log.isEnabledFor(lvl):
            _log.log(lvl, "%s", self.stats.report(top_n=top_n))

    def _log_param_stats(self, pass_id: int, batch_id: int):
        """Per-parameter scale telemetry (``--show_parameter_stats_period``:
        the reference logs max/avg absolute value per Parameter,
        ``TrainerInternal.cpp:81-92``)."""
        flat = jax.tree_util.tree_flatten_with_path(
            self.train_state.params)[0]
        for path, leaf in flat:
            arr = np.asarray(leaf, np.float32)
            _log.info(
                "param %s shape=%s abs_max=%.4g abs_avg=%.4g mean=%.4g "
                "std=%.4g (pass %d batch %d)",
                jax.tree_util.keystr(path), tuple(arr.shape),
                float(np.abs(arr).max(initial=0)), float(np.abs(arr).mean()),
                float(arr.mean()), float(arr.std()), pass_id, batch_id + 1)

    def evaluate(self, reader: Callable) -> Tuple[float, Dict[str, float]]:
        assert self.train_state is not None
        if self._eval_step is None:
            self._build_eval_step()
        if self.evaluator is not None:
            self.evaluator.reset()
        ts = self.train_state
        costs = []
        with tspan(self.tracer, "eval"):
            for host_batch in reader():
                batch = self._shard(host_batch)
                loss, stats = self._eval_step(ts.params, ts.state, batch)
                costs.append(float(loss))
                if self.evaluator is not None:
                    self.evaluator.update(jax.device_get(stats))
        metrics = self.evaluator.result() if self.evaluator is not None else {}
        return float(np.mean(costs)) if costs else 0.0, metrics

    # -- AOT warmup (ISSUE 16) -----------------------------------------------

    def warmup(self, sample_batches, rng: Optional[Any] = None
               ) -> Dict[str, Any]:
        """AOT-compile the training step for ``sample_batches``' shapes
        — one ``lower().compile()``, ZERO executions (``train_state``
        and the host step mirror are untouched; executing a step to warm
        it would mutate params). The step fingerprint (PR 2) already
        names exactly what must be cached, so a resume harness warms by
        replaying its known fingerprints' batch shapes through here.

        What the compile buys: with the persistent compilation cache
        configured (:func:`paddle_tpu.obs.xla_cache.
        setup_compilation_cache`) the serialized executable lands on
        disk, so THIS process's first real dispatch — and every future
        process resuming the same step — deserializes instead of
        recompiling. With the kernel autotuner enabled, the lowering's
        trace also runs any untuned flash-kernel trials now, off the
        training hot path. Returns ``{fingerprint, wall_s, cache_hit,
        autotune_trials, xla_cache_entries_added}``; emits a
        ``kind="compile"`` record (``meta.warmup=True``) when telemetry
        is attached.

        Args:
          sample_batches: host batches fixing the step's input shapes —
            ``steps_per_call * grad_accum`` batches in fused mode
            (``compile_fused``'s contract), one batch (or a one-element
            list) in plain mode.
          rng: PRNGKey for the lowering (default PRNGKey(0)).
        """
        assert self.train_state is not None, "call init() first"
        from ..nn import autotune
        from ..obs import xla_cache
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        fused = self.steps_per_call > 1 or self.grad_accum > 1
        ts = self.train_state
        if fused:
            K, M = self.steps_per_call, self.grad_accum
            if not isinstance(sample_batches, (list, tuple)) \
                    or len(sample_batches) != K * M:
                raise ValueError(
                    f"warmup needs steps_per_call*grad_accum = {K * M} "
                    f"host batches in fused mode (compile_fused's "
                    f"contract)")
            stacked = self._stack_group(list(sample_batches), K, M)
            if self._fused_step is None:
                self._build_fused_step(stacked)
            batch = self._shard_fused(stacked)
            step_fn = self._fused_step
            fp = _step_fingerprint(stacked)
        else:
            one = (sample_batches[0]
                   if isinstance(sample_batches, (list, tuple))
                   else sample_batches)
            batch = self._shard(one)
            if self._train_step is None:
                self._build_train_step()
            step_fn = self._train_step
            fp = ((1, 1),) + _step_fingerprint(one)
        entries_before = xla_cache.cache_entry_count()
        trials_before = autotune.stats()["trials"]
        t0 = time.perf_counter()
        step_fn.lower(ts.params, ts.state, ts.opt_state, ts.step, batch,
                      rng).compile()
        wall = time.perf_counter() - t0
        added = xla_cache.cache_entry_count() - entries_before
        cache_hit = (None if xla_cache.active_dir() is None
                     else added == 0)
        trials = autotune.stats()["trials"] - trials_before
        if self.telemetry is not None:
            self.telemetry.record_compile(
                fp, wall, cache_hit=cache_hit, autotune_trials=trials,
                meta={"warmup": True, "aot": True})
        return {"fingerprint": fp, "wall_s": round(wall, 6),
                "cache_hit": cache_hit, "autotune_trials": trials,
                "xla_cache_entries_added": added}

    # -- device-side attribution (ISSUE 6) -----------------------------------

    def attribution_report(self, sample_batches, rng: Optional[Any] = None,
                           profile_dir: Optional[str] = None,
                           emit: bool = True) -> Dict[str, Any]:
        """MFU-gap attribution of the compiled train step: parse the
        optimized (post-SPMD) HLO into per-``jax.named_scope`` FLOPs/bytes
        rooflines, a structured collective inventory, and an
        exposed-vs-overlappable communication estimate
        (:mod:`paddle_tpu.obs.hloprof` / :mod:`~paddle_tpu.obs.attribution`).

        PULL-BASED, OFF THE HOT LOOP: nothing here runs unless this method
        is called — a Trainer that never calls it is byte-identical to the
        pre-attribution build (same traced step, dispatch count, donation,
        zero fences; pinned by tests/test_hloprof.py in the PR-2/4 style).
        The report costs one AOT ``lower().compile()`` of the step (the
        live jit executable's text is not exposed) and zero executions:
        ``train_state`` and the host step mirror are untouched.

        Args:
          sample_batches: host batches fixing the step's input shapes —
            a list of ``steps_per_call * grad_accum`` batches in fused
            mode (``compile_fused``'s contract), one batch (or a
            one-element list) in plain mode.
          rng: PRNGKey for the lowering (default PRNGKey(0)).
          profile_dir: a ``Tracer.profile_window()`` / ``jax.profiler``
            capture directory — when it holds a device-lane Chrome trace
            the MEASURED compute-vs-communication split joins the static
            report under ``report["measured"]`` (absent on CPU captures:
            static-only, degrading gracefully).
          emit: emit the report as a ``kind="attribution"`` telemetry
            record to every sink (no-op with ``telemetry=None``).
        """
        assert self.train_state is not None, "call init() first"
        from ..obs import attribution as attr_lib
        from ..obs import hloprof
        from ..obs.telemetry import lowered_hlo_flops
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        fused = self.steps_per_call > 1 or self.grad_accum > 1
        ts = self.train_state
        if fused:
            if not isinstance(sample_batches, (list, tuple)):
                raise ValueError(
                    "fused attribution needs steps_per_call*grad_accum "
                    "host batches (compile_fused's contract)")
            K, M = self.steps_per_call, self.grad_accum
            if len(sample_batches) != K * M:
                raise ValueError(
                    f"attribution_report needs {K * M} host batches for "
                    f"K={K}, M={M}; got {len(sample_batches)}")
            stacked = self._stack_group(list(sample_batches), K, M)
            if self._fused_step is None:
                self._build_fused_step(stacked)
            batch = self._shard_fused(stacked)
            step_fn = self._fused_step
        else:
            one = (sample_batches[0]
                   if isinstance(sample_batches, (list, tuple))
                   else sample_batches)
            batch = self._shard(one)
            if self._train_step is None:
                self._build_train_step()
            step_fn = self._train_step
        args = (ts.params, ts.state, ts.opt_state, ts.step, batch, rng)
        lowered = step_fn.lower(*args)
        compiled = lowered.compile()
        # the agreement check must compare against the SAME optimized
        # module we parse (lowered_hlo_flops accepts anything with
        # cost_analysis(): here the Compiled, not the Lowered)
        cost_flops = lowered_hlo_flops(compiled)
        analysis = hloprof.parse_module(compiled.as_text())
        mesh = self.mesh
        report = attr_lib.build_report(
            analysis,
            device_kind=getattr(jax.devices()[0], "device_kind", ""),
            n_devices=int(mesh.devices.size),
            cost_analysis_flops=cost_flops,
            meta={
                "mesh_axes": dict(zip(mesh.axis_names, mesh.devices.shape)),
                "steps_per_call": self.steps_per_call,
                "grad_accum": self.grad_accum,
                "fused": fused,
            })
        if profile_dir is not None:
            measured = attr_lib.parse_profile_trace(profile_dir)
            if measured is not None:
                report["measured"] = measured
        if emit and self.telemetry is not None:
            self.telemetry.emit_event(report)
        return report

    # -- checkpoint ----------------------------------------------------------

    def save(self, checkpoint_dir: str, pass_id: int):
        assert self.train_state is not None
        return ckpt_lib.save_checkpoint(checkpoint_dir, pass_id,
                                        self.train_state.as_dict())

    def restore(self, checkpoint_dir: str, pass_id: Optional[int] = None):
        """Restore from a checkpoint. ``pass_id=None`` loads the newest
        READABLE pass via the fallback chain
        (:func:`~paddle_tpu.train.checkpoint.load_latest_valid`):
        poisoned dirs are quarantined to ``pass-NNNNN.corrupt`` — never
        deleted — and the previous readable pass loads instead
        (``trainer.last_quarantined`` records what was moved aside). An
        explicit ``pass_id`` stays strict and raises on corruption."""
        if pass_id is None:
            loaded = ckpt_lib.load_latest_valid(checkpoint_dir)
        else:
            loaded = ckpt_lib.load_checkpoint(checkpoint_dir, pass_id)
        self.last_quarantined = loaded.pop("_quarantined", [])
        self._last_restored_pass = int(loaded["pass_id"])
        # iterator position (absent in pre-saving_period checkpoints)
        self._last_iter_state = loaded.get("iter")
        put = lambda tree: jax.tree_util.tree_map(jnp.asarray, tree)
        params = put(loaded["params"])
        state = put(loaded.get("state", {}))
        if self._param_sharding is not None:
            # Re-commit the model-parallel layout (checkpoints hold host
            # arrays; without this a resumed run would continue replicated).
            from ..parallel import sharding as shard_lib
            specs = self._param_specs
            if specs is None:
                specs = self._param_sharding
                if isinstance(specs, shard_lib.ShardingRules):
                    specs = specs(params)
                self._param_specs = specs
            params = shard_lib.shard_tree(self.mesh, params, specs)
            state = shard_lib.shard_tree(self.mesh, state)
        # Rebuild optimizer-state pytree type (tuples/namedtuples flattened to
        # plain containers by the npz round-trip) by grafting leaves onto a
        # freshly-built state skeleton — then commit each loaded leaf to the
        # skeleton leaf's (possibly sharded) layout.
        skeleton = self.optimizer.init(params)
        flat_loaded = jax.tree_util.tree_leaves(put(loaded["opt_state"]))
        treedef = jax.tree_util.tree_structure(skeleton)
        opt_state = jax.tree_util.tree_unflatten(treedef, flat_loaded)
        if self._param_sharding is not None:
            opt_state = jax.tree_util.tree_map(
                lambda skel, val: jax.device_put(val, skel.sharding),
                skeleton, opt_state)
        self.train_state = TrainState(params, state, opt_state,
                                      jnp.asarray(loaded["step"], jnp.int32))
        return self.train_state
