"""Training driver, events, evaluators, checkpointing (successor of
paddle/trainer, v2 SGD event loop, gserver evaluators, ParamUtil checkpoints)."""

from . import checkpoint, events, evaluators
from .evaluators import (Auc, ChunkEvaluator, ClassificationError, Evaluator,
                         EvaluatorSet, PnPair, PrecisionRecall, RankAuc)
from .trainer import Trainer, TrainState
