"""Training driver, events, evaluators, checkpointing (successor of
paddle/trainer, v2 SGD event loop, gserver evaluators, ParamUtil checkpoints)
— plus the elastic fault-tolerance layer (ISSUE 10): the restart
supervisor, preemption handling, and the deterministic fault-injection
plane."""

from . import checkpoint, events, evaluators, faults, resilience
from .evaluators import (Auc, ChunkEvaluator, ClassificationError, Evaluator,
                         EvaluatorSet, PnPair, PrecisionRecall, RankAuc)
from .faults import (FaultSchedule, InjectedCrash, InjectedFault,
                     InjectedSaveError, Preempted)
from .resilience import (RunResult, SupervisorGaveUp, classify_failure,
                         install_preemption_handler, run_resilient)
from .trainer import Trainer, TrainState
