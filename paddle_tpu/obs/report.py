"""Run-summary CLI over a telemetry JSONL (ISSUE 6 satellite).

``python -m paddle_tpu.obs.report run.jsonl`` prints one table a human
can read off a finished (or crashed) run's telemetry file: throughput,
MFU, compiles/retraces, pipeline overlap, anomalies, and the step-time
breakdown — the ``printAllStatus`` successor for files instead of
processes.

The PR-4 final ``summary`` record (``Telemetry.close()``) is the
preferred source when present — it already aggregates the run the way
``Telemetry.summary()`` does (honest pipelined rates from record
timestamps, profiled records excluded). Without one (a crashed run that
never closed), the CLI falls back to aggregating the step records
directly, so a truncated JSONL still reports. ``kind="anomaly"`` records
(the Trainer echoes every detector verdict into the stream) and
NaN-sanitized losses feed the anomalies row.

``--json`` prints the summary dict instead of the table (machine
consumers); a rotated ``<path>.1`` sibling (``JsonlSink(max_bytes=...)``)
is read first automatically so the window spans both files.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import sys
from typing import Any, Dict, List, Optional

from .percentiles import (summarize_handoffs, summarize_requests,
                          summarize_scale)

__all__ = ["load_records", "summarize", "format_summary", "main"]


def load_records(path: str, include_rotated: bool = True
                 ) -> List[Dict[str, Any]]:
    """Parse a telemetry JSONL back into record dicts; a rotated
    ``<path>.1`` sibling is prepended when present (oldest first).
    Truncated trailing lines (a crash mid-write is the use case) are
    skipped, not fatal."""
    paths = []
    if include_rotated and os.path.exists(path + ".1"):
        paths.append(path + ".1")
    paths.append(path)
    out: List[Dict[str, Any]] = []
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    return out


def _mean(vals: List[float]) -> Optional[float]:
    vals = [v for v in vals if v is not None]
    return round(sum(vals) / len(vals), 4) if vals else None


def summarize(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate a record stream into the run-summary dict the table
    renders. Prefers the final ``summary`` record; derives everything it
    can from the step records otherwise."""
    steps = [r for r in records if r.get("kind") == "step"]
    compiles = [r for r in records if r.get("kind") == "compile"]
    anomalies = [r for r in records if r.get("kind") == "anomaly"]
    attributions = [r for r in records if r.get("kind") == "attribution"]
    summary_rec = next((r for r in reversed(records)
                        if r.get("kind") == "summary"), None)

    out: Dict[str, Any] = {
        "records": len(records),
        "steps": len(steps),
        "optimizer_steps": sum(int(r.get("k_steps") or 1) for r in steps),
        "compiles": len(compiles),
        "compile_wall_s": round(sum(r.get("wall_s") or 0.0
                                    for r in compiles), 3),
        "anomalies": len(anomalies),
        # the Trainer echoes each Verdict with its trigger kind renamed
        # to anomaly_kind (the record "kind" slot holds "anomaly")
        "anomaly_kinds": sorted({a.get("anomaly_kind") or "?"
                                 for a in anomalies}),
        "attribution_reports": len(attributions),
        "from_summary_record": summary_rec is not None,
    }

    nan_steps = sum(1 for r in steps
                    if (r.get("nonfinite_count") or 0) > 0
                    or ("loss" in r and r.get("loss") is None))
    out["nonfinite_steps"] = nan_steps

    if steps:
        last = steps[-1]
        out["last_step"] = last.get("step")
        out["last_loss"] = last.get("loss")
        out["retraces"] = last.get("retrace_count")
        span = steps[-1].get("ts", 0) - steps[0].get("ts", 0)
        done = sum(int(r.get("k_steps") or 1) for r in steps[1:])
        if span > 0 and done:
            out["steps_per_sec"] = round(done / span, 3)
        out["est_mfu_pct"] = next(
            (r.get("est_mfu_pct") for r in reversed(steps)
             if r.get("est_mfu_pct") is not None), None)
        out["tokens_per_sec"] = next(
            (r.get("tokens_per_sec") for r in reversed(steps)
             if r.get("tokens_per_sec") is not None), None)
        for key in ("host_stack_ms", "shard_ms", "dispatch_ms", "device_ms",
                    "replay_ms", "stage_ms", "drain_wait_ms",
                    "overlap_frac"):
            m = _mean([r.get(key) for r in steps if not r.get("profiled")])
            if m is not None:
                out[f"mean_{key}"] = m
        out["peak_bytes"] = max((r.get("peak_bytes") or 0 for r in steps),
                                default=0) or None

    if summary_rec is not None:
        # the close-time aggregate wins where it exists (it excludes
        # profiled records and derives pipelined rates honestly)
        for key, val in summary_rec.items():
            if key in ("kind", "ts"):
                continue
            if val is not None:
                out[key] = val
    if attributions:
        att = attributions[-1]
        out["attribution_est_mfu_pct"] = att.get("est_mfu_pct")
        comm = att.get("comm") or {}
        out["attribution_exposed_comm_ms"] = comm.get("exposed_ms")
    # serving SLO percentiles (ISSUE 11): p50/p95/p99 TTFT/TPOT +
    # goodput-under-deadline over kind="request" records, when present
    serving = summarize_requests(records)
    if serving is not None:
        out["serving"] = serving
    # autoscaler decisions (ISSUE 13): kind="scale" events aggregate
    # into the serving block (up/down/replace counts, final capacity)
    scale = summarize_scale(records)
    if scale is not None:
        out.setdefault("serving", {})["scale"] = scale
    # prefill→decode KV handoffs (ISSUE 18): kind="kv_handoff" events
    # aggregate into the serving block (count, wire bytes, quant mix)
    handoffs = summarize_handoffs(records)
    if handoffs is not None:
        out.setdefault("serving", {})["handoffs"] = handoffs
    # transport-fault counters (ISSUE 17 satellite): the fleet counts
    # retransmits/timeouts/corrupt replies in `fleet.stats()` but the
    # report rendered none of it. Prefer the fleet's own aggregate
    # record (`fleet.emit_stats()`, carries retransmits — those never
    # appear as stream events); fall back to counting the transport
    # events the stream does carry.
    fleet_rec = next((r for r in reversed(records)
                      if r.get("kind") == "fleet"), None)
    tev = [r for r in records if r.get("kind") == "transport"]
    transport: Optional[Dict[str, Any]] = None
    # registry read-through (ISSUE 19 satellite): when the stream
    # carries a `kind="metrics"` snapshot, the per-link transport_*
    # counters ARE the totals (incremented at the same sites as the
    # attribute counters) — sum them across links. The fleet-record /
    # classified-event paths stay the dark-mode fallbacks.
    met_rec = next((r for r in reversed(records)
                    if r.get("kind") == "metrics"), None)
    if met_rec is not None:
        tot: Dict[str, int] = {}
        for row in met_rec.get("metrics") or ():
            name = row.get("name") or ""
            if (name.startswith("transport_")
                    and row.get("type") == "counter"
                    and name[len("transport_"):] in (
                        "errors", "retransmits", "timeouts",
                        "corrupt_replies")):
                k = name[len("transport_"):]
                tot[k] = tot.get(k, 0) + int(row.get("value") or 0)
        if tot:
            transport = {"errors": 0, "retransmits": 0, "timeouts": 0,
                         "corrupt_replies": 0, **tot}
    if transport is None and fleet_rec is not None \
            and fleet_rec.get("transport") is not None:
        transport = dict(fleet_rec["transport"])
    elif transport is None and tev:
        transport = dict(collections.Counter(
            r.get("event") or "?" for r in tev))
    if transport is not None:
        transport["events"] = len(tev)
        out.setdefault("serving", {})["transport"] = transport
    # the streaming-SLO aggregate (burn rate etc.) rides the same
    # fleet record when the monitor was on
    if fleet_rec is not None and fleet_rec.get("slo") is not None:
        out.setdefault("serving", {})["slo"] = fleet_rec["slo"]
    # epoch-fenced membership (ISSUE 20): prefer the fleet record's
    # counters — a fence can appear TWICE in the raw stream (the
    # parent's authoritative record plus the child's own forensics
    # record, tagged source="replica" and shipped post-readmit), so
    # raw kind="fence" counting is the dark fallback only, restricted
    # to the parent-side records.
    fences = [r for r in records if r.get("kind") == "fence"]
    degrades = [r for r in records if r.get("kind") == "degrade"]
    membership: Optional[Dict[str, Any]] = None
    if fleet_rec is not None and fleet_rec.get("membership") is not None:
        membership = dict(fleet_rec["membership"])
    elif fences or degrades:
        membership = {
            "fences": sum(1 for r in fences
                          if r.get("source") != "replica"),
            "readmitted": 0, "false_deaths_averted": 0,
            "degradations": sum(1 for r in degrades
                                if r.get("event") == "engaged"),
        }
    if membership is not None:
        membership["fence_records"] = len(fences)
        reasons = collections.Counter(
            r.get("reason") or "?" for r in fences
            if r.get("source") != "replica")
        if reasons:
            membership["fence_reasons"] = dict(reasons)
        if degrades:
            membership["degrade_events"] = len(degrades)
        if fleet_rec is not None and fleet_rec.get("chaos") is not None:
            membership["chaos"] = fleet_rec["chaos"]
        out.setdefault("serving", {})["membership"] = membership
    return out


_ROWS = (
    ("records", "records"),
    ("steps (records / optimizer)", None),        # composite
    ("steps/sec", "steps_per_sec"),
    ("pipelined steps/sec", "pipelined_steps_per_sec"),
    ("tokens/sec", "tokens_per_sec"),
    ("est MFU %", "est_mfu_pct"),
    ("static-attribution MFU %", "attribution_est_mfu_pct"),
    ("exposed comm ms (static)", "attribution_exposed_comm_ms"),
    ("compiles / retraces", None),                # composite
    ("compile wall s", "compile_wall_s"),
    ("mean dispatch ms", "mean_dispatch_ms"),
    ("mean device ms", "mean_device_ms"),
    ("mean stage ms", "mean_stage_ms"),
    ("mean drain wait ms", "mean_drain_wait_ms"),
    ("mean overlap frac", "mean_overlap_frac"),
    ("peak device bytes", "peak_bytes"),
    ("last step / loss", None),                   # composite
    ("nonfinite steps", "nonfinite_steps"),
    ("anomalies", None),                          # composite
    ("stager leaked", "stager_leaked"),
)


def format_summary(s: Dict[str, Any]) -> str:
    """Fixed-width table of one run summary."""
    lines = ["telemetry run summary"
             + ("  (from final summary record)"
                if s.get("from_summary_record") else "  (no summary record"
                " — aggregated from step records)")]
    for label, key in _ROWS:
        if key is None:
            if label.startswith("steps "):
                val = f"{s.get('steps')} / {s.get('optimizer_steps')}"
            elif label.startswith("compiles"):
                val = f"{s.get('compiles')} / {s.get('retraces', 0)}"
            elif label.startswith("last step"):
                if s.get("last_step") is None:
                    continue
                val = f"{s.get('last_step')} / {s.get('last_loss')}"
            else:
                n = s.get("anomalies", 0)
                if not n:
                    val = "0"
                else:
                    val = f"{n} ({', '.join(s.get('anomaly_kinds', []))})"
        else:
            val = s.get(key)
            if val is None:
                continue
        lines.append(f"  {label:<28}{val}")
    sv = s.get("serving")
    if sv and sv.get("requests") is not None:
        lines.append("serving requests")
        lines.append(f"  {'requests (terminal / retried)':<28}"
                     f"{sv.get('requests')} / "
                     f"{sv.get('retried_attempts')}")
        reasons = sv.get("finish_reasons") or {}
        if reasons:
            lines.append(f"  {'finish reasons':<28}"
                         + ", ".join(f"{k}={v}"
                                     for k, v in sorted(reasons.items())))
        for key, label in (("ttft_ms", "TTFT ms"),
                           ("tpot_ms", "TPOT ms"),
                           ("wall_ms", "wall ms")):
            ps = [sv.get(f"{key}_p{p}") for p in (50, 95, 99)]
            if any(v is not None for v in ps):
                lines.append(f"  {label + ' p50/p95/p99':<28}"
                             + " / ".join(str(v) for v in ps))
        if sv.get("goodput_pct") is not None:
            lines.append(f"  {'goodput under deadline':<28}"
                         f"{sv['goodput_pct']}% "
                         f"({sv.get('deadline_met')}/"
                         f"{sv.get('deadline_requests')}, "
                         f"{sv.get('goodput_tokens')} tokens)")
        # serving-throughput aggregates (ISSUE 12): only rendered when
        # the engine features actually fired
        if sv.get("prefix_hit_blocks"):
            lines.append(f"  {'prefix-cache block sharing':<28}"
                         f"{sv['prefix_hit_blocks']} blocks "
                         f"({sv.get('block_sharing_ratio')} of reserved, "
                         f"{sv.get('cow_forks', 0)} COW forks)")
        if sv.get("draft_accept_rate") is not None:
            lines.append(f"  {'speculative accept rate':<28}"
                         f"{sv['draft_accept_rate']}")
        if sv.get("prefill_chunks"):
            lines.append(f"  {'prefill chunks':<28}"
                         f"{sv['prefill_chunks']}")
        # retention + KV-capacity rows (ISSUE 14): rendered when the
        # retained LRU actually served hits / the stream carries the
        # pool's byte accounting
        if sv.get("retained_hits"):
            lines.append(f"  {'retained prefix hits':<28}"
                         f"{sv['retained_hits']} blocks "
                         f"(rate {sv.get('retention_hit_rate')}, "
                         f"{sv.get('retained_blocks')} retained now)")
        if sv.get("kv_bytes_per_token") is not None:
            tp = sv.get("tp_degree") or 1
            lines.append(f"  {'KV bytes/token':<28}"
                         f"{sv['kv_bytes_per_token']} "
                         f"({sv.get('quant_dtype')}"
                         + (f", per shard)" if tp > 1 else ")"))
        # the serving mesh shape (ISSUE 15): rendered whenever the tick
        # stream says the engine ran tensor-parallel
        if (sv.get("tp_degree") or 1) > 1:
            lines.append(f"  {'tensor-parallel mesh':<28}"
                         f"tp={sv['tp_degree']} "
                         f"(head-sharded KV, per-shard bytes)")
    # prefill→decode KV handoffs (ISSUE 18) — rendered whenever the
    # disaggregated fleet actually streamed pages
    ho = (sv or {}).get("handoffs")
    if ho:
        lines.append("kv handoffs")
        lines.append(f"  {'handoffs (blocks / bytes)':<28}"
                     f"{ho['handoffs']} ({ho['blocks']} / "
                     f"{ho['wire_bytes']})")
        if ho.get("transfer_ms_mean") is not None:
            lines.append(f"  {'transfer ms mean/p95':<28}"
                         f"{ho['transfer_ms_mean']} / "
                         f"{ho.get('transfer_ms_p95')}")
        quants = ho.get("by_quant") or {}
        if quants:
            lines.append(f"  {'quant mix':<28}"
                         + ", ".join(f"{k}={v}" for k, v in
                                     sorted(quants.items())))
    # autoscaler decisions (ISSUE 13) — rendered whenever scale events
    # exist, even for a stream with no request records
    sc = (sv or {}).get("scale")
    if sc:
        lines.append("autoscaler")
        lines.append(f"  {'scale events (up/down/repl)':<28}"
                     f"{sc['events']} ({sc['up']}/{sc['down']}/"
                     f"{sc['replace']})")
        lines.append(f"  {'final replicas':<28}{sc['final_replicas']}")
        reasons = sc.get("reasons") or {}
        if reasons:
            lines.append(f"  {'scale reasons':<28}"
                         + ", ".join(f"{k}={v}" for k, v in
                                     sorted(reasons.items())))
    # transport-fault counters (ISSUE 17 satellite) — like the
    # autoscaler block, rendered whenever the evidence exists, even for
    # a stream with no request records
    tr = (sv or {}).get("transport")
    if tr:
        lines.append("transport")
        lines.append(f"  {'retransmits/timeouts/corrupt':<28}"
                     f"{tr.get('retransmits', 0)} / "
                     f"{tr.get('timeouts', tr.get('timeout', 0))} / "
                     f"{tr.get('corrupt_replies', tr.get('corrupt', 0))}")
        if tr.get("errors") is not None:
            lines.append(f"  {'transport errors':<28}{tr['errors']}")
        if tr.get("events"):
            lines.append(f"  {'transport events in stream':<28}"
                         f"{tr['events']}")
    # epoch-fenced membership + chaos plane (ISSUE 20) — rendered
    # whenever fences, re-admissions or degradations happened
    mb = (sv or {}).get("membership")
    if mb and (mb.get("fences") or mb.get("readmitted")
               or mb.get("false_deaths_averted")
               or mb.get("degradations") or mb.get("chaos")):
        lines.append("membership")
        lines.append(f"  {'fences / readmitted':<28}"
                     f"{mb.get('fences', 0)} / {mb.get('readmitted', 0)}")
        if mb.get("false_deaths_averted"):
            lines.append(f"  {'false deaths averted':<28}"
                         f"{mb['false_deaths_averted']}")
        stale = (mb.get("stale_epoch_replies", 0)
                 + mb.get("stale_epoch_handoffs", 0)
                 + mb.get("stale_metric_deltas", 0))
        if stale:
            lines.append(f"  {'stale-epoch discards':<28}{stale} "
                         f"(replies {mb.get('stale_epoch_replies', 0)}, "
                         f"handoffs {mb.get('stale_epoch_handoffs', 0)}, "
                         f"metrics {mb.get('stale_metric_deltas', 0)})")
        reasons = mb.get("fence_reasons") or {}
        if reasons:
            lines.append(f"  {'fence reasons':<28}"
                         + ", ".join(f"{k}={v}" for k, v in
                                     sorted(reasons.items())))
        if mb.get("degradations"):
            lines.append(f"  {'degradations (engaged/rel.)':<28}"
                         f"{mb.get('degradations', 0)}/"
                         f"{mb.get('degrade_releases', 0)}"
                         + (" [degraded now]" if mb.get("degraded")
                            else ""))
        ch = mb.get("chaos")
        if ch:
            lines.append(f"  {'chaos frames drop/delay':<28}"
                         f"{ch.get('frames_dropped', 0)} / "
                         f"{ch.get('frames_delayed', 0)} "
                         f"({ch.get('bytes_dropped', 0)} bytes dropped, "
                         f"{ch.get('delay_injected_s', 0)}s injected)")
    slo = (sv or {}).get("slo")
    if slo:
        lines.append("slo (streaming)")
        lines.append(f"  {'burn rate':<28}{slo.get('burn_rate')} "
                     f"(budget {slo.get('error_budget_pct')}%, window "
                     f"goodput {slo.get('window_goodput_pct')}%)")
        ps = [slo.get(f"ttft_ms_p{p}") for p in (50, 95, 99)]
        if any(v is not None for v in ps):
            lines.append(f"  {'TTFT ms p50/p95/p99 (P2)':<28}"
                         + " / ".join(str(v) for v in ps))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.obs.report",
        description="Run-summary table from a telemetry JSONL "
                    "(throughput, MFU, retraces, overlap, anomalies).")
    p.add_argument("jsonl", help="telemetry JSONL path (a rotated "
                                 "<path>.1 sibling is read automatically)")
    p.add_argument("--json", action="store_true",
                   help="print the summary dict as JSON instead")
    args = p.parse_args(argv)
    try:
        records = load_records(args.jsonl)
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not records:
        print("error: no records parsed", file=sys.stderr)
        return 2
    s = summarize(records)
    print(json.dumps(s) if args.json else format_summary(s))
    return 0


if __name__ == "__main__":
    sys.exit(main())
