"""``python -m paddle_tpu.obs.top`` — live fleet text dashboard
(ISSUE 17, tentpole part 2).

Tails the evidence a serving fleet already writes to disk — heartbeat
files under ``<root>/heartbeats/`` (seq, age, queue depth, free
blocks/slots) and, when given, the telemetry JSONL of terminal request
records — and renders one screenful per interval: a per-replica
liveness table plus the streaming SLO block (:class:`SLOMonitor` over
the tail of the record stream: rolling p50/p95/p99 TTFT/TPOT, goodput,
error-budget burn rate).

Read-only by construction: it opens the same files the fleet writes
atomically and never talks to the fleet process, so it can watch a
drill, a bench run, or a production-style deployment without being
part of it. ``--once`` prints a single frame and exits (the testable
mode; also handy for cron/CI snapshots)::

    python -m paddle_tpu.obs.top --root /tmp/fleet --jsonl tel.jsonl
    python -m paddle_tpu.obs.top --root /tmp/fleet --once

The metrics block (ISSUE 19): when the record stream carries
``kind="metrics"`` registry snapshots (``fleet.emit_stats()`` with
``metrics=True``) — or a live :class:`~paddle_tpu.obs.metrics.
MetricsHub` is passed to :func:`render` — each metric gets one line
with a unicode sparkline: counters render their per-snapshot RATE,
gauges their value history, histograms their bucket distribution.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from ..parallel import multihost
from .report import load_records
from .slo import SLOMonitor, SLOTargets

__all__ = ["render", "main", "sparkline"]

_HB_COLS = ("seq", "queued", "running", "prefilling",
            "pending_new_tokens", "free_blocks", "free_slots")

_SPARK = "▁▂▃▄▅▆▇█"


def _fmt(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.2f}"
    return str(v)


def sparkline(values, width: int = 24) -> str:
    """Render a numeric series as a unicode sparkline, downsampled to
    ``width`` points (evenly strided). Constant series render flat at
    the lowest glyph; empty input renders empty."""
    vals = [float(v) for v in values if v is not None]
    if not vals:
        return ""
    if len(vals) > width:
        step = len(vals) / width
        vals = [vals[int(i * step)] for i in range(width)]
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK[0] * len(vals)
    return "".join(
        _SPARK[min(len(_SPARK) - 1,
                   int((v - lo) / (hi - lo) * len(_SPARK)))]
        for v in vals)


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={v}"
                          for k, v in sorted(labels.items())) + "}"


def _metrics_lines(hub=None,
                   snapshots: Optional[List[List[Dict[str, Any]]]] = None,
                   limit: int = 48) -> List[str]:
    """The metrics block: one ``name{labels}  <spark>  current`` line
    per labeled child. A live hub supplies ring-buffer history; a list
    of ``kind="metrics"`` snapshot payloads supplies per-snapshot
    history (counter lines show successive-difference rates either
    way; histogram lines show the bucket distribution + count/mean)."""
    series: "Dict[Tuple[str, Tuple], Dict[str, Any]]" = {}
    if hub is not None:
        snapshots = (snapshots or []) + [hub.snapshot()]
    for snap in snapshots or []:
        for row in snap:
            key = (row["name"],
                   tuple(sorted((row.get("labels") or {}).items())))
            ent = series.setdefault(
                key, {"type": row["type"],
                      "labels": dict(row.get("labels") or {}),
                      "values": [], "last": None})
            ent["last"] = row
            if row["type"] == "histogram":
                continue
            ent["values"].append(row.get("value"))
    if hub is not None:
        # ring-buffer history beats per-snapshot history when live
        for (name, lkey), ent in series.items():
            for q in hub.query(name, **dict(lkey)):
                if tuple(sorted(q["labels"].items())) == lkey:
                    vals = [v for _, v in q["samples"]]
                    if vals:
                        ent["values"] = vals
    lines: List[str] = []
    for (name, _), ent in sorted(series.items()):
        row = ent["last"]
        label = name + _label_str(ent["labels"])
        if ent["type"] == "histogram":
            counts = row.get("counts") or []
            cnt = row.get("count") or 0
            mean = (row["sum"] / cnt) if cnt else None
            lines.append(f"  {label:<44} {sparkline(counts):<24} "
                         f"n={cnt} mean={_fmt(mean)}")
            continue
        vals = [v for v in ent["values"] if v is not None]
        if ent["type"] == "counter":
            # rate: successive differences of the cumulative value
            deltas = [b - a for a, b in zip(vals, vals[1:])] or vals
            cur = vals[-1] if vals else None
            lines.append(f"  {label:<44} {sparkline(deltas):<24} "
                         f"total={_fmt(cur)}")
        else:
            cur = vals[-1] if vals else None
            lines.append(f"  {label:<44} {sparkline(vals):<24} "
                         f"now={_fmt(cur)}")
        if len(lines) >= limit:
            lines.append(f"  ... ({len(series) - limit} more)")
            break
    return lines


def render(root: Optional[str] = None, jsonl: Optional[str] = None, *,
           now: Optional[float] = None, window: int = 256,
           targets: Optional[SLOTargets] = None, hub=None) -> str:
    """One dashboard frame as a string (pure function of the files —
    what ``--once`` prints and what the test asserts on). ``hub``: an
    optional live :class:`~paddle_tpu.obs.metrics.MetricsHub` to render
    the metrics block from directly (in-process dashboards/tests); the
    JSONL's ``kind="metrics"`` snapshots feed it otherwise."""
    now = time.time() if now is None else float(now)
    lines: List[str] = ["== paddle_tpu fleet top =="]
    beats: Dict[int, Dict] = (
        multihost.read_heartbeats(root) if root else {})
    if beats:
        hdr = ["replica", "age_s"] + list(_HB_COLS)
        rows = [hdr]
        for hid in sorted(beats):
            b = beats[hid]
            age = now - float(b.get("ts") or b.get("_mtime") or now)
            rows.append([str(hid), f"{age:.2f}"]
                        + [_fmt(b.get(c)) for c in _HB_COLS])
        widths = [max(len(r[i]) for r in rows)
                  for i in range(len(hdr))]
        for r in rows:
            lines.append("  " + "  ".join(
                c.rjust(w) for c, w in zip(r, widths)))
    else:
        lines.append("  (no heartbeats under "
                     f"{root!r})" if root else "  (no --root given)")
    snapshots: List[List[Dict[str, Any]]] = []
    if jsonl:
        mon = SLOMonitor(targets=targets, window=window)
        transport = 0
        fences = 0
        degrades = 0
        fleet_rec: Optional[Dict[str, Any]] = None
        try:
            records = load_records(jsonl)
        except OSError:
            records = []
        for rec in records:
            mon.observe(rec)
            if rec.get("kind") == "transport":
                transport += 1
            elif (rec.get("kind") == "fence"
                    and rec.get("source") != "replica"):
                # the child re-emits its own fence record post-readmit
                # (source="replica") — count the parent's verdicts only
                fences += 1
            elif rec.get("kind") == "degrade":
                degrades += 1
            elif rec.get("kind") == "fleet":
                fleet_rec = rec
            elif (rec.get("kind") == "metrics"
                    and rec.get("metrics") is not None):
                snapshots.append(rec["metrics"])
        rep = mon.report()
        lines.append("-- slo (streaming) --")
        lines.append(
            f"  requests={rep['requests']} retried="
            f"{rep['retried_attempts']} transport_events={transport}")
        lines.append(
            f"  goodput={_fmt(rep['goodput_pct'])}% window="
            f"{_fmt(rep['window_goodput_pct'])}% "
            f"burn_rate={_fmt(rep['burn_rate'])} "
            f"(budget {rep['error_budget_pct']}%)")
        for m in ("ttft_ms", "tpot_ms", "wall_ms"):
            lines.append(
                f"  {m}: p50={_fmt(rep[m + '_p50'])} "
                f"p95={_fmt(rep[m + '_p95'])} "
                f"p99={_fmt(rep[m + '_p99'])}")
        if rep["finish_reasons"]:
            reasons = " ".join(f"{k}={v}" for k, v in
                               sorted(rep["finish_reasons"].items()))
            lines.append(f"  finish: {reasons}")
        mb = (fleet_rec or {}).get("membership") or {}
        if fences or degrades or mb.get("readmitted") \
                or mb.get("false_deaths_averted"):
            # epoch-fenced membership (ISSUE 20): the fence/readmit
            # ledger plus the partition-degradation state, one line
            lines.append(
                f"  membership: fences={fences} "
                f"readmitted={mb.get('readmitted', 0)} "
                f"false_deaths_averted="
                f"{mb.get('false_deaths_averted', 0)} "
                f"degrade_events={degrades}"
                + (" [DEGRADED]" if mb.get("degraded") else ""))
        ch = (fleet_rec or {}).get("chaos") or {}
        if ch.get("frames_dropped") or ch.get("frames_delayed"):
            lines.append(
                f"  chaos: dropped={ch.get('frames_dropped', 0)} "
                f"delayed={ch.get('frames_delayed', 0)} "
                f"bytes_dropped={ch.get('bytes_dropped', 0)} "
                f"delay_s={ch.get('delay_injected_s', 0)}")
    if hub is not None or snapshots:
        mlines = _metrics_lines(hub=hub, snapshots=snapshots)
        if mlines:
            lines.append("-- metrics (registry) --")
            lines.extend(mlines)
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.obs.top",
        description="Live fleet dashboard over heartbeat files + "
                    "telemetry JSONL (read-only).")
    ap.add_argument("--root", default=None,
                    help="fleet root (reads <root>/heartbeats/)")
    ap.add_argument("--jsonl", default=None,
                    help="telemetry JSONL of request records")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="refresh period in seconds")
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit")
    ap.add_argument("--window", type=int, default=256,
                    help="burn-rate rolling window (requests)")
    ap.add_argument("--slo-goodput", type=float, default=99.0,
                    help="goodput objective in percent")
    ap.add_argument("--slo-ttft-ms", type=float, default=None,
                    help="optional absolute TTFT target (ms)")
    args = ap.parse_args(argv)
    targets = SLOTargets(goodput_pct=args.slo_goodput,
                         ttft_ms=args.slo_ttft_ms)
    while True:
        frame = render(args.root, args.jsonl, window=args.window,
                       targets=targets)
        if args.once:
            print(frame)
            return 0
        # clear + home, then the frame — plain ANSI, no curses dep
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":                      # pragma: no cover
    raise SystemExit(main())
