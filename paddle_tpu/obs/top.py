"""``python -m paddle_tpu.obs.top`` — live fleet text dashboard
(ISSUE 17, tentpole part 2).

Tails the evidence a serving fleet already writes to disk — heartbeat
files under ``<root>/heartbeats/`` (seq, age, queue depth, free
blocks/slots) and, when given, the telemetry JSONL of terminal request
records — and renders one screenful per interval: a per-replica
liveness table plus the streaming SLO block (:class:`SLOMonitor` over
the tail of the record stream: rolling p50/p95/p99 TTFT/TPOT, goodput,
error-budget burn rate).

Read-only by construction: it opens the same files the fleet writes
atomically and never talks to the fleet process, so it can watch a
drill, a bench run, or a production-style deployment without being
part of it. ``--once`` prints a single frame and exits (the testable
mode; also handy for cron/CI snapshots)::

    python -m paddle_tpu.obs.top --root /tmp/fleet --jsonl tel.jsonl
    python -m paddle_tpu.obs.top --root /tmp/fleet --once
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Any, Dict, List, Optional

from ..parallel import multihost
from .report import load_records
from .slo import SLOMonitor, SLOTargets

__all__ = ["render", "main"]

_HB_COLS = ("seq", "queued", "running", "prefilling",
            "pending_new_tokens", "free_blocks", "free_slots")


def _fmt(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.2f}"
    return str(v)


def render(root: Optional[str] = None, jsonl: Optional[str] = None, *,
           now: Optional[float] = None, window: int = 256,
           targets: Optional[SLOTargets] = None) -> str:
    """One dashboard frame as a string (pure function of the files —
    what ``--once`` prints and what the test asserts on)."""
    now = time.time() if now is None else float(now)
    lines: List[str] = ["== paddle_tpu fleet top =="]
    beats: Dict[int, Dict] = (
        multihost.read_heartbeats(root) if root else {})
    if beats:
        hdr = ["replica", "age_s"] + list(_HB_COLS)
        rows = [hdr]
        for hid in sorted(beats):
            b = beats[hid]
            age = now - float(b.get("ts") or b.get("_mtime") or now)
            rows.append([str(hid), f"{age:.2f}"]
                        + [_fmt(b.get(c)) for c in _HB_COLS])
        widths = [max(len(r[i]) for r in rows)
                  for i in range(len(hdr))]
        for r in rows:
            lines.append("  " + "  ".join(
                c.rjust(w) for c, w in zip(r, widths)))
    else:
        lines.append("  (no heartbeats under "
                     f"{root!r})" if root else "  (no --root given)")
    if jsonl:
        mon = SLOMonitor(targets=targets, window=window)
        transport = 0
        try:
            records = load_records(jsonl)
        except OSError:
            records = []
        for rec in records:
            mon.observe(rec)
            if rec.get("kind") == "transport":
                transport += 1
        rep = mon.report()
        lines.append("-- slo (streaming) --")
        lines.append(
            f"  requests={rep['requests']} retried="
            f"{rep['retried_attempts']} transport_events={transport}")
        lines.append(
            f"  goodput={_fmt(rep['goodput_pct'])}% window="
            f"{_fmt(rep['window_goodput_pct'])}% "
            f"burn_rate={_fmt(rep['burn_rate'])} "
            f"(budget {rep['error_budget_pct']}%)")
        for m in ("ttft_ms", "tpot_ms", "wall_ms"):
            lines.append(
                f"  {m}: p50={_fmt(rep[m + '_p50'])} "
                f"p95={_fmt(rep[m + '_p95'])} "
                f"p99={_fmt(rep[m + '_p99'])}")
        if rep["finish_reasons"]:
            reasons = " ".join(f"{k}={v}" for k, v in
                               sorted(rep["finish_reasons"].items()))
            lines.append(f"  finish: {reasons}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.obs.top",
        description="Live fleet dashboard over heartbeat files + "
                    "telemetry JSONL (read-only).")
    ap.add_argument("--root", default=None,
                    help="fleet root (reads <root>/heartbeats/)")
    ap.add_argument("--jsonl", default=None,
                    help="telemetry JSONL of request records")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="refresh period in seconds")
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit")
    ap.add_argument("--window", type=int, default=256,
                    help="burn-rate rolling window (requests)")
    ap.add_argument("--slo-goodput", type=float, default=99.0,
                    help="goodput objective in percent")
    ap.add_argument("--slo-ttft-ms", type=float, default=None,
                    help="optional absolute TTFT target (ms)")
    args = ap.parse_args(argv)
    targets = SLOTargets(goodput_pct=args.slo_goodput,
                         ttft_ms=args.slo_ttft_ms)
    while True:
        frame = render(args.root, args.jsonl, window=args.window,
                       targets=targets)
        if args.once:
            print(frame)
            return 0
        # clear + home, then the frame — plain ANSI, no curses dep
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":                      # pragma: no cover
    raise SystemExit(main())
