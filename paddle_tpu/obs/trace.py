"""Structured tracing for the pipelined hot loop — thread-aware spans
emitted as Chrome Trace Event Format JSON (ISSUE 4 tentpole).

PR 2's telemetry answers "how fast is each call"; PR 3 made the answer
multi-threaded (main loop + `GroupStager` thread + `data.buffered` fill
thread). A scalar-per-call JSONL cannot show *where* the overlap breaks
down — that needs a timeline a human can open. This module records
begin/end spans per thread and serializes them in the Trace Event Format
that Perfetto (https://ui.perfetto.dev) and `chrome://tracing` consume —
the same container the JAX/XLA profiler ecosystem standardized on, so the
host-side story lines up with device profiles side by side.

Design rules:

- **Spans are host-side and cheap.** One `perf_counter_ns` pair + one
  locked deque append per span; no device interaction, no fences, no
  extra dispatches. The Trainer guards every span behind ``tracer is
  None`` (via :func:`tspan`), so tracing off is the byte-identical hot
  loop (``tests/test_trace.py`` pins it).
- **Thread-aware by construction.** Events carry the OS thread id;
  ``thread_name`` metadata events name the main loop, the
  ``host_pipeline.stager`` thread, and the ``data.buffered.fill`` thread
  in the viewer.
- **Flow events link a group across threads.** A staged group's life —
  stack+shard on the stager thread, dispatch on the main thread, drain
  later still — is connected with ``s``/``t``/``f`` flow events sharing
  one flow id, so host/device overlap (or its absence) is visually
  auditable: the arrows cross threads exactly where the pipeline hides
  work.
- **Bounded memory.** The event buffer is a ring (``max_events``);
  long runs keep the most recent window, which is also what the anomaly
  flight recorder snapshots into a forensics bundle
  (:mod:`paddle_tpu.obs.anomaly`).

Usage::

    from paddle_tpu.obs import Tracer
    tracer = Tracer()
    trainer = Trainer(..., telemetry=tel, tracer=tracer)
    trainer.train(...)
    tracer.save("trace.json")      # open in ui.perfetto.dev

Programmatic device-profiler windows ride the same API:
``tracer.profile_window(log_dir)`` wraps a code region in
``jax.profiler.trace`` (TensorBoard/XProf capture) *and* a host span, so
the device capture is findable from the host timeline.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["Tracer", "tspan", "jax_profile"]

_log = logging.getLogger("paddle_tpu.trace")

# Shared no-op context for tracer-off call sites: stateless, so one
# instance is safe across threads and reentrant use.
_NULL = contextlib.nullcontext()


def tspan(tracer: Optional["Tracer"], name: str, **kw):
    """Null-safe span helper: a real ``tracer.span(...)`` when tracing is
    on, the shared no-op context when ``tracer`` is None — the hot loop
    never branches further than this."""
    if tracer is None:
        return _NULL
    return tracer.span(name, **kw)


@contextlib.contextmanager
def jax_profile(log_dir: str):
    """Best-effort programmatic ``jax.profiler`` capture window. A
    backend/profiler failure (double start, unsupported transport) is
    logged and the body still runs — a diagnostic capture must never
    kill the training it diagnoses."""
    import jax
    started = False
    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception:                        # pragma: no cover - backend
        _log.exception("jax.profiler.start_trace(%r) failed; continuing "
                       "without device capture", log_dir)
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:                # pragma: no cover - backend
                _log.exception("jax.profiler.stop_trace failed")


def _json_safe(v):
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return str(v)


class Tracer:
    """Thread-aware span recorder emitting Chrome Trace Event Format.

    Every finished span becomes one complete (``ph="X"``) event with
    microsecond ``ts``/``dur`` relative to the tracer's construction;
    optional flow ids attach ``s``/``t``/``f`` flow events at the span's
    start timestamp (inside the slice, so viewers bind the arrow to it).
    All methods are thread-safe; spans may begin and end on any thread
    (each span's events carry the thread it ran on).

    Args:
      max_events: ring-buffer bound on retained events (oldest dropped;
        ``dropped_events`` counts evictions). Metadata (process/thread
        names) is kept separately and never evicted.
      clock: optional injectable clock (``callable() -> seconds``).
        When set, every timestamp is ``clock() * 1e6`` — an ABSOLUTE
        microsecond time base shared by whoever else reads the same
        clock. This is the fleet-tracing mode (ISSUE 17): parent and
        child replicas all stamp spans with the message-carried fleet
        clock, so a SimClock drill's merged timeline is deterministic
        and cross-process spans land on one comparable axis. Default
        (None) keeps the PR-4 behavior: ``perf_counter_ns`` relative to
        tracer construction.
    """

    def __init__(self, max_events: int = 200_000, clock=None):
        self.pid = os.getpid()
        self._clock = clock
        self._t0 = time.perf_counter_ns()
        self._events: collections.deque = collections.deque(
            maxlen=int(max_events))
        self._meta: List[Dict[str, Any]] = [
            {"ph": "M", "name": "process_name", "pid": self.pid, "tid": 0,
             "args": {"name": "paddle_tpu"}}]
        self._lock = threading.Lock()
        self._seen_threads: Dict[int, str] = {}
        self._flow_seq = itertools.count(1)
        self.dropped_events = 0

    # -- clock / bookkeeping -------------------------------------------------

    def _now_us(self) -> float:
        if self._clock is not None:
            return float(self._clock()) * 1e6
        return (time.perf_counter_ns() - self._t0) / 1e3

    def now_us(self) -> float:
        """The tracer's current timestamp (us) — for callers recording
        already-timed spans via :meth:`complete`."""
        return self._now_us()

    def _note_thread(self, tid: int) -> None:
        # Compare the LIVE name every call, not just first-seen: OS thread
        # idents are recycled (a per-pass stager thread can inherit the
        # ident of pass 1's dead fill thread), and a stale cache would
        # merge two distinct threads' spans onto one mislabelled track.
        name = threading.current_thread().name
        if self._seen_threads.get(tid) == name:
            return
        with self._lock:
            if self._seen_threads.get(tid) != name:
                self._seen_threads[tid] = name
                self._meta.append(
                    {"ph": "M", "name": "thread_name", "pid": self.pid,
                     "tid": tid, "args": {"name": name}})

    def _append(self, evs: List[Dict[str, Any]]) -> None:
        with self._lock:
            room = self._events.maxlen - len(self._events)
            if room < len(evs):
                self.dropped_events += len(evs) - room
            self._events.extend(evs)

    # -- recording -----------------------------------------------------------

    def new_flow(self) -> int:
        """A fresh flow id for linking spans across threads."""
        return next(self._flow_seq)

    @contextlib.contextmanager
    def span(self, name: str, flow_start: Optional[int] = None,
             flow_step: Optional[int] = None, flow_end: Optional[int] = None,
             **args):
        """Record one span around the ``with`` body. ``flow_start`` /
        ``flow_step`` / ``flow_end`` emit the matching flow event (phases
        ``s``/``t``/``f``) bound to this span, linking it to the other
        spans carrying the same id."""
        tid = threading.get_ident()
        self._note_thread(tid)
        t0 = self._now_us()
        try:
            yield
        finally:
            self._emit_span(name, tid, t0, self._now_us(), flow_start,
                            flow_step, flow_end, args)

    def complete(self, name: str, t0_us: float,
                 t1_us: Optional[float] = None,
                 flow_start: Optional[int] = None,
                 flow_step: Optional[int] = None,
                 flow_end: Optional[int] = None, **args) -> None:
        """Record an ALREADY-TIMED span with explicit microsecond
        timestamps (the tracer's time base — with an injected clock,
        ``seconds * 1e6``). This is how retroactive spans are stamped:
        a scheduler records a request's queue wait only at admit time,
        from the request's own submit timestamp (ISSUE 17)."""
        tid = threading.get_ident()
        self._note_thread(tid)
        self._emit_span(name, tid, float(t0_us),
                        float(t0_us if t1_us is None else t1_us),
                        flow_start, flow_step, flow_end, args)

    def _emit_span(self, name, tid, t0, t1, flow_start, flow_step,
                   flow_end, args) -> None:
        ev: Dict[str, Any] = {
            "ph": "X", "name": name, "cat": "paddle_tpu",
            "pid": self.pid, "tid": tid,
            "ts": t0, "dur": max(t1 - t0, 0.001)}
        if args:
            ev["args"] = {k: _json_safe(v) for k, v in args.items()}
        evs = [ev]
        for fid, ph in ((flow_start, "s"), (flow_step, "t"),
                        (flow_end, "f")):
            if fid is None:
                continue
            fe = {"ph": ph, "name": "group", "cat": "flow",
                  "id": int(fid), "pid": self.pid, "tid": tid, "ts": t0}
            if ph == "f":
                fe["bp"] = "e"       # bind to the enclosing slice
            evs.append(fe)
        self._append(evs)

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker (``ph="i"``) — e.g. an anomaly verdict
        pinned onto the timeline at trigger time."""
        tid = threading.get_ident()
        self._note_thread(tid)
        ev: Dict[str, Any] = {
            "ph": "i", "name": name, "cat": "paddle_tpu", "s": "t",
            "pid": self.pid, "tid": tid, "ts": self._now_us()}
        if args:
            ev["args"] = {k: _json_safe(v) for k, v in args.items()}
        self._append([ev])

    @contextlib.contextmanager
    def profile_window(self, log_dir: str, name: str = "jax_profile"):
        """A ``jax.profiler.trace`` capture window recorded as a host span
        too, so the device capture is findable from the host timeline.
        Lazy like any context manager: nothing starts until ``with``
        entry (an unused return value must not leave the device profiler
        running)."""
        with self.span(name, log_dir=log_dir), jax_profile(log_dir):
            yield

    # -- output --------------------------------------------------------------

    def events(self) -> List[Dict[str, Any]]:
        """Snapshot: metadata events + every retained span/flow event."""
        with self._lock:
            return list(self._meta) + list(self._events)

    def drain_events(self) -> List[Dict[str, Any]]:
        """Pop every buffered span/flow/instant event (metadata stays).
        The child→parent span-batch shipping primitive (ISSUE 17): a
        process replica drains its tracer into each tick reply, so
        spans ride the transport the work already uses — no
        side-channel files, nothing to garbage-collect on a SIGKILL."""
        with self._lock:
            evs = list(self._events)
            self._events.clear()
        return evs

    def tail(self, n: int) -> List[Dict[str, Any]]:
        """Metadata + the most recent ``n`` events (the flight-recorder
        window); ``n <= 0`` returns metadata only (``[-0:]`` would be the
        whole ring)."""
        with self._lock:
            evs = list(self._events)
        n = int(n)
        return list(self._meta) + (evs[-n:] if n > 0 else [])

    def chrome_trace(self, events: Optional[List[Dict[str, Any]]] = None
                     ) -> Dict[str, Any]:
        """The Trace Event Format container (`traceEvents` sorted by
        timestamp — viewers do not require it, but the bench gate checks
        monotonicity on exactly this serialization)."""
        evs = self.events() if events is None else list(events)
        evs.sort(key=lambda e: e.get("ts", -1.0))
        clock = ("injected clock (absolute us)"
                 if self._clock is not None else
                 "perf_counter_ns (us since tracer construction)")
        return {"traceEvents": evs, "displayTimeUnit": "ms",
                "otherData": {"producer": "paddle_tpu.obs.trace",
                              "clock": clock,
                              "dropped_events": self.dropped_events}}

    def save(self, path: str) -> str:
        """Write the Chrome trace JSON (open in ui.perfetto.dev)."""
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
