"""Observability subsystem — the ``Stat.h``/``REGISTER_TIMER`` successor
for the fused hot loop (ISSUE 2) plus structured tracing and the
anomaly-triggered flight recorder (ISSUE 4).

The layers:

- :mod:`~paddle_tpu.obs.sinks` — pluggable record consumers (in-memory,
  JSONL file, logging); ``emit`` is thread-safe (stager/fill threads
  write too).
- :mod:`~paddle_tpu.obs.health` — device-side training-health scalars
  (grad/param/update norms, update ratio, NaN/Inf sentinel) traced into
  the compiled step.
- :mod:`~paddle_tpu.obs.telemetry` — the :class:`Telemetry` object the
  Trainer drives: per-call step-time breakdown (host stack / shard /
  dispatch / fenced device / events-replay), retrace+compile tracking
  keyed by step fingerprint with HLO cost-analysis FLOPs, MFU and
  tokens/sec accounting, and device-memory peak sampling.
- :mod:`~paddle_tpu.obs.trace` — :class:`Tracer`: thread-aware spans
  emitted as Chrome Trace Event Format JSON (Perfetto-viewable), with
  flow events linking a group's stager-thread staging to its main-thread
  dispatch and drain, and programmatic ``jax.profiler`` capture windows.
- :mod:`~paddle_tpu.obs.anomaly` — :class:`AnomalyDetector`: rolling
  robust statistics over the telemetry stream (slow-step outliers,
  retrace bursts, drain stalls, memory high-water, the NaN sentinel);
  on trigger, a one-shot forensics bundle (telemetry ring + trace tail +
  config/env/mesh snapshot + verdict) lands on disk.
- :mod:`~paddle_tpu.obs.hloprof` + :mod:`~paddle_tpu.obs.attribution`
  (ISSUE 6) — the device-side attribution layer: a structured parser
  over the compiled step's optimized HLO (per-op FLOPs/bytes, named-
  scope paths, loop trip counts, collective inventory) feeding a
  per-scope roofline / MFU-gap report with an exposed-vs-overlappable
  communication estimate, joined with measured ``jax.profiler``
  device lanes when a capture exists (``Trainer.attribution_report``).
- :mod:`~paddle_tpu.obs.report` — ``python -m paddle_tpu.obs.report
  run.jsonl``: run-summary table (throughput, MFU, retraces, overlap,
  anomalies) from a telemetry JSONL.
- The fleet observability plane (ISSUE 17):
  :mod:`~paddle_tpu.obs.fleet_trace` merges router + per-replica span
  batches (shipped back on tick replies, stamped with the one fleet
  clock) into a single Chrome/Perfetto timeline with per-replica lanes
  and rid-keyed flows; :mod:`~paddle_tpu.obs.slo` streams rolling
  P²-estimated p50/p95/p99 TTFT/TPOT, goodput, and error-budget burn
  rate over terminal request records (``fleet.slo_report()``);
  ``python -m paddle_tpu.obs.top`` is the live text dashboard over
  heartbeat files + telemetry JSONL; and
  :class:`~paddle_tpu.obs.anomaly.ServingAnomalyDetector` extends the
  flight recorder with per-replica serving kinds (tick stall, accept/
  prefix-hit collapse, retransmit burst, queue divergence).

:mod:`~paddle_tpu.obs.xla_flags` (ISSUE 8) assembles the opt-in TPU
async-collective / latency-hiding XLA flag set (documented provenance +
caveats) that lets the backend actually float the bucketed gradient
all-reduces under the backward pass.

Attach with ``Trainer(..., telemetry=Telemetry(sinks=[JsonlSink(path)]),
tracer=Tracer(), anomaly=AnomalyDetector(out_dir))``. With none attached
the hot loop is unchanged: same traced step, same dispatch count, same
donation, zero extra device fetches.
"""

from . import attribution, hloprof, xla_cache, xla_flags
from .anomaly import (ANOMALY_KINDS, SERVING_ANOMALY_KINDS,
                      AnomalyDetector, ServingAnomalyDetector, Verdict)
from .attribution import build_report, format_report, parse_profile_trace
from .fleet_trace import (flow_connected, flow_summary, lane_monotonic,
                          merge_fleet_trace, save_fleet_trace)
from .hloprof import (DCN_BYTES_PER_S, HBM_BANDWIDTH, ICI_BANDWIDTH,
                      collective_inventory, parse_collectives, parse_module)
from .health import (HEALTH_KEYS, health_scalars, tree_l2_norm,
                     tree_nonfinite_count)
from .metrics import (Counter, Gauge, Histogram, MetricsHub,
                      log_buckets, parse_exposition)
from .percentiles import (GOODPUT_REASONS, P2Quantile, percentile,
                          summarize_handoffs, summarize_requests,
                          summarize_scale)
from .sinks import InMemorySink, JsonlSink, LoggingSink, Sink
from .slo import SLOMonitor, SLOTargets
from .telemetry import (PEAK_FLOPS, Telemetry, device_memory_stats,
                        device_peak_flops, lowered_hlo_flops)
from .trace import Tracer, jax_profile, tspan

__all__ = [
    "Telemetry", "Sink", "InMemorySink", "JsonlSink", "LoggingSink",
    "HEALTH_KEYS", "health_scalars", "tree_l2_norm", "tree_nonfinite_count",
    "PEAK_FLOPS", "device_peak_flops", "lowered_hlo_flops",
    "device_memory_stats",
    "Tracer", "tspan", "jax_profile",
    "AnomalyDetector", "ServingAnomalyDetector", "Verdict",
    "ANOMALY_KINDS", "SERVING_ANOMALY_KINDS",
    "hloprof", "attribution", "xla_flags",
    "parse_module", "collective_inventory", "parse_collectives",
    "build_report", "format_report", "parse_profile_trace",
    "ICI_BANDWIDTH", "DCN_BYTES_PER_S", "HBM_BANDWIDTH",
    "percentile", "P2Quantile", "summarize_requests", "summarize_scale",
    "summarize_handoffs", "GOODPUT_REASONS",
    "SLOMonitor", "SLOTargets",
    "MetricsHub", "Counter", "Gauge", "Histogram", "log_buckets",
    "parse_exposition",
    "merge_fleet_trace", "save_fleet_trace", "flow_summary",
    "flow_connected", "lane_monotonic",
]
