"""Observability subsystem — the ``Stat.h``/``REGISTER_TIMER`` successor
for the fused hot loop (ISSUE 2).

Three layers:

- :mod:`~paddle_tpu.obs.sinks` — pluggable record consumers (in-memory,
  JSONL file, logging).
- :mod:`~paddle_tpu.obs.health` — device-side training-health scalars
  (grad/param/update norms, update ratio, NaN/Inf sentinel) traced into
  the compiled step.
- :mod:`~paddle_tpu.obs.telemetry` — the :class:`Telemetry` object the
  Trainer drives: per-call step-time breakdown (host stack / shard /
  dispatch / fenced device / events-replay), retrace+compile tracking
  keyed by step fingerprint with HLO cost-analysis FLOPs, MFU and
  tokens/sec accounting, and device-memory peak sampling.

Attach with ``Trainer(..., telemetry=Telemetry(sinks=[JsonlSink(path)]))``.
With no Telemetry attached the hot loop is unchanged: same traced step,
same dispatch count, same donation, zero extra device fetches.
"""

from .health import (HEALTH_KEYS, health_scalars, tree_l2_norm,
                     tree_nonfinite_count)
from .sinks import InMemorySink, JsonlSink, LoggingSink, Sink
from .telemetry import (PEAK_FLOPS, Telemetry, device_memory_stats,
                        device_peak_flops, lowered_hlo_flops)

__all__ = [
    "Telemetry", "Sink", "InMemorySink", "JsonlSink", "LoggingSink",
    "HEALTH_KEYS", "health_scalars", "tree_l2_norm", "tree_nonfinite_count",
    "PEAK_FLOPS", "device_peak_flops", "lowered_hlo_flops",
    "device_memory_stats",
]
