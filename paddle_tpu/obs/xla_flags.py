"""Opt-in XLA flag assembly for TPU collective/compute overlap (ISSUE 8).

``Trainer(grad_sync="bucketed")`` gives the scheduler per-bucket
all-reduces it CAN float under the backward — whether it actually does
is the backend scheduler's call. On TPU, XLA's async-collective fusion
and latency-hiding scheduler are what turn an eligible schedule into an
overlapped one; several of the relevant passes sit behind flags. This
module assembles that flag set with documented provenance so a run
script does::

    from paddle_tpu.obs import xla_flags
    xla_flags.apply_overlap_flags()        # BEFORE importing/initializing jax
    # or: XLA_FLAGS="$(python -m paddle_tpu.obs.xla_flags)" python train.py

CAVEATS (read before enabling):

- Flags are parsed ONCE at backend initialization: `apply_overlap_flags`
  must run before jax creates its TPU client, or the flags are silently
  ignored (the helper warns when jax looks initialized).
- These are ``--xla_tpu_*`` / scheduler tunables, NOT stable API: names
  drift across libtpu releases, and an unknown flag aborts the runtime.
  `strict=False` (default) keeps only the conservative core set; pass
  `strict=True` to get everything and accept the version risk.
- On CPU/GPU backends the TPU flags are inert at best; the helper is a
  no-op unless ``force=True``.

Provenance of the set (public sources, same pattern as the
``PEAK_FLOPS``/``ICI_BANDWIDTH`` tables):

- ``xla_tpu_enable_async_collective_fusion*`` and
  ``xla_tpu_overlap_compute_collective_tc`` — the async-collective +
  compute/collective overlap set published in Google's MaxText/
  accelerator-microbenchmark repos as the TPU performance baseline.
- ``xla_tpu_enable_data_parallel_all_reduce_opt`` and
  ``xla_tpu_data_parallel_opt_different_sized_ops`` — dp all-reduce
  scheduling optimizations from the same set (precisely the gradient
  all-reduce this PR buckets).
- ``xla_enable_async_all_gather`` / ``xla_enable_async_collective_permute``
  — async lowering of the remaining collective kinds (XLA flag registry,
  ``xla/debug_options_flags.cc``).
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Dict, List, Optional

_log = logging.getLogger("paddle_tpu.obs.xla_flags")

__all__ = ["OVERLAP_FLAGS", "EXTENDED_FLAGS", "overlap_flags",
           "merge_xla_flags", "apply_overlap_flags"]

# The conservative core: async collective fusion + compute/collective
# overlap + dp all-reduce scheduling. Widely exercised together on
# v4/v5e/v5p-era libtpu.
OVERLAP_FLAGS: Dict[str, str] = {
    "--xla_tpu_enable_async_collective_fusion": "true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather": "true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps": "true",
    "--xla_tpu_overlap_compute_collective_tc": "true",
    "--xla_tpu_enable_data_parallel_all_reduce_opt": "true",
    "--xla_tpu_data_parallel_opt_different_sized_ops": "true",
}

# The version-riskier extras (strict=True): async lowering for the
# non-all-reduce collective kinds.
EXTENDED_FLAGS: Dict[str, str] = {
    "--xla_enable_async_all_gather": "true",
    "--xla_enable_async_collective_permute": "true",
}


def overlap_flags(strict: bool = False) -> List[str]:
    """The overlap flag set as ``--flag=value`` strings. ``strict=True``
    appends the extended set (see module docstring for the risk note)."""
    flags = dict(OVERLAP_FLAGS)
    if strict:
        flags.update(EXTENDED_FLAGS)
    return [f"{k}={v}" for k, v in flags.items()]


def merge_xla_flags(new_flags: List[str],
                    existing: Optional[str] = None) -> str:
    """Merge flags into an XLA_FLAGS string. An operator-set value for
    the same flag WINS (the helper must never silently override an
    explicit choice); order is existing-first."""
    existing = existing if existing is not None \
        else os.environ.get("XLA_FLAGS", "")
    have = {f.split("=", 1)[0] for f in existing.split() if f}
    merged = [f for f in existing.split() if f]
    merged += [f for f in new_flags if f.split("=", 1)[0] not in have]
    return " ".join(merged)


def _jax_initialized() -> bool:
    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        # xla_bridge caches live clients post-init; inspect without
        # triggering initialization ourselves
        from jax._src import xla_bridge
        backends = getattr(xla_bridge, "_backends", None)
        if backends is None:
            # probe point moved in a newer jax: assume the worst (a
            # silent False here would defeat the warning this helper
            # exists to give)
            return True
        return bool(backends)
    except Exception:
        return True      # jax imported and unprobeable: assume the worst


def apply_overlap_flags(strict: bool = False, force: bool = False,
                        env: Optional[Dict[str, str]] = None) -> str:
    """Merge the overlap set into ``env['XLA_FLAGS']`` (default
    ``os.environ``) and return the resulting string. No-op (with a log
    line) unless a TPU looks reachable (``JAX_PLATFORMS``/``TPU_*`` env
    hints) or ``force=True``; warns when jax already initialized a
    backend — at that point the flags cannot take effect in this
    process."""
    env = os.environ if env is None else env
    hints = env.get("JAX_PLATFORMS", "")
    tpu_likely = ("tpu" in hints.lower()
                  or any(k.startswith(("TPU_", "LIBTPU")) for k in env))
    if not (tpu_likely or force):
        _log.info("apply_overlap_flags: no TPU hints in the environment "
                  "and force=False — leaving XLA_FLAGS untouched")
        return env.get("XLA_FLAGS", "")
    if _jax_initialized():
        _log.warning(
            "apply_overlap_flags: jax has already initialized a backend — "
            "XLA_FLAGS changes will NOT take effect in this process; set "
            "them before importing jax (or via the shell)")
    merged = merge_xla_flags(overlap_flags(strict=strict),
                             existing=env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = merged
    return merged


if __name__ == "__main__":    # XLA_FLAGS="$(python -m paddle_tpu.obs.xla_flags)"
    strict = "--strict" in sys.argv[1:]
    print(merge_xla_flags(overlap_flags(strict=strict)))
