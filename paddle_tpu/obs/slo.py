"""Streaming SLO monitor over terminal request records (ISSUE 17,
tentpole part 2).

:func:`~paddle_tpu.obs.percentiles.summarize_requests` is a batch
aggregate — it answers "how did the run go" after every record is in
hand. A serving fleet needs the LIVE form of the same vocabulary:
rolling p50/p95/p99 TTFT/TPOT/wall (P² streaming estimators, O(1)
memory per quantile), goodput, and the **error-budget burn rate** an
SLO-derivative autoscaler steers on (the ROADMAP's named consumer —
:meth:`~paddle_tpu.serve.fleet.ServingFleet.stats` publishes the
signal).

Burn-rate semantics (the SRE convention, windowed):

- The objective is ``targets.goodput_pct`` — at least that percentage
  of terminal requests must be *good*: finished (``"length"``/
  ``"eos"``), within their deadline when they carried one, and within
  the optional absolute TTFT/TPOT targets.
- The **error budget** is the allowed bad fraction,
  ``1 - goodput_pct/100``.
- The **burn rate** is the observed bad fraction over the rolling
  window divided by the budget: 1.0 means the fleet is consuming its
  budget exactly as fast as allowed; >1 means overspending (an alert /
  scale-up signal); 0 means no bad requests in the window.

Record semantics match :func:`summarize_requests` exactly: only
terminal records count (``finish_reason="retried"`` rows are lineage,
not outcomes), and shed records are excluded from the latency
estimators (a shed does no work and records ``wall_ms=0`` — counting
it would make p50 *improve* exactly when overload is worst) while
still burning error budget.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Dict, Optional

from .percentiles import GOODPUT_REASONS, P2Quantile

__all__ = ["SLOTargets", "SLOMonitor"]

_METRICS = ("ttft_ms", "tpot_ms", "wall_ms")
_PERCENTILES = (50, 95, 99)


@dataclasses.dataclass
class SLOTargets:
    """The objective a request stream is judged against. ``ttft_ms`` /
    ``tpot_ms`` are optional absolute latency targets a finished
    request must also meet to count as good (None = finishing in
    budget is enough)."""
    goodput_pct: float = 99.0
    ttft_ms: Optional[float] = None
    tpot_ms: Optional[float] = None

    @property
    def error_budget(self) -> float:
        """Allowed bad fraction, floored so a 100% objective cannot
        divide by zero (burn rate saturates instead)."""
        return max(1e-9, 1.0 - self.goodput_pct / 100.0)


class SLOMonitor:
    """Windowed streaming SLO aggregate: feed every terminal
    ``kind="request"`` record through :meth:`observe`, read
    :meth:`report` any time.

    Args:
      targets: the :class:`SLOTargets` objective (default: 99% good,
        no absolute latency targets — deadline/finish semantics only).
      window: rolling window (requests) for the burn rate and the
        windowed goodput; the percentile estimators are whole-stream
        (P² is a running estimate, "rolling" in the sense of updated
        per record).
    """

    def __init__(self, targets: Optional[SLOTargets] = None,
                 window: int = 256, metrics=None):
        self.targets = targets or SLOTargets()
        self.window = int(window)
        # optional metrics registry (ISSUE 19, satellite 3): when set
        # (a MetricsHub or scoped view), every observe() publishes the
        # rolling percentiles and burn rate as gauges — report() stays
        # byte-identical either way
        self.metrics = metrics
        self._est = {m: {p: P2Quantile(p) for p in _PERCENTILES}
                     for m in _METRICS}
        self._recent: collections.deque = collections.deque(
            maxlen=self.window)          # good? per terminal request
        self.requests = 0
        self.retried_attempts = 0
        self.good = 0
        self.tokens = 0
        self.good_tokens = 0
        self.reasons: collections.Counter = collections.Counter()

    # -- ingestion ---------------------------------------------------------

    def _is_good(self, rec: Dict[str, Any]) -> bool:
        if rec.get("finish_reason") not in GOODPUT_REASONS:
            return False
        wall = rec.get("wall_ms")
        if (rec.get("deadline_s") is not None
                and (wall is None or wall > rec["deadline_s"] * 1e3)):
            return False
        if (self.targets.ttft_ms is not None
                and (rec.get("ttft_ms") is None
                     or rec["ttft_ms"] > self.targets.ttft_ms)):
            return False
        if (self.targets.tpot_ms is not None
                and rec.get("tpot_ms") is not None
                and rec["tpot_ms"] > self.targets.tpot_ms):
            return False
        return True

    def observe(self, rec: Dict[str, Any]) -> None:
        """Feed one telemetry record; non-request and retried-lineage
        records are ignored, so the whole stream can be piped
        through."""
        if rec.get("kind") != "request":
            return
        if rec.get("finish_reason") == "retried":
            self.retried_attempts += 1
            return
        self.requests += 1
        self.reasons[rec.get("finish_reason") or "?"] += 1
        self.tokens += int(rec.get("new_tokens") or 0)
        if rec.get("finish_reason") != "shed":
            for m in _METRICS:
                v = rec.get(m)
                if v is not None:
                    for est in self._est[m].values():
                        est.observe(float(v))
        good = self._is_good(rec)
        if good:
            self.good += 1
            self.good_tokens += int(rec.get("new_tokens") or 0)
        self._recent.append(good)
        if self.metrics is not None:
            for m in _METRICS:
                for p in _PERCENTILES:
                    v = self._est[m][p].value()
                    if v is not None:
                        self.metrics.gauge(
                            f"slo_{m}_p{p}",
                            f"rolling p{p} {m} (P2 estimate)").set(v)
            self.metrics.gauge(
                "slo_burn_rate",
                "windowed error-budget burn rate").set(self.burn_rate())

    # -- readout -----------------------------------------------------------

    def burn_rate(self) -> float:
        """Windowed error-budget burn rate (0.0 before any terminal
        record — no evidence is not an alert)."""
        if not self._recent:
            return 0.0
        bad = 1.0 - sum(self._recent) / len(self._recent)
        return bad / self.targets.error_budget

    def report(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "requests": self.requests,
            "retried_attempts": self.retried_attempts,
            "finish_reasons": dict(self.reasons),
            "new_tokens_total": self.tokens,
            "goodput_tokens": self.good_tokens,
            "goodput_pct": (round(100.0 * self.good / self.requests, 2)
                            if self.requests else None),
            "window": len(self._recent),
            "window_goodput_pct": (
                round(100.0 * sum(self._recent) / len(self._recent), 2)
                if self._recent else None),
            "error_budget_pct": round(
                100.0 * self.targets.error_budget, 4),
            "burn_rate": round(self.burn_rate(), 4),
            "targets": dataclasses.asdict(self.targets),
        }
        for m in _METRICS:
            for p in _PERCENTILES:
                v = self._est[m][p].value()
                out[f"{m}_p{p}"] = (round(v, 4)
                                    if v is not None else None)
        return out
