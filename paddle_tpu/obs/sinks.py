"""Telemetry sinks — where :class:`~paddle_tpu.obs.Telemetry` records go.

A sink consumes finished record dicts (JSON-serializable by construction:
the Telemetry layer converts device scalars to Python floats before
emitting). Three built-ins cover the reference's output surfaces: in-memory
(tests/notebooks), JSONL file (the machine-readable ``printAllStatus``
successor — one record per line, append-only, crash-tolerant), and the
logging module (human eyes).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Any, Dict, List, Optional

__all__ = ["Sink", "InMemorySink", "JsonlSink", "LoggingSink"]


class Sink:
    """Base sink: ``emit(record)`` consumes one record; ``close()`` releases
    resources. Sinks must tolerate being closed twice.

    THREAD-SAFETY CONTRACT (ISSUE 4): ``emit`` may be called from more
    than one thread. The obs layer is no longer single-threaded by
    construction — the hot loop runs main + stager + fill threads, and
    consumers (event handlers, anomaly tooling, user code holding the
    Telemetry) may emit from any of them — so every Sink implementation
    must make ``emit`` safe under concurrent callers (the built-ins
    lock; ``LoggingSink`` rides the stdlib logging module's own handler
    lock)."""

    def emit(self, record: Dict[str, Any]) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemorySink(Sink):
    """Keeps every record in ``self.records`` — the test/notebook sink.
    Emits are locked: two threads appending concurrently must both land
    (and ``by_kind`` must never iterate a list mid-resize)."""

    def __init__(self):
        self.records: List[Dict[str, Any]] = []
        self._lock = threading.Lock()

    def emit(self, record: Dict[str, Any]) -> None:
        with self._lock:
            self.records.append(record)

    def by_kind(self, kind: str) -> List[Dict[str, Any]]:
        with self._lock:
            return [r for r in self.records if r.get("kind") == kind]


class JsonlSink(Sink):
    """One JSON object per line, flushed per emit (a record is never half
    on disk after a crash — the CRC'd-checkpoint philosophy applied to
    telemetry). The file opens lazily on first emit so constructing a
    Telemetry never touches the filesystem.

    ``max_bytes`` (ISSUE 6 satellite) bounds the file: when the next
    record would push past it, the current file rotates to ``<path>.1``
    (replacing any previous ``.1``) and writing continues fresh — a
    week-long run keeps at most ~2x ``max_bytes`` of telemetry on disk
    instead of growing unboundedly, and the most recent window (the one
    a postmortem reads) is always intact. Rotation happens BEFORE the
    write, so no emitted record is ever split across files; a single
    record larger than ``max_bytes`` still lands whole. Default None:
    unbounded, the pre-rotation behavior."""

    def __init__(self, path: str, max_bytes: Optional[int] = None):
        self.path = path
        self.max_bytes = max_bytes
        self.rotations = 0
        self._f = None
        self._written = 0
        self._lock = threading.Lock()

    def emit(self, record: Dict[str, Any]) -> None:
        with self._lock:
            if self._f is None:
                self._f = open(self.path, "a")
                try:                               # append-mode resume
                    self._written = os.path.getsize(self.path)
                except OSError:
                    self._written = 0
            line = json.dumps(record) + "\n"
            if (self.max_bytes is not None and self._written > 0
                    and self._written + len(line) > self.max_bytes):
                self._f.close()
                os.replace(self.path, self.path + ".1")
                self.rotations += 1
                self._f = open(self.path, "a")
                self._written = 0
            self._f.write(line)
            self._f.flush()
            self._written += len(line)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    @staticmethod
    def read(path: str) -> List[Dict[str, Any]]:
        """Parse a JSONL telemetry file back into record dicts."""
        out = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
        return out


class LoggingSink(Sink):
    """Compact per-record line through the stdlib logging module."""

    _STEP_KEYS = ("step", "loss", "host_stack_ms", "shard_ms", "dispatch_ms",
                  "device_ms", "replay_ms", "retrace_count", "grad_norm",
                  "tokens_per_sec", "est_mfu_pct", "peak_bytes")

    def __init__(self, logger: Optional[logging.Logger] = None,
                 level: int = logging.INFO):
        self.logger = logger or logging.getLogger("paddle_tpu.telemetry")
        self.level = level

    def emit(self, record: Dict[str, Any]) -> None:
        kind = record.get("kind", "step")
        if kind == "compile":
            self.logger.log(self.level,
                            "telemetry compile #%s wall=%.3fs flops=%s %s",
                            record.get("compile_count"),
                            record.get("wall_s", float("nan")),
                            record.get("hlo_flops"),
                            record.get("fingerprint", ""))
            return
        parts = []
        for k in self._STEP_KEYS:
            v = record.get(k)
            if v is None:
                continue
            parts.append(f"{k}={v:.4g}" if isinstance(v, float) else
                         f"{k}={v}")
        self.logger.log(self.level, "telemetry %s %s", kind, " ".join(parts))
