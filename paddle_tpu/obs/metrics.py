"""Fleet-wide typed time-series metrics registry (ISSUE 19 tentpole).

PRs 2/4/6/17 gave the stack *records* (JSONL telemetry), *traces*
(Chrome/Perfetto flows), and *streaming SLO quantiles* — but every
counter still lives in an ad-hoc dict (``fleet.stats()``, transport
attribute counters, ``router_ms``, autoscaler EMAs, allocator gauges)
with no history, no types, no common query path, and no exposition
format. This module is the missing metrics plane:

- :class:`Counter` — monotone (``inc(n)`` with ``n < 0`` is a
  ``ValueError``; monotonicity is what makes cross-host delta merge and
  rate computation sound).
- :class:`Gauge` — last-write-wins scalar (``set``/``inc``/``dec``).
- :class:`Histogram` — fixed log-spaced buckets (:func:`log_buckets`)
  plus streaming sum/count. Fixed bounds, not adaptive: two replicas'
  histograms merge by adding per-bucket counts only when the bounds are
  byte-identical on both sides, which adaptive bucketing cannot
  guarantee.

Every metric is backed by a bounded ring-buffer time series — samples
``(ts, value)`` stamped by the hub's injected clock (the fleet's
SimClock in drills, so history is deterministic), with configurable
retention and oldest-first eviction. Labels
(``metric.labels(replica=..., role=..., link=...)``) key independent
children of one logical metric; the hub interns on the sorted label
set, so ``counter("x", a="1")`` from two call sites is the same object.

Cross-host protocol (the PR-17 span-batch move, verbatim): a process
replica's hub accumulates locally; :meth:`MetricsHub.drain_delta` pops
the since-last-drain increments (counter deltas, gauge last-values,
histogram bucket/sum/count deltas) and the replica piggybacks them on
its tick reply — no side-channel, so deltas undelivered at SIGKILL
honestly die with the process. The parent merges with
:meth:`MetricsHub.absorb_delta`, namespacing per replica by merging a
``replica=<id>`` label. Because counters are monotone and histograms
share fixed bounds, the merge is plain addition and at-least-once
delivery stays exactly-once (the transport's seq/reply-cache dedup
means each drained batch reaches ``absorb_delta`` once).

Reads: :meth:`MetricsHub.snapshot` (current values, one dict per
labeled child), :meth:`MetricsHub.query` (ring-buffer history,
``since=`` filtered), and :meth:`MetricsHub.render` — Prometheus text
exposition (``# HELP``/``# TYPE``, escaped labels, cumulative
``_bucket{le=...}`` + ``_sum``/``_count`` for histograms) with
:func:`parse_exposition` as the inverse for round-trip tests and remote
scrapes. Prometheus text because it is the lingua franca every scrape
pipeline already parses, is human-readable in a terminal, and costs one
string format per sample — no new dependency, no binary schema.

Default-off doctrine (the observability contract since ISSUE 2): no
component constructs a hub on its own; every instrumentation site
guards on ``is not None`` and the dark path is byte-identical —
pinned by the instrumented-vs-dark twin drill in the bench fleet gate.
"""

from __future__ import annotations

import bisect
import collections
import math
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsHub",
           "log_buckets", "parse_exposition"]


def log_buckets(lo: float = 1e-4, hi: float = 1e4,
                per_decade: int = 2) -> List[float]:
    """Fixed log-spaced histogram bucket upper bounds, ``lo`` to ``hi``
    inclusive at ``per_decade`` bounds per decade. The default spans
    1e-4..1e4 — wide enough that one policy covers microsecond RTTs and
    multi-second step times, because FIXED bounds (never resized from
    data) are what make cross-host histogram merge plain addition."""
    if lo <= 0 or hi <= lo:
        raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
    if per_decade < 1:
        raise ValueError(f"per_decade must be >= 1, got {per_decade}")
    n = int(round(math.log10(hi / lo) * per_decade))
    # 6 significant digits: stable float reprs so two hosts computing
    # the same policy render/compare byte-identical bounds
    return [float(f"{lo * 10 ** (i / per_decade):.6g}")
            for i in range(n + 1)]


_DEFAULT_BUCKETS = log_buckets()


class _Metric:
    """Common base: identity (name + labels), help text, and the
    bounded ``(ts, value)`` ring the hub's clock stamps."""

    kind = "untyped"

    def __init__(self, hub: "MetricsHub", name: str, help: str,
                 labels: Dict[str, str], retention: int):
        self.hub = hub
        self.name = name
        self.help = help
        self.label_values = dict(labels)
        self.series: collections.deque = collections.deque(
            maxlen=retention)

    def labels(self, **labels) -> "_Metric":
        """The sibling child of this metric with ``labels`` merged over
        this child's labels (get-or-create through the hub)."""
        merged = dict(self.label_values)
        merged.update({k: str(v) for k, v in labels.items()})
        return self.hub._get(self.kind, self.name, self.help, merged,
                             getattr(self, "buckets", None))

    def _stamp(self, value: float) -> None:
        self.series.append((self.hub.clock(), value))

    def samples(self, since: Optional[float] = None
                ) -> List[Tuple[float, float]]:
        """Ring-buffer history, optionally only samples with
        ``ts >= since``."""
        if since is None:
            return list(self.series)
        return [(t, v) for t, v in self.series if t >= since]


class Counter(_Metric):
    """Monotone counter. The series stamps the CUMULATIVE value at each
    increment, so rates are successive differences."""

    kind = "counter"

    def __init__(self, hub, name, help, labels, retention):
        super().__init__(hub, name, help, labels, retention)
        self.value = 0.0
        self._shipped = 0.0        # drained-up-to watermark

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(
                f"counter {self.name!r} is monotone; inc({n}) rejected")
        if n == 0:
            return
        self.value += n
        self._stamp(self.value)


class Gauge(_Metric):
    """Last-write-wins scalar (``value`` is None before the first
    set)."""

    kind = "gauge"

    def __init__(self, hub, name, help, labels, retention):
        super().__init__(hub, name, help, labels, retention)
        self.value: Optional[float] = None
        self._dirty = False        # set since last drain?

    def set(self, v: float) -> None:
        self.value = float(v)
        self._dirty = True
        self._stamp(self.value)

    def inc(self, n: float = 1.0) -> None:
        self.set((self.value or 0.0) + n)

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)


class Histogram(_Metric):
    """Fixed-bucket histogram with streaming sum/count. ``counts`` is
    per-bucket NON-cumulative (last slot is the +Inf overflow); the
    exposition renderer emits the Prometheus cumulative form. The ring
    series holds raw observations (the distribution-sparkline and
    ``query()`` source)."""

    kind = "histogram"

    def __init__(self, hub, name, help, labels, retention,
                 buckets: Optional[List[float]] = None):
        super().__init__(hub, name, help, labels, retention)
        bs = list(_DEFAULT_BUCKETS if buckets is None else buckets)
        if bs != sorted(bs) or len(set(bs)) != len(bs):
            raise ValueError(f"buckets must be strictly increasing, "
                             f"got {bs}")
        self.buckets = bs
        self.counts = [0] * (len(bs) + 1)
        self.sum = 0.0
        self.count = 0
        self._shipped_counts = [0] * (len(bs) + 1)
        self._shipped_sum = 0.0
        self._shipped_count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        # le semantics: the first bound >= v owns the observation
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.sum += v
        self.count += 1
        self._stamp(v)

    def merge(self, counts: List[int], sum_: float, count: int) -> None:
        """Add another histogram's (delta) counts into this one — the
        cross-host absorb path. Bounds must match exactly (fixed-bucket
        policy); raw observations do not travel, only the counts."""
        if len(counts) != len(self.counts):
            raise ValueError(
                f"histogram {self.name!r}: cannot merge {len(counts)} "
                f"bucket counts into {len(self.counts)}")
        for i, c in enumerate(counts):
            self.counts[i] += c
        self.sum += sum_
        self.count += count


class _Scoped:
    """Label-scoped facade over a hub: same ``counter``/``gauge``/
    ``histogram`` get-or-create surface with this scope's labels merged
    in automatically — how a fleet hands its engine/scheduler a
    ``replica=<i>``-namespaced view without those components knowing
    about fleet topology."""

    def __init__(self, hub: "MetricsHub", labels: Dict[str, str]):
        self.hub = hub
        self._labels = {k: str(v) for k, v in labels.items()}

    def _merge(self, labels):
        merged = dict(self._labels)
        merged.update(labels)
        return merged

    def counter(self, name, help="", **labels) -> Counter:
        return self.hub.counter(name, help, **self._merge(labels))

    def gauge(self, name, help="", **labels) -> Gauge:
        return self.hub.gauge(name, help, **self._merge(labels))

    def histogram(self, name, help="", buckets=None,
                  **labels) -> Histogram:
        return self.hub.histogram(name, help, buckets=buckets,
                                  **self._merge(labels))

    def scoped(self, **labels) -> "_Scoped":
        return _Scoped(self.hub, self._merge(labels))

    @property
    def clock(self) -> Callable[[], float]:
        return self.hub.clock


class MetricsHub:
    """The registry: get-or-create typed metrics keyed on
    ``(name, sorted labels)``, one clock, one retention policy.

    Args:
      clock: timestamp source for ring-buffer samples (callable → s).
        The fleet passes its own clock, so a SimClock drill's metric
        history is deterministic. Default: ``time.time``.
      retention: ring-buffer length per labeled child (oldest-first
        eviction beyond it). History is bounded BY CONSTRUCTION —
        a weeks-long serving process cannot leak memory through its
        own observability.
    """

    def __init__(self, clock: Optional[Callable[[], float]] = None,
                 retention: int = 512):
        if retention < 1:
            raise ValueError(f"retention must be >= 1, got {retention}")
        self.clock = clock if clock is not None else time.time
        self.retention = int(retention)
        self._lock = threading.RLock()
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                            _Metric] = {}
        self._kinds: Dict[str, str] = {}      # name -> kind (one type)
        self._help: Dict[str, str] = {}
        self._bounds: Dict[str, List[float]] = {}

    # -- registration ------------------------------------------------------

    @staticmethod
    def _key(name: str, labels: Dict[str, str]
             ) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
        return (name, tuple(sorted((str(k), str(v))
                                   for k, v in labels.items())))

    def _get(self, kind: str, name: str, help: str,
             labels: Dict[str, str],
             buckets: Optional[List[float]] = None) -> _Metric:
        with self._lock:
            key = self._key(name, labels)
            m = self._metrics.get(key)
            if m is not None:
                if m.kind != kind:
                    raise ValueError(
                        f"metric {name!r} is a {m.kind}, not a {kind}")
                return m
            if self._kinds.setdefault(name, kind) != kind:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{self._kinds[name]}, cannot re-register as {kind}")
            if help and not self._help.get(name):
                self._help[name] = help
            h = self._help.get(name, "")
            lbl = dict(key[1])
            if kind == "counter":
                m = Counter(self, name, h, lbl, self.retention)
            elif kind == "gauge":
                m = Gauge(self, name, h, lbl, self.retention)
            else:
                bs = self._bounds.setdefault(
                    name, list(_DEFAULT_BUCKETS if buckets is None
                               else buckets))
                m = Histogram(self, name, h, lbl, self.retention,
                              buckets=bs)
            self._metrics[key] = m
            return m

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get("counter", name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[List[float]] = None,
                  **labels) -> Histogram:
        return self._get("histogram", name, help, labels, buckets)

    def scoped(self, **labels) -> _Scoped:
        """A label-scoped facade (see :class:`_Scoped`)."""
        return _Scoped(self, labels)

    # -- reads -------------------------------------------------------------

    def snapshot(self) -> List[Dict[str, Any]]:
        """Current value of every labeled child, one dict each, sorted
        by (name, labels) for deterministic output."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            for (name, lbl), m in sorted(self._metrics.items()):
                d: Dict[str, Any] = {"name": name, "type": m.kind,
                                     "labels": dict(lbl)}
                if isinstance(m, Histogram):
                    d.update(count=m.count, sum=m.sum,
                             buckets=list(m.buckets),
                             counts=list(m.counts))
                else:
                    d["value"] = m.value
                out.append(d)
        return out

    def query(self, name: str, since: Optional[float] = None,
              **labels) -> List[Dict[str, Any]]:
        """Ring-buffer history for every child of ``name`` whose labels
        are a superset of ``labels``: ``[{"labels": ..., "samples":
        [(ts, value), ...]}, ...]``."""
        want = {str(k): str(v) for k, v in labels.items()}
        out = []
        with self._lock:
            for (n, lbl), m in sorted(self._metrics.items()):
                if n != name:
                    continue
                have = dict(lbl)
                if any(have.get(k) != v for k, v in want.items()):
                    continue
                out.append({"labels": have,
                            "samples": m.samples(since)})
        return out

    # -- Prometheus text exposition ----------------------------------------

    @staticmethod
    def _esc(v: str) -> str:
        return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    @classmethod
    def _fmt_labels(cls, labels: Dict[str, str],
                    extra: Optional[Tuple[str, str]] = None) -> str:
        items = sorted(labels.items())
        if extra is not None:
            items = items + [extra]
        if not items:
            return ""
        return ("{" + ",".join(f'{k}="{cls._esc(v)}"'
                               for k, v in items) + "}")

    @staticmethod
    def _fmt_val(v: Optional[float]) -> str:
        if v is None:
            return "NaN"
        f = float(v)
        if f == int(f) and abs(f) < 1e15:
            return str(int(f))
        return repr(f)

    def render(self) -> str:
        """Prometheus-compatible text exposition of the whole registry
        (the remote-scrape payload; :func:`parse_exposition` is the
        inverse)."""
        by_name: Dict[str, List[_Metric]] = collections.OrderedDict()
        with self._lock:
            for (name, _), m in sorted(self._metrics.items()):
                by_name.setdefault(name, []).append(m)
            lines: List[str] = []
            for name, children in by_name.items():
                if self._help.get(name):
                    lines.append(f"# HELP {name} "
                                 f"{self._esc(self._help[name])}")
                lines.append(f"# TYPE {name} {children[0].kind}")
                for m in children:
                    if isinstance(m, Histogram):
                        cum = 0
                        for bound, c in zip(m.buckets, m.counts):
                            cum += c
                            lines.append(
                                f"{name}_bucket"
                                + self._fmt_labels(
                                    m.label_values,
                                    ("le", self._fmt_val(bound)))
                                + f" {cum}")
                        lines.append(
                            f"{name}_bucket"
                            + self._fmt_labels(m.label_values,
                                               ("le", "+Inf"))
                            + f" {m.count}")
                        lines.append(f"{name}_sum"
                                     + self._fmt_labels(m.label_values)
                                     + f" {self._fmt_val(m.sum)}")
                        lines.append(f"{name}_count"
                                     + self._fmt_labels(m.label_values)
                                     + f" {m.count}")
                    else:
                        lines.append(name
                                     + self._fmt_labels(m.label_values)
                                     + f" {self._fmt_val(m.value)}")
        return "\n".join(lines) + "\n"

    # -- cross-host delta protocol -----------------------------------------

    def drain_delta(self) -> List[Dict[str, Any]]:
        """Pop everything that changed since the last drain, as a
        JSON-able batch the tick reply carries (the PR-17 span-batch
        move): counter increments, gauge last-values, histogram
        bucket/sum/count deltas. Draining advances the shipped
        watermark, so a batch lost with a SIGKILLed process honestly
        dies — the parent never sees it and never will."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            for (name, lbl), m in sorted(self._metrics.items()):
                if isinstance(m, Counter):
                    d = m.value - m._shipped
                    if d:
                        out.append({"kind": "counter", "name": name,
                                    "help": m.help, "labels": dict(lbl),
                                    "inc": d})
                        m._shipped = m.value
                elif isinstance(m, Gauge):
                    if m._dirty and m.value is not None:
                        out.append({"kind": "gauge", "name": name,
                                    "help": m.help, "labels": dict(lbl),
                                    "value": m.value})
                        m._dirty = False
                else:
                    dc = [a - b for a, b in zip(m.counts,
                                                m._shipped_counts)]
                    if any(dc):
                        out.append({
                            "kind": "histogram", "name": name,
                            "help": m.help, "labels": dict(lbl),
                            "buckets": list(m.buckets), "counts": dc,
                            "sum": m.sum - m._shipped_sum,
                            "count": m.count - m._shipped_count})
                        m._shipped_counts = list(m.counts)
                        m._shipped_sum = m.sum
                        m._shipped_count = m.count
        return out

    def absorb_delta(self, deltas: List[Dict[str, Any]],
                     **extra_labels) -> None:
        """Merge a drained batch into this hub, with ``extra_labels``
        merged over each entry's own labels — the parent namespaces a
        replica's whole registry with ``replica=<id>`` in one call.
        Monotone counters and fixed-bound histograms make this plain
        addition; gauges are last-write-wins."""
        for d in deltas:
            labels = dict(d.get("labels") or {})
            labels.update({k: str(v) for k, v in extra_labels.items()})
            kind = d.get("kind")
            name = d["name"]
            help_ = d.get("help", "")
            if kind == "counter":
                self.counter(name, help_, **labels).inc(d["inc"])
            elif kind == "gauge":
                self.gauge(name, help_, **labels).set(d["value"])
            elif kind == "histogram":
                h = self.histogram(name, help_,
                                   buckets=d.get("buckets"), **labels)
                h.merge(d["counts"], d["sum"], d["count"])
            else:
                raise ValueError(f"unknown delta kind {kind!r}")


# one sample line: name, optional {labels}, value
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(s: str) -> str:
    return (s.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def parse_exposition(text: str) -> Dict[str, Any]:
    """Parse Prometheus text exposition back into
    ``{"types": {name: kind}, "samples": [(name, labels, value)]}`` —
    the round-trip check for :meth:`MetricsHub.render` and the reader
    side of the ``metrics`` transport scrape op."""
    types: Dict[str, str] = {}
    samples: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 4:
                types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, body, raw = m.groups()
        labels = {k: _unescape(v)
                  for k, v in _LABEL_RE.findall(body or "")}
        try:
            val = float(raw)
        except ValueError:
            # histogram +Inf bucket bound parses; other junk is skipped
            if raw == "+Inf":
                val = math.inf
            else:
                continue
        samples.append((name, labels, val))
    return {"types": types, "samples": samples}
