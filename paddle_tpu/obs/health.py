"""Training-health monitors — device-side scalars extending the
overflow-trip machinery (``Trainer(nan_check=True)``; the reference's
feenableexcept trap, ``TrainerMain.cpp:36``) with the standard
loss-scale-era diagnostics: global gradient norm, global parameter norm,
update/param ratio, and a NaN/Inf sentinel.

Everything here is traced INTO the compiled train step (pure jnp on the
gradient/update/param pytrees — a handful of reduce ops XLA fuses into the
step program), so monitoring adds no extra device dispatch: the scalars
ride the step's existing outputs and the host fetches them with the same
per-call sync that already fetches the losses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["HEALTH_KEYS", "tree_l2_norm", "tree_nonfinite_count",
           "health_scalars"]

# The fixed key set every health pytree carries (schema contract for the
# JSONL sink and its tests).
HEALTH_KEYS = ("grad_norm", "param_norm", "update_norm", "update_ratio",
               "nonfinite_count")


def tree_l2_norm(tree) -> jax.Array:
    """Global L2 norm over every leaf of a pytree (f32 accumulation)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    sq = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    return jnp.sqrt(sq)


def tree_nonfinite_count(tree) -> jax.Array:
    """Total count of NaN/Inf elements across a pytree (int32)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.int32)
    return sum(jnp.sum(~jnp.isfinite(l.astype(jnp.float32))).astype(jnp.int32)
               for l in leaves)


def health_scalars(grads, updates, new_params, loss) -> dict:
    """The per-step health pytree (dict of scalars), traced inside the
    compiled step. ``update_ratio`` is ||update|| / ||param|| — the
    learning-dynamics sanity signal (healthy training sits around 1e-3;
    a collapse toward 0 or blow-up toward 1 flags a bad LR). The sentinel
    counts non-finite elements in the gradients plus the step loss."""
    gn = tree_l2_norm(grads)
    pn = tree_l2_norm(new_params)
    un = tree_l2_norm(updates)
    bad = tree_nonfinite_count(grads) + \
        (~jnp.isfinite(loss.astype(jnp.float32))).astype(jnp.int32)
    return {
        "grad_norm": gn,
        "param_norm": pn,
        "update_norm": un,
        "update_ratio": un / jnp.maximum(pn, 1e-12),
        "nonfinite_count": bad,
    }
