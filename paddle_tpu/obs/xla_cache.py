"""Persistent XLA compilation-cache setup (ISSUE 16).

One helper owns the ``jax.config`` knobs for the on-disk compile cache,
the way :mod:`~paddle_tpu.obs.xla_flags` owns the overlap flag set —
documented provenance, explicit opt-in, and a probe the warmup paths use
to report whether a compile was served from disk.

Why opt-in and never implicit: under the remote-TPU (axon) plugin,
executables deserialized from the persistent cache hang at execution
time (bench.py pins this caveat at its top) — so nothing in this repo
flips the cache on as a side effect of importing. The two sanctioned
switches are an explicit :func:`setup_compilation_cache` call (the
serving replica child does this when its spec carries
``compile_cache_dir``) and the ``PADDLE_TPU_COMPILE_CACHE`` environment
variable for local/CPU runs.

``min_compile_time_s`` defaults to 0 so even sub-second CPU test
programs persist — the cold-vs-warm spawn gate depends on tiny programs
hitting the cache. Production TPU configs can raise it to skip caching
trivial programs.
"""

from __future__ import annotations

import os
from typing import Mapping, Optional

ENV_VAR = "PADDLE_TPU_COMPILE_CACHE"

_active: Optional[str] = None


def setup_compilation_cache(cache_dir: Optional[str] = None, *,
                            min_compile_time_s: float = 0.0,
                            env: Optional[Mapping[str, str]] = None
                            ) -> Optional[str]:
    """Point jax's persistent compilation cache at ``cache_dir`` (or
    ``$PADDLE_TPU_COMPILE_CACHE``). Returns the activated directory, or
    None when neither source names one — the no-op path costs nothing,
    the same contract as ``apply_overlap_flags`` without hints."""
    d = cache_dir or (env or os.environ).get(ENV_VAR)
    if not d:
        return None
    import jax
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(d))
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_time_s))
    try:
        # cache regardless of serialized size (tiny CPU test programs)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass                        # knob absent on this jax version
    global _active
    _active = str(d)
    return _active


def active_dir() -> Optional[str]:
    """The directory a prior :func:`setup_compilation_cache` activated
    in this process (None = cache not configured here)."""
    return _active


def reset() -> None:
    """Forget the recorded activation (test hygiene; does not un-set
    the jax config — jax has no supported 'off' transition)."""
    global _active
    _active = None


def cache_entry_count(cache_dir: Optional[str] = None) -> int:
    """Number of serialized executables in ``cache_dir`` (default: the
    active dir). The warmup paths diff this across a compile to report
    ``cache_hit``: no new entries ⇒ the executable came from disk."""
    d = cache_dir or _active
    if not d or not os.path.isdir(d):
        return 0
    return sum(1 for n in os.listdir(d) if n.endswith("-cache"))
