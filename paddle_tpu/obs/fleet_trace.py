"""Fleet trace merge: one Chrome/Perfetto timeline from many tracers
(ISSUE 17, tentpole part 1).

A fleet drill produces span batches from several uncoordinated
:class:`~paddle_tpu.obs.trace.Tracer` instances — the router's own, one
per in-process replica, and batches shipped back from subprocess
replicas piggybacked on tick replies. Each batch is internally
consistent (one time base: the fleet clock every message already
carries) but the identifiers are not mergeable as-is: every child
stamps its OS pid and OS thread idents, which collide across forks and
are nondeterministic run-to-run.

:func:`merge_fleet_trace` canonicalizes into ONE trace:

- **Lanes**: pid 0 is the fleet router; replica *r* becomes pid
  ``r + 1`` (named ``replica r``) regardless of the OS pid its spans
  were stamped with. Shipped ``ph="M"`` metadata is dropped and fresh
  process/thread names are emitted, so the viewer shows stable lanes.
- **Tids** are canonicalized per lane in first-appearance order (OS
  idents are nondeterministic; first-appearance order over a
  SimClock-stamped stream is not).
- **Flows**: spans already carry ``s``/``t``/``f`` flow events keyed by
  rid (the globally unique request id IS the flow id), so a request
  that is submitted at the router, killed with replica 0, resubmitted,
  and finished on replica 1 renders as one connected arrow chain
  across three lanes. :func:`flow_connected` checks exactly that.
- Events are stable-sorted by timestamp — with a SimClock drill the
  merged ``traceEvents`` list is deterministic across runs
  (``tests/test_fleet_obs.py`` pins it).
"""

from __future__ import annotations

import collections
import json
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

__all__ = ["merge_fleet_trace", "save_fleet_trace", "flow_summary",
           "flow_connected", "lane_monotonic"]

ROUTER_PID = 0

# equal-timestamp tie-break: flow starts sort before everything, flow
# ends after everything (see merge_fleet_trace)
_FLOW_PHASE_ORDER = {"s": 0, "f": 2}


def _canon(events: Iterable[Dict[str, Any]], pid: int,
           tidmap: Dict[Tuple[int, int], int]) -> List[Dict[str, Any]]:
    out = []
    for ev in events:
        if ev.get("ph") == "M":
            continue                 # re-emitted fresh per lane below
        ev = dict(ev)
        key = (pid, ev.get("tid", 0))
        if key not in tidmap:
            tidmap[key] = len([k for k in tidmap if k[0] == pid]) + 1
        ev["pid"] = pid
        ev["tid"] = tidmap[key]
        out.append(ev)
    return out


def merge_fleet_trace(
        router_events: Iterable[Dict[str, Any]],
        replica_events: Mapping[int, Iterable[Dict[str, Any]]],
        tail: Optional[int] = None) -> Dict[str, Any]:
    """Merge the router's events and per-replica span batches into one
    Chrome Trace Event Format dict. ``replica_events`` maps replica id
    → its (possibly concatenated) event batches; ``tail`` keeps only
    the most recent N non-metadata events after sorting (the anomaly
    bundle's flight-recorder view)."""
    tidmap: Dict[Tuple[int, int], int] = {}
    meta: List[Dict[str, Any]] = [
        {"ph": "M", "name": "process_name", "pid": ROUTER_PID, "tid": 0,
         "args": {"name": "fleet-router"}}]
    merged = _canon(router_events, ROUTER_PID, tidmap)
    for rid in sorted(replica_events):
        pid = int(rid) + 1
        meta.append({"ph": "M", "name": "process_name", "pid": pid,
                     "tid": 0, "args": {"name": f"replica {rid}"}})
        merged.extend(_canon(replica_events[rid], pid, tidmap))
    # stable sort: equal-ts events keep lane/emit order, so SimClock
    # drills (many spans sharing one tick timestamp) stay deterministic.
    # Flow phases tie-break at equal ts ("s" first, "f" last): a
    # SimClock tick collapses a request's last decode, its finish and
    # its fleet-side terminal onto ONE timestamp, and journey order —
    # which flow_connected() audits — must still read s..t..f
    merged.sort(key=lambda e: (e.get("ts", -1.0),
                               _FLOW_PHASE_ORDER.get(e.get("ph"), 1)))
    if tail is not None and tail >= 0:
        merged = merged[-int(tail):] if tail else []
    return {"traceEvents": meta + merged, "displayTimeUnit": "ms",
            "otherData": {"producer": "paddle_tpu.obs.fleet_trace",
                          "clock": "fleet clock (absolute us)",
                          "replicas": sorted(int(r)
                                             for r in replica_events)}}


def save_fleet_trace(trace: Dict[str, Any], path: str) -> str:
    """Write a merged trace as JSON (open in ui.perfetto.dev)."""
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


def flow_summary(trace: Dict[str, Any]
                 ) -> Dict[int, List[Tuple[str, int]]]:
    """Flow id → the ordered ``(phase, pid)`` list of its flow events
    — the cross-process skeleton of each request's journey."""
    flows: Dict[int, List[Tuple[str, int]]] = collections.defaultdict(list)
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") in ("s", "t", "f") and ev.get("cat") == "flow":
            flows[int(ev["id"])].append((ev["ph"], ev.get("pid", -1)))
    return dict(flows)


def flow_connected(trace: Dict[str, Any], fid: int) -> bool:
    """True iff flow ``fid`` is one well-formed chain: starts with
    ``s``, ends with ``f``, every middle hop is ``t``. (Events are
    timestamp-ordered by the merge, so list order is journey order.)"""
    phases = [ph for ph, _ in flow_summary(trace).get(int(fid), [])]
    if len(phases) < 2 or phases[0] != "s" or phases[-1] != "f":
        return False
    return all(ph == "t" for ph in phases[1:-1])


def lane_monotonic(trace: Dict[str, Any]) -> bool:
    """True iff every lane's (pid's) non-metadata events are
    timestamp-monotonic — the merge-order sanity check the
    determinism test asserts."""
    last: Dict[int, float] = {}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") == "M":
            continue
        pid, ts = ev.get("pid", -1), ev.get("ts", 0.0)
        if ts < last.get(pid, float("-inf")):
            return False
        last[pid] = ts
    return True
