"""Per-scope roofline attribution + exposed-communication estimate over a
parsed HLO module (ISSUE 6 tentpole, part 2).

:mod:`.hloprof` turns the compiled step's HLO text into a structured op
inventory; this module turns the inventory into the two artifacts the
MFU gap needs:

- **The per-scope roofline table.** Every op's FLOPs and buffer bytes
  roll up onto its ``jax.named_scope`` path (the PR-2 scope tree:
  embed / block / attn / ffn / head, tp_attn / sp_allgather / ...), with
  loop-aware execution multipliers, forward/backward split, and a static
  roofline per region: ``est_compute_ms = flops / peak``,
  ``est_memory_ms = bytes / HBM bandwidth``, bound = whichever wins,
  ``idle_ms`` = the time the MXU sits idle while the region is
  memory-bound. The ``mfu_gap_rank`` orders regions by idle time — the
  direct answer to "which region leaves the most hardware idle".
- **The exposed-communication estimate.** Each collective is costed
  against the ICI/DCN bandwidth table and classified by whether it has
  independent compute to hide behind: a BACKWARD collective (the grad
  all-reduce — ``transpose(...)`` in its op metadata) can overlap the
  rest of the backward pass; forward/activation collectives sit on the
  critical path. ``exposed_ms`` charges the non-overlappable time plus
  any overlappable excess beyond the backward-compute budget — the
  measured-not-projected input the all-reduce-overlap ROADMAP item needs.

**Static vs measured.** Everything above is a *static* cost model — the
analytic what-if for the spec-sheet device (on CPU test meshes the
tables substitute ``DEFAULT_DEVICE`` and the report says so via
``bandwidth_assumed``). :func:`parse_profile_trace` is the measured
path: it parses the Chrome-trace JSON a ``Tracer.profile_window()`` /
``jax.profiler`` capture leaves on disk, splits device-lane wall time
into compute vs communication, and interval-subtracts their overlap to
get *measured* exposed-communication time. On real TPU the two sides
join in one report; with no device lanes in the capture (CPU) the
measured block is simply absent — static-only, degrading gracefully.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from . import hloprof
from .hloprof import (DCN_BYTES_PER_S, DEFAULT_DEVICE, HBM_BANDWIDTH,
                      ICI_BANDWIDTH, ModuleAnalysis)

__all__ = ["build_report", "parse_profile_trace", "format_report"]


def hloprof_grad_sync_scope() -> str:
    """The named-scope root ``parallel.overlap`` traces its explicit
    gradient-sync psums under (imported lazily: obs must not depend on
    the parallel package at import time)."""
    try:
        from ..parallel.overlap import GRAD_SYNC_SCOPE
        return GRAD_SYNC_SCOPE
    except Exception:
        return "grad_sync"

_UNSCOPED = "(unscoped)"

# the serving runtime's named-scope root (TransformerLM.prefill /
# decode_step trace under it): scopes below it are classified as decode
# work, and the report carries a `decode` roofline aggregate — decode is
# memory-bound by construction (one token of compute streams the whole
# parameter set + active KV), and the aggregate's bound says so on the
# same spec-sheet HBM tables as every other row.
DECODE_SCOPE = "decode"


def _scope_key(scope: Tuple[str, ...]) -> str:
    return "/".join(scope) or _UNSCOPED


def build_report(analysis: ModuleAnalysis, *,
                 device_kind: str = "",
                 n_devices: int = 1,
                 cost_analysis_flops: Optional[float] = None,
                 peak_flops: Optional[float] = None,
                 hbm_bytes_per_s: Optional[float] = None,
                 ici_bytes_per_s: Optional[float] = None,
                 inter_slice: bool = False,
                 meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Build the JSON-safe attribution report from a parsed module.

    Args:
      analysis: :func:`hloprof.parse_module` output for the compiled
        (post-SPMD, per-device) HLO.
      device_kind: ``jax.devices()[0].device_kind`` — keys the bandwidth
        and peak-FLOPs tables; unknown kinds substitute
        ``DEFAULT_DEVICE`` and set ``bandwidth_assumed``.
      n_devices: mesh size (the default replica-group size for
        collectives whose groups aren't printed explicitly).
      cost_analysis_flops: ``compiled.cost_analysis()['flops']`` when the
        backend provides it — recorded alongside the parsed static total
        with the agreement percentage (the bench smoke gate pins <= 5%).
      inter_slice: cost collectives at DCN instead of ICI bandwidth.
      meta: extra fields merged into the report (mesh axes, K/M, ...).
    """
    from .telemetry import PEAK_FLOPS      # lazy: telemetry imports jax

    assumed = False
    peak = peak_flops if peak_flops is not None \
        else PEAK_FLOPS.get(device_kind)
    hbm = hbm_bytes_per_s if hbm_bytes_per_s is not None \
        else HBM_BANDWIDTH.get(device_kind)
    ici = ici_bytes_per_s if ici_bytes_per_s is not None \
        else ICI_BANDWIDTH.get(device_kind)
    if peak is None or hbm is None or ici is None:
        assumed = True
        peak = peak if peak is not None else PEAK_FLOPS[DEFAULT_DEVICE]
        hbm = hbm if hbm is not None else HBM_BANDWIDTH[DEFAULT_DEVICE]
        ici = ici if ici is not None else ICI_BANDWIDTH[DEFAULT_DEVICE]
    comm_bw = DCN_BYTES_PER_S if inter_slice else ici

    # -- per-scope rollup ---------------------------------------------------
    by_scope: Dict[str, Dict[str, float]] = {}
    rollup: Dict[str, float] = {}        # every scope-path prefix -> flops
    for op in analysis.ops:
        key = _scope_key(op.scope)
        e = by_scope.setdefault(key, {
            "flops": 0.0, "flops_static": 0.0, "bytes": 0.0,
            "fwd_flops": 0.0, "bwd_flops": 0.0, "ops": 0})
        e["ops"] += 1
        if op.flops:
            e["flops"] += op.flops * op.multiplier
            e["flops_static"] += op.flops
            side = "bwd_flops" if op.backward else "fwd_flops"
            e[side] += op.flops * op.multiplier
            for i in range(1, len(op.scope) + 1):
                pref = "/".join(op.scope[:i])
                rollup[pref] = rollup.get(pref, 0.0) \
                    + op.flops * op.multiplier
            if not op.scope:
                rollup[_UNSCOPED] = rollup.get(_UNSCOPED, 0.0) \
                    + op.flops * op.multiplier
        if not op.fusion_internal:
            # fusion-boundary bytes only: fusion internals live in
            # registers/VMEM, so counting them would inflate the
            # memory-traffic proxy the roofline divides by
            e["bytes"] += op.bytes * op.multiplier

    flops_total = analysis.flops_loop_aware()
    flops_static = analysis.flops_static()

    scopes = []
    for key, e in by_scope.items():
        compute_ms = e["flops"] / peak * 1e3
        memory_ms = e["bytes"] / hbm * 1e3
        est_ms = max(compute_ms, memory_ms)
        scopes.append({
            "scope": key,
            "region": DECODE_SCOPE if (key == DECODE_SCOPE or
                                       key.startswith(DECODE_SCOPE + "/"))
            else None,
            "flops": round(e["flops"]),
            "flops_static": round(e["flops_static"]),
            "flops_frac": round(e["flops"] / flops_total, 4)
            if flops_total else 0.0,
            "bytes": round(e["bytes"]),
            "intensity_flops_per_byte": round(e["flops"] / e["bytes"], 3)
            if e["bytes"] else None,
            "est_compute_ms": round(compute_ms, 6),
            "est_memory_ms": round(memory_ms, 6),
            "est_ms": round(est_ms, 6),
            "bound": ("compute" if compute_ms >= memory_ms else "memory")
            if est_ms else "none",
            "idle_ms": round(max(est_ms - compute_ms, 0.0), 6),
            "idle_frac": round(1.0 - compute_ms / est_ms, 4)
            if est_ms else 0.0,
            "bwd_frac": round(e["bwd_flops"] / e["flops"], 4)
            if e["flops"] else 0.0,
            "ops": e["ops"],
        })
    scopes.sort(key=lambda s: -s["flops"])
    mfu_gap_rank = sorted(
        (s for s in scopes if s["est_ms"] > 0),
        key=lambda s: -s["idle_ms"])
    mfu_gap_rank = [{"scope": s["scope"], "idle_ms": s["idle_ms"],
                     "idle_frac": s["idle_frac"], "bound": s["bound"],
                     "est_ms": s["est_ms"]} for s in mfu_gap_rank]

    # -- collectives: exposed vs overlappable -------------------------------
    inventory = hloprof.collective_inventory(analysis,
                                             default_group=n_devices)
    bwd_compute_ms = sum(op.flops * op.multiplier
                         for op in analysis.ops if op.backward) / peak * 1e3
    collectives = []
    total_wire = exposed_base_ms = overlappable_ms = 0.0
    grad_ar_wire = grad_ar_count = 0
    grad_rows = []
    gs_scope = hloprof_grad_sync_scope()
    decode_rows_comm = []
    for c in inventory:
        wire_total = c.wire_bytes * c.multiplier
        t_comm_ms = wire_total / comm_bw * 1e3
        # a backward collective (the grad sync autodiff's transpose
        # emits) has the REST of the backward pass as independent
        # compute to hide behind; forward/activation collectives feed
        # the very next op — critical path. Explicit grad-sync psums
        # (parallel.overlap) are recognized by their named scope too:
        # the accumulated-gradient sync is traced OUTSIDE the transpose
        # (no backward metadata) but is still the gradient collective.
        is_grad_sync = bool(c.scope) and c.scope[0] == gs_scope
        # a collective under a decode/* scope is SERVING communication
        # (ISSUE 15: the tp-sharded tick's out-proj/ffn all-reduces and
        # any AG/RS the partitioner derives) — classified into the
        # serving comm table below instead of falling through unlabeled
        is_decode = bool(c.scope) and c.scope[0] == DECODE_SCOPE
        overlappable = c.backward or is_grad_sync
        d = c.to_dict()
        d.update({
            "wire_bytes_total": round(wire_total),
            "t_comm_ms": round(t_comm_ms, 6),
            "overlappable": overlappable,
            "region": DECODE_SCOPE if is_decode else None,
        })
        collectives.append(d)
        if is_decode:
            decode_rows_comm.append(d)
        total_wire += wire_total
        if overlappable:
            overlappable_ms += t_comm_ms
        else:
            exposed_base_ms += t_comm_ms
        if c.kind == "all-reduce" and (c.backward or is_grad_sync):
            grad_ar_wire += wire_total
            grad_ar_count += 1
            if is_grad_sync:
                # one row per explicit sync bucket (ISSUE 8: the
                # per-bucket comm table the smoke gate asserts)
                grad_rows.append({
                    "scope": d["scope"],
                    "payload_bytes": c.payload_bytes,
                    "wire_bytes_total": round(wire_total),
                    "t_comm_ms": round(t_comm_ms, 6),
                    "multiplier": c.multiplier,
                    "is_async": c.is_async,
                    "sched_distance": c.sched_distance,
                })
    hidden_ms = min(overlappable_ms, bwd_compute_ms)
    exposed_ms = exposed_base_ms + (overlappable_ms - hidden_ms)
    grad_ar_ms = grad_ar_wire / comm_bw * 1e3
    comm = {
        "total_wire_bytes_per_device": round(total_wire),
        "t_comm_ms": round(exposed_base_ms + overlappable_ms, 6),
        "overlappable_ms": round(overlappable_ms, 6),
        "exposed_ms": round(exposed_ms, 6),
        "backward_compute_budget_ms": round(bwd_compute_ms, 6),
        "link": "DCN" if inter_slice else "ICI",
        "bytes_per_s": comm_bw,
        "grad_allreduce": {
            "ops": grad_ar_count,
            "wire_bytes_per_device": round(grad_ar_wire),
            "t_comm_ms": round(grad_ar_ms, 6),
            # what stays exposed if the grad sync overlaps the backward
            # pass (the ROADMAP all-reduce-overlap item's target number)
            "exposed_ms_if_overlapped": round(
                max(0.0, grad_ar_ms - bwd_compute_ms), 6),
            "exposed_ms_today": round(grad_ar_ms, 6),
            "hides_under_backward": bool(grad_ar_ms <= bwd_compute_ms),
            # per-bucket rows of an explicit (parallel.overlap) sync —
            # empty under the implicit partitioner sync
            "buckets": grad_rows,
        } if grad_ar_count else None,
    }

    # -- decode aggregate (serving programs only) ---------------------------
    decode_rows = [s for s in scopes if s["region"] == DECODE_SCOPE]
    decode = None
    if decode_rows:
        d_flops = sum(s["flops"] for s in decode_rows)
        d_bytes = sum(s["bytes"] for s in decode_rows)
        d_comp = d_flops / peak * 1e3
        d_mem = d_bytes / hbm * 1e3
        decode = {
            "flops": round(d_flops),
            "bytes": round(d_bytes),
            "est_compute_ms": round(d_comp, 6),
            "est_memory_ms": round(d_mem, 6),
            "bound": ("compute" if d_comp >= d_mem else "memory")
            if (d_comp or d_mem) else "none",
            "intensity_flops_per_byte": round(d_flops / d_bytes, 3)
            if d_bytes else None,
            "scopes": len(decode_rows),
        }
        if decode_rows_comm:
            # the serving comm table (ISSUE 15): tensor-parallel
            # collectives the sharded tick pays per dispatch — on the
            # tick's critical path (no backward to hide behind), so
            # their wire time adds directly to per-token latency
            decode["comm"] = {
                "ops": len(decode_rows_comm),
                "kinds": {},
                "wire_bytes_total": round(sum(
                    r["wire_bytes_total"] for r in decode_rows_comm)),
                "t_comm_ms": round(sum(
                    r["t_comm_ms"] for r in decode_rows_comm), 6),
                "collectives": [
                    {"kind": r["kind"], "scope": r["scope"],
                     "payload_bytes": r["payload_bytes"],
                     "wire_bytes_total": r["wire_bytes_total"],
                     "t_comm_ms": r["t_comm_ms"],
                     "multiplier": r["multiplier"]}
                    for r in decode_rows_comm],
            }
            for r in decode_rows_comm:
                k = decode["comm"]["kinds"]
                k[r["kind"]] = k.get(r["kind"], 0) + 1

    # -- headline ------------------------------------------------------------
    compute_ms = flops_total / peak * 1e3
    memory_ms = sum(e["bytes"] for e in by_scope.values()) / hbm * 1e3
    est_step_ms = max(compute_ms, memory_ms) + exposed_ms
    agreement = None
    if cost_analysis_flops:
        agreement = round(
            100.0 * (flops_static - cost_analysis_flops)
            / cost_analysis_flops, 3)
    report = {
        "kind": "attribution",
        "device_kind": device_kind or None,
        "model_device": DEFAULT_DEVICE if assumed else device_kind,
        "bandwidth_assumed": assumed,
        "n_devices": n_devices,
        "peak_flops": peak,
        "hbm_bytes_per_s": hbm,
        "flops_total": round(flops_total),
        "flops_static": round(flops_static),
        "cost_analysis_flops": cost_analysis_flops,
        "flops_vs_cost_analysis_pct": agreement,
        "unknown_trip_loops": analysis.unknown_trip_loops,
        "est_compute_ms": round(compute_ms, 6),
        "est_memory_ms": round(memory_ms, 6),
        "est_step_ms": round(est_step_ms, 6),
        "est_mfu_pct": round(100.0 * compute_ms / est_step_ms, 2)
        if est_step_ms else None,
        "scopes": scopes,
        "scope_rollup": {k: round(v) for k, v in sorted(rollup.items())},
        "mfu_gap_rank": mfu_gap_rank,
        "decode": decode,
        "collectives": collectives,
        "comm": comm,
    }
    if meta:
        report.update(meta)
    return report


# ---------------------------------------------------------------------------
# measured path: device lanes of a jax.profiler Chrome-trace capture
# ---------------------------------------------------------------------------

_COMM_NAME_RE = re.compile(
    r"all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute|"
    r"\bsend\b|\brecv\b|\bnccl", re.I)
_DEVICE_PROC_RE = re.compile(r"TPU|/device:|GPU", re.I)


def _merge_intervals(iv: List[Tuple[float, float]]
                     ) -> List[Tuple[float, float]]:
    iv = sorted(iv)
    out: List[Tuple[float, float]] = []
    for s, e in iv:
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _interval_overlap(a: List[Tuple[float, float]],
                      b: List[Tuple[float, float]]) -> float:
    """Total overlap between two merged interval lists."""
    total, i, j = 0.0, 0, 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            total += e - s
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def parse_profile_trace(log_dir: str) -> Optional[Dict[str, Any]]:
    """Parse the Chrome-trace JSON of a ``jax.profiler`` capture under
    ``log_dir`` (the ``Tracer.profile_window()`` output tree) into
    measured device compute-vs-communication wall time.

    Returns None when no trace file or no device lanes exist (a CPU
    capture) — the caller degrades to the static report. Collective ops
    are recognized by name on the device lanes; exposed communication is
    the comm wall minus its interval-overlap with compute."""
    paths = sorted(
        glob.glob(os.path.join(log_dir, "**", "*.trace.json.gz"),
                  recursive=True)
        + glob.glob(os.path.join(log_dir, "**", "*.trace.json"),
                    recursive=True))
    if not paths:
        return None
    path = paths[-1]                    # most recent capture wins
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt") as f:
                data = json.load(f)
        else:
            with open(path) as f:
                data = json.load(f)
    except (OSError, json.JSONDecodeError, EOFError):
        return None
    events = data.get("traceEvents", [])
    device_pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name = (e.get("args") or {}).get("name", "")
            if _DEVICE_PROC_RE.search(name):
                device_pids.add(e.get("pid"))
    if not device_pids:
        return None
    comm_iv: List[Tuple[float, float]] = []
    comp_iv: List[Tuple[float, float]] = []
    comm_us = comp_us = 0.0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        ts, dur = e.get("ts"), e.get("dur")
        if ts is None or dur is None or dur <= 0:
            continue
        if _COMM_NAME_RE.search(e.get("name", "")):
            comm_iv.append((ts, ts + dur))
            comm_us += dur
        else:
            comp_iv.append((ts, ts + dur))
            comp_us += dur
    if not comm_iv and not comp_iv:
        return None
    comm_m, comp_m = _merge_intervals(comm_iv), _merge_intervals(comp_iv)
    overlap_us = _interval_overlap(comm_m, comp_m)
    comm_union = sum(e - s for s, e in comm_m)
    all_iv = _merge_intervals(comm_iv + comp_iv)
    wall_us = (all_iv[-1][1] - all_iv[0][0]) if all_iv else 0.0
    return {
        "source": path,
        "device_lanes": len(device_pids),
        "device_compute_ms": round(comp_us / 1e3, 3),
        "device_comm_ms": round(comm_us / 1e3, 3),
        "exposed_comm_ms": round((comm_union - overlap_us) / 1e3, 3),
        "comm_overlap_frac": round(overlap_us / comm_union, 4)
        if comm_union else None,
        "device_wall_ms": round(wall_us / 1e3, 3),
    }


# ---------------------------------------------------------------------------
# human-readable rendering (the report CLI and notebooks share this)
# ---------------------------------------------------------------------------

def format_report(report: Dict[str, Any], top_n: int = 12) -> str:
    """Compact fixed-width rendering of an attribution report."""
    lines = []
    dev = report.get("model_device") or "?"
    lines.append(
        f"attribution ({'assumed ' if report.get('bandwidth_assumed') else ''}"
        f"{dev}, {report.get('n_devices')} dev): "
        f"{report.get('flops_total'):.3e} FLOPs/step, "
        f"est {report.get('est_step_ms'):.3f} ms, "
        f"est MFU {report.get('est_mfu_pct')}%")
    lines.append(f"{'scope':<34}{'GFLOPs':>10}{'frac':>7}{'MB':>9}"
                 f"{'bound':>8}{'idle_ms':>9}")
    for s in report.get("scopes", [])[:top_n]:
        lines.append(
            f"{s['scope'][:33]:<34}{s['flops'] / 1e9:>10.3f}"
            f"{s['flops_frac']:>7.2%}{s['bytes'] / 1e6:>9.2f}"
            f"{s['bound']:>8}{s['idle_ms']:>9.4f}")
    comm = report.get("comm") or {}
    lines.append(
        f"comm: {comm.get('total_wire_bytes_per_device', 0) / 1e6:.2f} MB "
        f"wire/dev over {comm.get('link')}, "
        f"{comm.get('t_comm_ms', 0):.3f} ms total, "
        f"{comm.get('exposed_ms', 0):.3f} ms exposed "
        f"({comm.get('overlappable_ms', 0):.3f} ms overlappable vs "
        f"{comm.get('backward_compute_budget_ms', 0):.3f} ms bwd budget)")
    gar = comm.get("grad_allreduce")
    if gar:
        lines.append(
            f"grad all-reduce: {gar['ops']} ops, "
            f"{gar['wire_bytes_per_device'] / 1e6:.2f} MB/dev, "
            f"{gar['t_comm_ms']:.3f} ms exposed today, "
            f"{gar['exposed_ms_if_overlapped']:.3f} ms if overlapped with "
            f"backward (hides: {gar['hides_under_backward']})")
        for row in gar.get("buckets") or []:
            sd = row.get("sched_distance")
            lines.append(
                f"  {row['scope']:<32}{row['payload_bytes'] / 1e6:>8.2f} MB"
                f"{row['t_comm_ms']:>10.4f} ms  x{row['multiplier']:g}"
                f"  sched_distance={'-' if sd is None else sd}")
    dec_comm = (report.get("decode") or {}).get("comm")
    if dec_comm:
        kinds = ", ".join(f"{k} x{v}" for k, v in
                          sorted(dec_comm.get("kinds", {}).items()))
        lines.append(
            f"decode tp comm: {dec_comm['ops']} ops ({kinds}), "
            f"{dec_comm['wire_bytes_total'] / 1e6:.3f} MB wire/dev, "
            f"{dec_comm['t_comm_ms']:.4f} ms per tick (critical path)")
    measured = report.get("measured")
    if measured:
        lines.append(
            f"measured: compute {measured['device_compute_ms']} ms, comm "
            f"{measured['device_comm_ms']} ms, exposed "
            f"{measured['exposed_comm_ms']} ms "
            f"(overlap {measured['comm_overlap_frac']})")
    return "\n".join(lines)
