"""The telemetry core — per-call step-time breakdown, compile/retrace
tracking, MFU accounting, and device-memory sampling for the fused hot
loop.

Design rules (README "Observability" has the long form):

- **Fencing.** Device work is async: a dispatch returns as soon as XLA has
  enqueued the program, so a wall-clock timer around the call measures
  *dispatch* cost, not compute. Device time therefore requires a
  ``jax.block_until_ready`` fence on the call's outputs — which serializes
  the pipeline. Telemetry owns that fence and ONLY installs it when
  telemetry is on.
- **Zero overhead when off.** With no Telemetry attached the trainer's hot
  loop is byte-identical to the untelemetered build: same traced step
  function (no health outputs), same dispatch count, same donation, zero
  extra device fetches (``tests/test_obs.py`` pins this).
- **Compile observability.** The trainer keys every dispatch by its *step
  fingerprint* — (K, M, leaf shapes/dtypes) of the stacked group — and
  reports a new fingerprint to :meth:`Telemetry.observe_fingerprint`. The
  first fingerprint is the initial compile; each later one is a RETRACE
  (jit cache miss) — the silent step-time doubler this counter exists to
  surface. Per-compile wall time is the first call's dispatch wall (trace +
  compile + enqueue), and an HLO ``cost_analysis()``-derived FLOPs estimate
  (from the un-compiled Lowered, so it costs one extra trace, not a second
  compile) feeds the live MFU / tokens-per-second metric.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import jax

from .sinks import InMemorySink, JsonlSink, LoggingSink, Sink
from .health import HEALTH_KEYS

__all__ = ["Telemetry", "PEAK_FLOPS", "device_peak_flops",
           "lowered_hlo_flops", "device_memory_stats"]

_log = logging.getLogger("paddle_tpu.telemetry")

# Peak dense bf16 FLOP/s per chip by device kind (public spec sheets) — the
# MFU denominator. bench.py consumes this table too.
PEAK_FLOPS = {
    "TPU v6 lite": 918e12,   # v6e (Trillium)
    "TPU v6e": 918e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v3": 123e12,
    "TPU v2": 46e12,
}

# device kinds whose missing PEAK_FLOPS entry was already logged — the
# fallback is one-shot per kind per process, not silent and not spammy
_unknown_kinds_logged: set = set()


def device_peak_flops(device=None) -> Optional[float]:
    """Spec-sheet peak FLOP/s for ``device`` (default: first local device);
    None when the device kind has no published entry. An unknown TPU kind
    logs a one-shot WARNING (MFU silently reading None on new hardware is
    exactly the kind of quiet observability rot this layer exists to
    prevent); non-TPU kinds (CPU, GPU plugins) log once at DEBUG."""
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    peak = PEAK_FLOPS.get(kind)
    if peak is None and kind not in _unknown_kinds_logged:
        _unknown_kinds_logged.add(kind)
        if "TPU" in kind:
            _log.warning(
                "no PEAK_FLOPS entry for device kind %r — est_mfu_pct will "
                "be None; add the spec-sheet bf16 peak to "
                "obs.telemetry.PEAK_FLOPS (or pass Telemetry(peak_flops=))",
                kind)
        else:
            _log.debug("device kind %r has no peak-FLOPs entry (MFU "
                       "accounting disabled)", kind)
    return peak


def lowered_hlo_flops(lowered) -> Optional[float]:
    """FLOPs estimate from a ``jax.stages.Lowered``'s ``cost_analysis()``
    (XLA's HLO-level count; no compile needed). Returns None when the
    backend doesn't implement cost analysis."""
    try:
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):        # older jax returns [dict]
            ca = ca[0] if ca else {}
        flops = ca.get("flops")
        return float(flops) if flops is not None else None
    except Exception:                            # pragma: no cover - backend
        return None


def device_memory_stats(device=None) -> Dict[str, int]:
    """``device.memory_stats()`` with the None/unimplemented cases folded to
    an empty dict (CPU returns None; some plugins raise)."""
    device = device or jax.devices()[0]
    try:
        stats = device.memory_stats()
    except Exception:                            # pragma: no cover - backend
        return {}
    if not stats:
        return {}
    return {k: int(v) for k, v in stats.items()
            if isinstance(v, (int, np.integer))}


def _scalar(x):
    """Device/npy scalar -> strict-JSON-safe value: finite floats pass
    through, NaN/Inf become None (json.dumps would emit bare ``NaN``
    literals otherwise — invalid per RFC 8259, breaking strict parsers on
    exactly the diagnostic file a NaN run produces). The
    ``nonfinite_count`` sentinel (always a finite count) carries the
    poisoned-run signal."""
    v = float(np.asarray(x))
    return v if np.isfinite(v) else None


class Telemetry:
    """Pluggable-sink telemetry for the training hot loop.

    Args:
      sinks: Sink instances (or the classes themselves — ``JsonlSink``
        still needs a path, so classes only work for no-arg sinks);
        defaults to one :class:`InMemorySink`.
      health: trace the health monitors (grad/param/update norms, NaN
        sentinel) into the compiled step. Costs a few fused reduces on
        device; off by default only when the caller says so.
      memory: sample ``device.memory_stats()`` once per fused call.
      fence: block on the call's outputs to measure true device time.
        Turn off on pathological transports where ``block_until_ready``
        does not fence (experiments/PERF.md "Incident") — dispatch time
        and throughput-derived metrics remain.
      flops_per_step: analytic FLOPs per optimizer step (e.g.
        ``bench.transformer_train_flops``). When absent, the HLO
        cost-analysis estimate (per *call*, i.e. K steps) is used.
      tokens_per_step: tokens consumed per optimizer step — enables
        ``tokens_per_sec`` in step records.
      peak_flops: MFU denominator override (defaults to the spec-sheet
        table keyed by device kind; None on CPU, which disables MFU).
      retrace_warn_threshold: one-shot WARNING once ``retrace_count``
        reaches this many distinct step fingerprints beyond the first
        compile — the silent-recompile trap (each distinct ragged pass
        tail compiles a new program). The warning points at
        ``batched(drop_last=True)`` / padding to a fixed shape. The
        default (3) never fires for the healthy fused pattern (one
        full-group shape + one tail shape = 1 retrace).
    """

    def __init__(self, sinks: Optional[Sequence[Sink]] = None,
                 health: bool = True, memory: bool = True,
                 fence: bool = True,
                 flops_per_step: Optional[float] = None,
                 tokens_per_step: Optional[float] = None,
                 peak_flops: Optional[float] = None,
                 retrace_warn_threshold: int = 3):
        if sinks is None:
            sinks = [InMemorySink()]
        self.sinks: List[Sink] = [s() if isinstance(s, type) else s
                                  for s in sinks]
        self.health = health
        self.memory = memory
        self.fence = fence
        self.flops_per_step = flops_per_step
        self.tokens_per_step = tokens_per_step
        self._peak_flops = peak_flops
        # compile tracking
        self._fingerprints: Dict[Any, int] = {}
        self.compile_count = 0
        self.retrace_count = 0
        self.retrace_warn_threshold = int(retrace_warn_threshold)
        self._retrace_warned = False
        self.hlo_flops_per_call: Optional[float] = None
        # memory peaks
        self.peak_bytes: Optional[int] = None       # per-pass peak
        self.peak_bytes_run: Optional[int] = None   # whole-run peak
        # latest health scalars (host-side, refreshed per call)
        self.last_health: Dict[str, float] = {}
        self._steps_emitted = 0
        # set by host_pipeline when the stager thread missed its join
        # deadline at close — surfaced in summary() so a leak is visible
        # in the run's own output, not only in a log line
        self.stager_leaked = False
        # bumped by background workers (AsyncCheckpointer's write thread)
        # when their work fails — the failure also re-raises at the
        # owner's next fence, but the counter survives into summary()
        # even when the fence is never reached (interpreter exit)
        self.background_failures = 0
        self._closed = False

    # -- compile / retrace -------------------------------------------------

    def observe_fingerprint(self, fingerprint) -> bool:
        """Report a dispatch's step fingerprint. Returns True when it is
        NEW (this dispatch will trace + compile). The first fingerprint is
        the initial compile; later new ones increment ``retrace_count``."""
        if fingerprint in self._fingerprints:
            return False
        self._fingerprints[fingerprint] = self.compile_count
        self.compile_count += 1
        if self.compile_count > 1:
            self.retrace_count += 1
            if (not self._retrace_warned
                    and self.retrace_count >= self.retrace_warn_threshold):
                self._retrace_warned = True
                _log.warning(
                    "%d distinct step fingerprints have each compiled their "
                    "own program (%d retraces) — every distinct batch/group "
                    "shape is a silent recompile. Ragged pass tails are the "
                    "usual cause: use data.batched(..., drop_last=True), pad "
                    "batches to a fixed shape, or make the pass length a "
                    "multiple of steps_per_call*grad_accum.",
                    self.compile_count, self.retrace_count)
        return True

    def record_compile(self, fingerprint, wall_s: float,
                       hlo_flops: Optional[float] = None,
                       meta: Optional[Dict[str, Any]] = None,
                       cache_hit: Optional[bool] = None,
                       autotune_trials: Optional[int] = None) -> None:
        """Emit a compile record (fires once per new fingerprint).
        ``cache_hit``: whether the executable came out of the persistent
        compilation cache (None = cache not configured / unknown);
        ``autotune_trials``: kernel-tuner trials this compile paid
        (0 = every key was already cached). Both keys are always present
        on the record so downstream readers need no schema probe."""
        if hlo_flops is not None:
            self.hlo_flops_per_call = hlo_flops
        rec = {"kind": "compile", "ts": time.time(),
               "fingerprint": str(fingerprint),
               "compile_count": self.compile_count,
               "retrace_count": self.retrace_count,
               "wall_s": round(float(wall_s), 6),
               "hlo_flops": hlo_flops,
               "cache_hit": cache_hit,
               "autotune_trials": (None if autotune_trials is None
                                   else int(autotune_trials))}
        if meta:
            rec.update(meta)
        self._emit(rec)

    # -- memory ------------------------------------------------------------

    def begin_pass(self, pass_id: int) -> None:
        """Reset the per-pass memory peak (whole-run peak persists)."""
        self.peak_bytes = None

    def sample_memory(self) -> Optional[int]:
        """Sample device memory; returns current bytes-in-use (None when
        the backend reports nothing, e.g. CPU). The per-pass peak is the
        max of the bytes-in-use SAMPLES this pass (the device's own
        ``peak_bytes_in_use`` counter is process-monotonic — it never
        resets, so it can only feed the whole-run peak); sampling
        happens once per fused call, so short intra-call spikes between
        samples are not observed."""
        if not self.memory:
            return None
        stats = device_memory_stats()
        if not stats:
            return None
        cur = stats.get("bytes_in_use")
        dev_peak = stats.get("peak_bytes_in_use")
        if cur is not None:
            self.peak_bytes = max(self.peak_bytes or 0, cur)
        run_cand = dev_peak if dev_peak is not None else cur
        if run_cand is not None:
            self.peak_bytes_run = max(self.peak_bytes_run or 0, run_cand)
        return cur

    # -- step records --------------------------------------------------------

    def emit_step(self, record: Dict[str, Any]) -> Dict[str, Any]:
        """Finalize and emit one per-call step record. The caller provides
        the breakdown fields; this layer attaches compile counters, memory,
        health, and the throughput/MFU derivations. Returns the finalized
        record (the exact object the sinks received — the trainer hands it
        to ``events.TelemetryRecord``)."""
        rec = {"kind": "step", "ts": time.time()}
        rec.update(record)
        rec["compile_count"] = self.compile_count
        rec["retrace_count"] = self.retrace_count
        cur = self.sample_memory()
        rec["bytes_in_use"] = cur
        rec["peak_bytes"] = self.peak_bytes
        rec.update(self.last_health)
        for k in HEALTH_KEYS:          # fixed schema even with health=False
            rec.setdefault(k, None)
        # host-pipeline overlap keys: fixed schema; None outside pipelined
        # mode (stage_ms = background stack+shard wall, drain_wait_ms = the
        # blocking loss fetch at drain, overlap_frac = fraction of staging
        # cost hidden from the main thread)
        for k in ("stage_ms", "drain_wait_ms", "overlap_frac"):
            rec.setdefault(k, None)
        # throughput / MFU: prefer true device time (fenced); fall back to
        # dispatch wall when fencing is off (labelled by fenced=False).
        # PIPELINED records (drain_wait_ms present) get NO per-record
        # throughput/MFU: without a fence the per-call device time is
        # unknowable, and a group released late (boundary/pass-end
        # drain-all, host-bound windows) can drain with ~0 wait — deriving
        # a rate from dispatch+drain would inflate it arbitrarily (>100%
        # MFU). summary() derives the honest aggregate steps/s from the
        # record timestamps instead.
        k_steps = rec.get("k_steps") or 1
        dev_s = rec.get("device_ms")
        disp_s = rec.get("dispatch_ms")
        rec.setdefault("profiled", False)   # fixed schema
        pipelined = rec.get("drain_wait_ms") is not None
        # profiled calls (anomaly-armed jax.profiler capture) fence INSIDE
        # the dispatch window, so their dispatch_ms includes device compute
        # — no honest per-record rate can be derived from them either
        total_ms = (0.0 if pipelined or rec["profiled"]
                    else (dev_s or 0.0) + (disp_s or 0.0))
        rec["fenced"] = bool(self.fence and dev_s is not None)
        if total_ms > 0:
            per_step_s = total_ms * 1e-3 / k_steps
            if self.tokens_per_step:
                rec["tokens_per_sec"] = round(
                    self.tokens_per_step / per_step_s, 2)
            flops = self.flops_per_step
            if flops is None and self.hlo_flops_per_call:
                flops = self.hlo_flops_per_call / k_steps
            peak = (self._peak_flops if self._peak_flops is not None
                    else device_peak_flops())
            rec["est_mfu_pct"] = (
                round(100.0 * flops / per_step_s / peak, 2)
                if (flops and peak) else None)
        # strict-JSON guarantee: no bare NaN/Inf literals reach a sink
        # (a NaN loss would otherwise break every downstream parser)
        for k, v in rec.items():
            if isinstance(v, float) and not np.isfinite(v):
                rec[k] = None
        self._steps_emitted += 1
        self._emit(rec)
        return rec

    def emit_event(self, record: Dict[str, Any]) -> Dict[str, Any]:
        """Emit one non-step record to every sink — the carrier for
        ``kind="attribution"`` reports (``Trainer.attribution_report``)
        and ``kind="anomaly"`` verdicts, so a run's JSONL holds the whole
        story (``obs.report`` reads these back). Stamps ``ts`` and a
        ``kind`` (default ``"event"``) when absent."""
        rec = {"kind": record.get("kind", "event"), "ts": time.time()}
        rec.update(record)
        self._emit(rec)
        return rec

    def update_health(self, health_host: Dict[str, Any]) -> Dict[str, float]:
        """Record the latest fetched health scalars (host values for ONE
        optimizer step). Returns the JSON-safe dict it stored."""
        out = {}
        for k in HEALTH_KEYS:
            if k in health_host:
                out[k] = _scalar(health_host[k])
        self.last_health = out
        return out

    # -- plumbing ------------------------------------------------------------

    def _emit(self, rec: Dict[str, Any]) -> None:
        for s in self.sinks:
            try:
                s.emit(rec)
            except Exception:                    # a broken sink must never
                _log.exception("telemetry sink %r failed", s)  # kill training

    def close(self) -> None:
        """Emit one final ``summary`` record to every sink, then close
        them. The summary makes a run's JSONL self-contained — the
        aggregate view (mean breakdowns, retrace totals, peak memory,
        ``stager_leaked``) previously existed only in-process. Idempotent:
        a second close neither re-emits nor fails."""
        if not self._closed:
            self._closed = True
            try:
                self._emit({"kind": "summary", "ts": time.time(),
                            **self.summary()})
            except Exception:
                _log.exception("telemetry summary emit failed at close")
        for s in self.sinks:
            try:
                s.close()
            except Exception:
                _log.exception("telemetry sink %r close failed", s)

    # -- summaries -----------------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        """Aggregate view (bench.py wires this into its output JSON)."""
        mem = self.peak_bytes_run
        out = {"steps_emitted": self._steps_emitted,
               "compile_count": self.compile_count,
               "retrace_count": self.retrace_count,
               "hlo_flops_per_call": self.hlo_flops_per_call,
               "peak_bytes": mem,
               "stager_leaked": self.stager_leaked,
               "background_failures": self.background_failures}
        for s in self.sinks:
            if isinstance(s, InMemorySink) and s.records:
                steps = s.by_kind("step")
                if steps:
                    for key in ("host_stack_ms", "shard_ms", "dispatch_ms",
                                "device_ms", "replay_ms", "stage_ms",
                                "drain_wait_ms", "overlap_frac"):
                        # profiled records fence inside their dispatch
                        # window (anomaly-armed capture) — their breakdown
                        # is not comparable, same rule as emit_step
                        vals = [r[key] for r in steps
                                if r.get(key) is not None
                                and not r.get("profiled")]
                        if vals:
                            out[f"mean_{key}"] = round(
                                float(np.mean(vals)), 4)
                    last = steps[-1]
                    for key in ("tokens_per_sec", "est_mfu_pct",
                                "grad_norm"):
                        if last.get(key) is not None:
                            out[key] = last[key]
                    # pipelined aggregate rate from record timestamps (the
                    # per-record rate is deliberately absent — see
                    # emit_step): steps completed between the first and
                    # last drained records over their wall span
                    piped = [r for r in steps
                             if r.get("drain_wait_ms") is not None]
                    if len(piped) >= 2:
                        span = piped[-1]["ts"] - piped[0]["ts"]
                        done = sum(r.get("k_steps") or 1
                                   for r in piped[1:])
                        if span > 0:
                            rate = done / span
                            out["pipelined_steps_per_sec"] = round(rate, 3)
                            if self.tokens_per_step:
                                out["pipelined_tokens_per_sec"] = round(
                                    rate * self.tokens_per_step, 2)
                break
        return out
