"""Structured HLO analyzer — the device-side half of the observability
stack (ISSUE 6 tentpole, part 1).

PR 2 gave the compiled step ONE number (``cost_analysis()`` FLOPs feeding
``est_mfu_pct``). This module opens the program up: it parses the
optimized (post-SPMD) HLO text into a computation-aware op inventory so
the attribution layer (:mod:`.attribution`) can answer *where* the FLOPs,
bytes, and collective traffic go — per ``jax.named_scope`` region, per
collective op, forward vs backward.

What the parser extracts, and why each piece exists:

- **Instructions with metadata.** Every HLO op carries
  ``metadata={op_name="jit(f)/jit(main)/transpose(jvp(block))/attn/dot"}``
  — the traced path through ``jax.named_scope`` regions, with autodiff
  transforms wrapped around the outermost scope (``jvp(x)`` = forward,
  ``transpose(jvp(x))`` = backward). :func:`scope_of` unwraps the
  transforms and filters the non-scope components (jit frames, while/body
  machinery, einsum specs, arg path labels), recovering the scope tree
  PR 2 annotated into the models.
- **FLOPs for the ops that carry them.** ``dot`` FLOPs are exact from the
  printed shapes (2 x result elements x contracted elements — the same
  convention XLA's own HloCostAnalysis uses), ``convolution`` from the
  kernel size and ``dim_labels``; everything else contributes its buffer
  traffic but no FLOPs (elementwise is noise next to the MXU work on any
  real model; ``bench.py --smoke`` gates the total against
  ``cost_analysis()`` within 5%).
- **The call graph, with loop trip counts.** ``cost_analysis()`` counts a
  ``while`` body ONCE — so does the static op inventory (that is what
  makes the two comparable) — but the fused step *executes* the body K
  times (steps-per-call scan), each step M times (grad-accum scan), each
  microbatch L times (remat scan-over-layers). The analyzer recovers each
  loop's trip count from its condition computation (``compare(iv,
  constant(K)), direction=LT``) and propagates multipliers through the
  call graph, so every op carries both its static count and its true
  per-dispatch execution count.
- **A structured collective inventory.** Every collective op with kind,
  dtype, payload bytes (variadic tuples aggregated; async ``-start``
  forms counted once, results only), replica-group size, scope, and
  forward/backward direction — generalized from the regex pass that
  lived in ``experiments/scaling_projection.py``. The legacy
  :func:`parse_collectives` aggregate (ring-factor wire bytes by kind) is
  promoted here VERBATIM as the single source of truth; the projection
  experiment now imports it, and its numbers are pinned unchanged.

Bandwidth tables (``ICI_BANDWIDTH`` / ``DCN_BYTES_PER_S`` /
``HBM_BANDWIDTH``) follow the ``PEAK_FLOPS`` pattern: public spec-sheet
numbers keyed by ``device_kind``, used as the cost model for the
exposed-communication estimate and the per-scope roofline. Provenance:
ICI one-way bytes/s = aggregate spec Gbit/s / 8 / 2 (half the
bidirectional aggregate), e.g. v5e 1600 Gbit/s -> 100 GB/s one-way — the
same constant SCALING_r05 used; HBM from the published GB/s figures. On
unknown kinds (CPU test meshes) the attribution layer substitutes the
``DEFAULT_DEVICE`` entry and labels the report ``bandwidth_assumed`` —
a static what-if for the hardware the step is destined for, never a
silent guess.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "DTYPE_BYTES", "ICI_BANDWIDTH", "DCN_BYTES_PER_S", "HBM_BANDWIDTH",
    "DEFAULT_DEVICE", "HloOp", "CollectiveOp", "ModuleAnalysis",
    "parse_module", "collective_inventory", "parse_collectives",
    "scope_of", "COLLECTIVE_KINDS",
]

DTYPE_BYTES = {"f64": 8, "f32": 4, "s32": 4, "u32": 4, "bf16": 2,
               "f16": 2, "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1,
               "s64": 8, "u64": 8, "c64": 8, "c128": 16,
               "f8e4m3fn": 1, "f8e5m2": 1}

# One-way per-chip ICI bytes/s (public spec sheets: aggregate Gbit/s / 8
# / 2). v5e matches the SCALING_r05 constant exactly.
ICI_BANDWIDTH = {
    "TPU v4": 150e9,         # 2400 Gbit/s aggregate
    "TPU v5 lite": 100e9,    # v5e: 1600 Gbit/s aggregate
    "TPU v5e": 100e9,
    "TPU v5p": 300e9,        # 4800 Gbit/s aggregate
    "TPU v6 lite": 224e9,    # v6e (Trillium): 3584 Gbit/s aggregate
    "TPU v6e": 224e9,
}

# Per-chip DCN share when 8 chips sit behind one host NIC (the
# SCALING_r05 constant — inter-slice traffic crosses the data-center
# network, not ICI).
DCN_BYTES_PER_S = 25e9 / 8

# HBM bytes/s per chip (public spec sheets) — the roofline's memory axis.
HBM_BANDWIDTH = {
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1638e9,   # Trillium: 2x v5e
    "TPU v6e": 1638e9,
}

# The what-if device substituted when the local device kind has no table
# entries (CPU test meshes): the fleet's v5e, labelled `bandwidth_assumed`.
DEFAULT_DEVICE = "TPU v5e"

COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute")

# ---------------------------------------------------------------------------
# legacy line-based collective parse — promoted VERBATIM from
# experiments/scaling_projection.py (single source of truth; the
# projection numbers are pinned against this exact arithmetic)
# ---------------------------------------------------------------------------

# XLA aggregates gradients into VARIADIC collectives whose result is a
# tuple: `(f32[64]{0}, f32[128,3]{1,0}) all-reduce(...)` — the shape group
# must accept both single shapes and tuples.
_SHAPE = r"\w+\[[\d,]*\](?:\{[^}]*\})?"
_COLL_RE = re.compile(
    r"((?:" + _SHAPE + r")|\((?:" + _SHAPE + r")(?:,\s*(?:" + _SHAPE +
    r"))*\))\s+"
    r"(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
    r"(-start)?\(")


def _shape_bytes(shape_s: str, kind: str = "", is_start: bool = False) -> int:
    """Total bytes of a shape or tuple-of-shapes string, counting only the
    RESULT buffers for async '*-start' forms. Per-kind, per XLA's HLO:
    all-gather-start and collective-permute-start carry
    ``(operand..., result..., [u32 contexts])`` tuples (count the trailing
    result half after dropping the dimensionless context scalars);
    all-reduce/reduce-scatter/all-to-all '-start' shapes are already
    results-only (count everything). The n=8 sync-HLO cross-check in
    ``experiments/scaling_projection.py`` guards this assumption against
    XLA lowering drift."""
    shapes = list(re.finditer(r"(\w+)\[([\d,]*)\]", shape_s))
    if is_start:
        shapes = [m for m in shapes
                  if not (m.group(1) in ("u32", "s32") and not m.group(2))]
        if kind in ("all-gather", "collective-permute") \
                and len(shapes) >= 2 and len(shapes) % 2 == 0:
            shapes = shapes[len(shapes) // 2:]
    total = 0
    for m in shapes:
        dt, dims = m.group(1), m.group(2)
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * DTYPE_BYTES.get(dt, 4)
    return total


def _group_size(op_line: str, default: int) -> int:
    """Replica-group size of one collective op: the ring factor must use
    the GROUP the op actually spans (a tp=4 activation all-reduce on a
    dp x tp mesh rings over 4 devices, not the whole mesh)."""
    m = re.search(r"replica_groups=\{\{([\d,]+)\}", op_line)
    if m:                          # explicit form {{0,1,2,3},{4,...}}
        return len(m.group(1).split(","))
    m = re.search(r"replica_groups=\[(\d+),(\d+)\]<=", op_line)
    if m:                          # iota form [groups, group_size]<=[...]
        return int(m.group(2))
    return default


def _ring_wire_bytes(kind: str, buffer_bytes: float, group: int) -> float:
    """Per-device wire bytes of one collective under the standard ring
    algorithm (the factor set SCALING_r05 is built on)."""
    if kind == "all-reduce":
        return 2.0 * buffer_bytes * (group - 1) / group
    if kind == "reduce-scatter":
        return 1.0 * buffer_bytes * (group - 1)   # result is the 1/g shard
    if kind in ("all-gather", "all-to-all"):
        return 1.0 * buffer_bytes * (group - 1) / group
    return float(buffer_bytes)                    # collective-permute


def parse_collectives(hlo: str, n_devices: int):
    """Per-device wire bytes by collective kind (ring-algorithm factors
    over each op's replica group). This is the exact aggregate
    ``experiments/scaling_projection.py`` always computed — kept
    line-based and byte-for-byte compatible so the committed SCALING_*
    projections reproduce."""
    # XLA interleaves /*index=N*/ comments inside big variadic tuples —
    # strip them or the tuple regex stops at the first comment
    hlo = re.sub(r"/\*.*?\*/", "", hlo)
    by_kind: Dict[str, Dict[str, Any]] = {}
    for line in hlo.splitlines():
        m = _COLL_RE.search(line)
        if not m:
            continue
        shape_s, kind = m.group(1), m.group(2)
        b = _shape_bytes(shape_s, kind=kind, is_start=bool(m.group(3)))
        g = _group_size(line, n_devices)
        if g <= 1:                 # degenerate 1-device group moves nothing
            continue
        wire = _ring_wire_bytes(kind, b, g)
        e = by_kind.setdefault(kind, {"ops": 0, "buffer_bytes": 0,
                                      "wire_bytes_per_device": 0.0,
                                      "group_sizes": []})
        e["ops"] += 1
        e["buffer_bytes"] += b
        e["wire_bytes_per_device"] += wire
        if g not in e["group_sizes"]:
            e["group_sizes"].append(g)
    return by_kind


# ---------------------------------------------------------------------------
# scope extraction from op_name metadata
# ---------------------------------------------------------------------------

# Transform wrappers jax prints around the outermost scope component:
# jvp(x) marks the forward pass, transpose(jvp(x)) the backward.
_WRAP_RE = re.compile(
    r"^(jvp|transpose|custom_jvp|custom_vjp|vmap|pmap|shard_map|remat|"
    r"checkpoint|named)\((.*)\)$")

# Path machinery that is NOT a named scope: control-flow frames and the
# checkpoint/remat markers the layer scan inserts.
_SKIP_COMPONENTS = {"", "while", "body", "cond", "branch", "scan",
                    "checkpoint", "remat", "rematted_computation"}

# A scope component as jax.named_scope would have produced it — filters
# einsum specs ('bqhd,bkhd->bhqk'), arg-path labels ("batches['x']",
# "opt_state.m[...]"), and primitive suffixes.
_SCOPE_NAME_RE = re.compile(r"^[A-Za-z_][\w.\-]*$")


def _split_path(op_name: str) -> List[str]:
    """Split an op_name on '/' at paren depth 0 ONLY: a transform wrapper
    may span several scope components (``transpose(jvp(scope_a/scope_b))``)
    and a naive split would shear its parentheses apart — losing both the
    inner scopes and the backward flag."""
    parts, cur, depth = [], [], 0
    for ch in op_name:
        if ch == "/" and depth == 0:
            parts.append("".join(cur))
            cur = []
            continue
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(0, depth - 1)
        cur.append(ch)
    parts.append("".join(cur))
    return parts


def scope_of(op_name: str) -> Tuple[Tuple[str, ...], bool]:
    """``(scope_path, backward)`` of one ``metadata op_name`` string.

    The last path component is the primitive (dropped); ``jit(...)``
    frames and control-flow machinery are dropped; transform wrappers are
    unwrapped (``transpose(...)`` anywhere marks the op backward), and a
    wrapper whose inside spans several components
    (``transpose(jvp(grad_sync/bucket0))``) re-expands in place. What
    survives is the ``jax.named_scope`` nesting, e.g.
    ``('block_scan', 'attn')``."""
    comps: List[str] = []
    backward = False
    work = _split_path(op_name)[:-1]
    while work:
        part = work.pop(0)
        if part.startswith("jit("):
            continue
        m = _WRAP_RE.match(part)
        if m:
            if m.group(1) == "transpose":
                backward = True
            work[0:0] = _split_path(m.group(2))
            continue
        if part in _SKIP_COMPONENTS:
            continue
        if not _SCOPE_NAME_RE.match(part):
            continue
        comps.append(part)
    return tuple(comps), backward


# ---------------------------------------------------------------------------
# computation-aware module parse
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class HloOp:
    """One parsed HLO instruction."""
    name: str
    opcode: str
    computation: str
    result_shapes: List[Tuple[str, Tuple[int, ...]]]
    operand_shapes: List[Tuple[str, Tuple[int, ...]]]
    operand_names: List[str]
    attrs: str
    op_name: str
    scope: Tuple[str, ...]
    backward: bool
    is_root: bool
    flops: float = 0.0           # one execution of the enclosing computation
    bytes: float = 0.0           # operand + result buffer bytes
    multiplier: float = 1.0      # executions per dispatch (loop-aware)
    fusion_internal: bool = False


@dataclasses.dataclass
class CollectiveOp:
    """One collective in the inventory (structured form)."""
    kind: str
    name: str
    computation: str
    dtypes: List[str]
    payload_bytes: int
    group_size: int
    variadic: int                # result buffers aggregated into the op
    is_async: bool               # '-start' form
    scope: Tuple[str, ...]
    backward: bool
    multiplier: float
    wire_bytes: float            # per execution, ring factors
    # Scheduling distance of an async pair: compute ops (fusions / dots /
    # convs / custom-calls / flop-carrying ops) between this '-start' and
    # its matching '-done' in the printed instruction order of their
    # computation — 0 means the collective is issued and immediately
    # awaited (no overlap), larger means the scheduler found independent
    # compute to hide it behind. None for synchronous collectives (no
    # start/done pair) and unmatched starts.
    sched_distance: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["scope"] = "/".join(self.scope) or "(unscoped)"
        return d


@dataclasses.dataclass
class ModuleAnalysis:
    """The parsed module: every instruction, grouped by computation, with
    loop-aware execution multipliers resolved."""
    ops: List[HloOp]
    computations: Dict[str, List[HloOp]]
    entry: str
    trip_counts: Dict[str, float]        # while-op name -> trips
    unknown_trip_loops: int = 0

    def flops_static(self) -> float:
        return float(sum(op.flops for op in self.ops))

    def flops_loop_aware(self) -> float:
        return float(sum(op.flops * op.multiplier for op in self.ops))


_COMP_HEAD_RE = re.compile(
    r"^(ENTRY\s+)?%?([\w.\-]+)\s*(?:\(.*\))?\s*->\s*.*\{\s*$")
_INSTR_RE = re.compile(
    r"^\s*(ROOT\s+)?%?([\w.\-]+)\s*=\s*"
    r"(\([^=]*?\)|\S+)\s+"       # result type: tuple or single shape
    r"([\w\-]+)\(")              # opcode
_SHAPE_FIND_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def _parse_shapes(text: str) -> List[Tuple[str, Tuple[int, ...]]]:
    out = []
    for m in _SHAPE_FIND_RE.finditer(text):
        dims = tuple(int(d) for d in m.group(2).split(",") if d)
        out.append((m.group(1), dims))
    return out


def _numel(dims: Tuple[int, ...]) -> int:
    n = 1
    for d in dims:
        n *= d
    return n


def _shapes_bytes(shapes: List[Tuple[str, Tuple[int, ...]]]) -> float:
    return float(sum(_numel(dims) * DTYPE_BYTES.get(dt, 4)
                     for dt, dims in shapes))


def _balanced_paren_span(text: str, open_idx: int) -> int:
    """Index one past the ')' matching the '(' at ``open_idx``."""
    depth = 0
    for i in range(open_idx, len(text)):
        c = text[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return i + 1
    return len(text)


def _dot_flops(op: HloOp) -> float:
    """2 x result elements x contracted elements — XLA's own dot count."""
    if not op.result_shapes or not op.operand_shapes:
        return 0.0
    m = re.search(r"lhs_contracting_dims=\{([\d,]*)\}", op.attrs)
    lhs = op.operand_shapes[0][1]
    contracted = 1
    if m:
        for d in m.group(1).split(","):
            if d and int(d) < len(lhs):
                contracted *= lhs[int(d)]
    return 2.0 * _numel(op.result_shapes[0][1]) * contracted


def _conv_flops(op: HloOp) -> float:
    """2 x result elements x (kernel elements / output features): per
    output element the kernel's spatial x input-feature window multiplies
    in once (grouped convs fall out — the kernel already holds
    in-features-per-group)."""
    if len(op.operand_shapes) < 2 or not op.result_shapes:
        return 0.0
    rhs_dt, rhs = op.operand_shapes[1]
    m = re.search(r"dim_labels=[\w?]+_([\w?]+)->", op.attrs)
    out_features = 1
    if m and "o" in m.group(1):
        o_idx = m.group(1).index("o")
        if o_idx < len(rhs):
            out_features = rhs[o_idx]
    return (2.0 * _numel(op.result_shapes[0][1]) * _numel(rhs)
            / max(out_features, 1))


# Opcodes XLA's HloCostAnalysis charges 1 flop per OUTPUT element
# (calibrated against the CPU backend: add/mul/div/select/compare/convert
# each count; exp/tanh/rsqrt land in the separate `transcendentals`
# counter and are deliberately NOT flops here either).
ELEMENTWISE_FLOP_OPS = frozenset((
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "select", "compare", "convert", "negate", "abs", "sign", "floor",
    "ceil", "round-nearest-afz", "round-nearest-even", "power",
    "remainder", "clamp", "and", "or", "xor", "not", "shift-left",
    "shift-right-arithmetic", "shift-right-logical", "is-finite",
    "atan2"))

# Pure data movement / control flow: no flops, and their buffer bytes are
# plumbing (a while op's operand tuple is the whole carried state), so
# they are excluded from the roofline's memory-traffic proxy too.
PLUMBING_OPS = frozenset((
    "while", "conditional", "call", "tuple", "get-tuple-element",
    "parameter", "constant", "iota", "after-all", "bitcast",
    "partition-id", "replica-id", "get-dimension-size", "opt-barrier",
    "domain"))


def _elementwise_flops(op: "HloOp") -> float:
    if not op.result_shapes:
        return 0.0
    return float(_numel(op.result_shapes[0][1]))


def _reduce_flops(op: "HloOp") -> float:
    """~(input - output) elements per reduction body op — approximated as
    the non-scalar operand elements (the init scalars contribute ~0)."""
    return float(sum(_numel(dims) for _, dims in op.operand_shapes if dims))


_CALL_ATTR_RES = (
    ("body", re.compile(r"body=%?([\w.\-]+)")),
    ("condition", re.compile(r"condition=%?([\w.\-]+)")),
    ("calls", re.compile(r"calls=%?([\w.\-]+)")),
    ("to_apply", re.compile(r"to_apply=%?([\w.\-]+)")),
)
_BRANCH_RE = re.compile(r"branch_computations=\{([^}]*)\}")


def _op_calls(op: HloOp) -> List[Tuple[str, str]]:
    """(kind, computation) references this op makes."""
    refs = []
    for kind, rx in _CALL_ATTR_RES:
        m = rx.search(op.attrs)
        if m:
            refs.append((kind, m.group(1)))
    m = _BRANCH_RE.search(op.attrs)
    if m:
        for name in re.findall(r"%?([\w.\-]+)", m.group(1)):
            refs.append(("branch", name))
    return refs


def parse_module(hlo: str) -> ModuleAnalysis:
    """Parse optimized HLO text into a computation-aware op inventory with
    loop-aware execution multipliers (see the module docstring)."""
    hlo = re.sub(r"/\*.*?\*/", "", hlo)
    computations: Dict[str, List[HloOp]] = {}
    order: List[str] = []
    entry = None
    current: Optional[str] = None
    constants: Dict[str, int] = {}    # %name -> scalar int value (s32/u32)

    for line in hlo.splitlines():
        head = _COMP_HEAD_RE.match(line)
        if head and not line.startswith(" "):
            current = head.group(2)
            computations[current] = []
            order.append(current)
            if head.group(1):
                entry = current
            continue
        if line.strip() == "}":
            current = None
            continue
        if current is None:
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        is_root, name, rtype, opcode = (bool(m.group(1)), m.group(2),
                                        m.group(3), m.group(4))
        open_idx = m.end() - 1
        close = _balanced_paren_span(line, open_idx)
        operands = line[open_idx + 1:close - 1]
        attrs = line[close:]
        meta = re.search(r'op_name="([^"]*)"', attrs)
        op_name = meta.group(1) if meta else ""
        scope, backward = scope_of(op_name)
        op = HloOp(
            name=name, opcode=opcode, computation=current,
            result_shapes=_parse_shapes(rtype),
            operand_shapes=_parse_shapes(operands),
            operand_names=re.findall(r"%([\w.\-]+)", operands),
            attrs=attrs, op_name=op_name, scope=scope, backward=backward,
            is_root=is_root)
        if opcode == "constant":
            cm = re.match(r"\s*(-?\d+)\s*$", operands)
            if cm and op.result_shapes and not op.result_shapes[0][1]:
                constants[name] = int(cm.group(1))
        if opcode == "dot":
            op.flops = _dot_flops(op)
        elif opcode == "convolution":
            op.flops = _conv_flops(op)
        elif opcode in ELEMENTWISE_FLOP_OPS:
            op.flops = _elementwise_flops(op)
        elif opcode in ("reduce", "reduce-window"):
            op.flops = _reduce_flops(op)
        if opcode not in PLUMBING_OPS:
            op.bytes = (_shapes_bytes(op.operand_shapes)
                        + _shapes_bytes(op.result_shapes))
        computations[current].append(op)

    if entry is None and order:
        entry = order[-1]

    # -- trip counts: while condition = compare(iv, constant, LT) ----------
    analysis = ModuleAnalysis(ops=[], computations=computations,
                              entry=entry or "", trip_counts={})

    def trip_of(cond_comp: str) -> Optional[float]:
        for op in computations.get(cond_comp, []):
            if op.is_root and op.opcode == "compare":
                direction = re.search(r"direction=(\w+)", op.attrs)
                names = op.operand_names
                if direction and names:
                    if direction.group(1) == "LT" and names[-1] in constants:
                        return float(constants[names[-1]])
                    if direction.group(1) == "GT" and names[0] in constants:
                        return float(constants[names[0]])
        return None

    # -- propagate execution multipliers through the call DAG --------------
    comp_mult: Dict[str, float] = {}
    fusion_targets: set = set()

    def walk(comp: str, mult: float) -> None:
        comp_mult[comp] = comp_mult.get(comp, 0.0) + mult
        for op in computations.get(comp, []):
            for kind, target in _op_calls(op):
                if target not in computations:
                    continue
                factor = 1.0
                if kind in ("body", "condition"):
                    cond = None
                    for k, t in _op_calls(op):
                        if k == "condition":
                            cond = t
                    trips = trip_of(cond) if cond else None
                    if trips is None:
                        if kind == "body":      # count each loop once
                            analysis.unknown_trip_loops += 1
                        trips = 1.0
                    else:
                        analysis.trip_counts[op.name] = trips
                    factor = trips
                elif kind in ("calls", "to_apply"):
                    fusion_targets.add(target)
                walk(target, mult * factor)

    if entry:
        walk(entry, 1.0)

    ops: List[HloOp] = []
    for comp, comp_ops in computations.items():
        mult = comp_mult.get(comp, 1.0)
        internal = comp in fusion_targets
        for op in comp_ops:
            op.multiplier = mult
            op.fusion_internal = internal
            ops.append(op)
    analysis.ops = ops
    return analysis


def _sched_distance(op: HloOp, comp_ops: List[HloOp]) -> Optional[int]:
    """Compute ops between an async '-start' and its '-done' in the
    computation's printed instruction order (see
    :attr:`CollectiveOp.sched_distance`)."""
    try:
        i = comp_ops.index(op)
    except ValueError:
        return None
    for j in range(i + 1, len(comp_ops)):
        other = comp_ops[j]
        if op.name in other.operand_names and \
                other.opcode.endswith("-done"):
            return sum(
                1 for k in range(i + 1, j)
                if comp_ops[k].flops > 0
                or comp_ops[k].opcode in ("fusion", "dot", "convolution",
                                          "custom-call"))
    return None


def collective_inventory(analysis: ModuleAnalysis,
                         default_group: int = 1) -> List[CollectiveOp]:
    """Structured per-op collective inventory from a parsed module:
    kind, dtype(s), payload bytes (variadic-aggregated; '-start' forms
    results-only), replica-group size, named-scope attribution,
    forward/backward direction, the loop-aware execution multiplier, and
    — for async start/done pairs — the scheduling distance (intervening
    compute ops), so overlap is visible in the static report, not just
    wall clock. Degenerate 1-device groups are dropped (they move
    nothing), matching :func:`parse_collectives`."""
    out = []
    for op in analysis.ops:
        base = op.opcode[:-6] if op.opcode.endswith("-start") else op.opcode
        if base not in COLLECTIVE_KINDS:
            continue
        is_async = op.opcode.endswith("-start")
        # reconstruct the result-type string for the shared payload rule
        rtype = ", ".join(
            f"{dt}[{','.join(str(d) for d in dims)}]"
            for dt, dims in op.result_shapes)
        payload = _shape_bytes(rtype, kind=base, is_start=is_async)
        group = _group_size(op.attrs, default_group)
        if group <= 1:
            continue
        shapes = op.result_shapes
        if is_async:
            # mirror _shape_bytes' result-half selection for dtype listing
            shapes = [s for s in shapes
                      if not (s[0] in ("u32", "s32") and not s[1])]
            if base in ("all-gather", "collective-permute") \
                    and len(shapes) >= 2 and len(shapes) % 2 == 0:
                shapes = shapes[len(shapes) // 2:]
        out.append(CollectiveOp(
            kind=base, name=op.name, computation=op.computation,
            dtypes=sorted({dt for dt, _ in shapes}),
            payload_bytes=int(payload), group_size=group,
            variadic=len(shapes), is_async=is_async, scope=op.scope,
            backward=op.backward, multiplier=op.multiplier,
            wire_bytes=_ring_wire_bytes(base, payload, group),
            sched_distance=(_sched_distance(
                op, analysis.computations.get(op.computation, []))
                if is_async else None)))
    return out
