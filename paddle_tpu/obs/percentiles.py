"""Serving SLO percentile aggregation (ISSUE 11 satellite): p50/p95/p99
TTFT/TPOT and goodput-under-deadline over ``kind="request"`` telemetry
records, so scheduling policies are comparable as NUMBERS, not logs —
the SJF-vs-FCFS differential in the bench fleet gate is exactly two of
these summaries diffed.

Definitions (the serving-SLO vocabulary the scheduler emits):

- **TTFT / TPOT / wall percentiles** are computed over TERMINAL records
  only — ``finish_reason="retried"`` rows are resubmission lineage, not
  outcomes (counting them would double-weight every request a replica
  death touched).
- **Goodput-under-deadline**: of the deadline-carrying terminal
  requests, the fraction that actually FINISHED (``"length"``/``"eos"``)
  within their deadline. Timed-out, shed, and late completions all count
  against it — goodput is the number that keeps overload honest, because
  raw throughput still looks fine while every request misses its SLO.
- Percentiles use the **nearest-rank** method (no interpolation):
  deterministic, exact on small CI-sized samples, and p99 of N<100
  requests degrades to the max rather than inventing a value.
"""

from __future__ import annotations

import collections
import math
from typing import Any, Dict, Iterable, List, Optional

__all__ = ["percentile", "P2Quantile", "summarize_requests",
           "summarize_scale", "summarize_handoffs", "GOODPUT_REASONS"]

# finish reasons that count as useful completed work
GOODPUT_REASONS = ("length", "eos")


def percentile(values: Iterable[Optional[float]],
               p: float) -> Optional[float]:
    """Nearest-rank percentile of the non-None values (None if empty):
    the smallest value with at least ``p``% of the sample at or below
    it. ``p=50`` of [1,2,3,4] is 2; ``p=99`` of any sample <= 100 items
    is the max."""
    vals = sorted(v for v in values if v is not None)
    if not vals:
        return None
    k = max(1, int(math.ceil(p / 100.0 * len(vals))))
    return vals[min(k, len(vals)) - 1]


class P2Quantile:
    """Streaming quantile estimate — the P² algorithm (Jain & Chlamtac,
    CACM 1985): five markers tracking (min, p/2, p, (1+p)/2, max) whose
    heights are adjusted with a piecewise-parabolic fit as observations
    arrive. O(1) memory and O(1) per observation, which is the point
    (ISSUE 17): :func:`summarize_requests` is batch-only — it needs
    every record in hand — while a live SLO monitor must answer "what
    is p99 TTFT *right now*" over an unbounded stream. P² over a
    reservoir sample because the estimate is deterministic for a given
    stream (SimClock drills stay reproducible) and never holds more
    than five floats.

    Below five observations the estimate is EXACT: the raw values are
    kept and :meth:`value` answers with the same nearest-rank rule as
    :func:`percentile` (p99 of a 3-sample stream is the max, not an
    extrapolation).
    """

    def __init__(self, p: float):
        if not 0.0 < p < 100.0:
            raise ValueError(f"p must be in (0, 100), got {p}")
        self.p = float(p) / 100.0
        self.n = 0
        self._q: List[float] = []          # marker heights
        self._pos: List[float] = []        # actual marker positions
        self._want: List[float] = []       # desired marker positions
        self._dpos: List[float] = []       # desired-position increments

    def observe(self, x: float) -> None:
        x = float(x)
        self.n += 1
        if self.n <= 5:
            self._q.append(x)
            self._q.sort()
            if self.n == 5:
                p = self.p
                self._pos = [1.0, 2.0, 3.0, 4.0, 5.0]
                self._want = [1.0, 1.0 + 2.0 * p, 1.0 + 4.0 * p,
                              3.0 + 2.0 * p, 5.0]
                self._dpos = [0.0, p / 2.0, p, (1.0 + p) / 2.0, 1.0]
            return
        q, pos = self._q, self._pos
        # locate the cell; clamp the extremes to the observation
        if x < q[0]:
            q[0] = x
            k = 0
        elif x >= q[4]:
            q[4] = x
            k = 3
        else:
            k = next(i for i in range(4) if q[i] <= x < q[i + 1])
        for i in range(k + 1, 5):
            pos[i] += 1.0
        for i in range(5):
            self._want[i] += self._dpos[i]
        # adjust the three interior markers toward their desired
        # positions — parabolic when the fit stays monotone, linear
        # otherwise (the P² fallback rule)
        for i in (1, 2, 3):
            d = self._want[i] - pos[i]
            if ((d >= 1.0 and pos[i + 1] - pos[i] > 1.0)
                    or (d <= -1.0 and pos[i - 1] - pos[i] < -1.0)):
                d = 1.0 if d >= 1.0 else -1.0
                qn = q[i] + d / (pos[i + 1] - pos[i - 1]) * (
                    (pos[i] - pos[i - 1] + d) * (q[i + 1] - q[i])
                    / (pos[i + 1] - pos[i])
                    + (pos[i + 1] - pos[i] - d) * (q[i] - q[i - 1])
                    / (pos[i] - pos[i - 1]))
                if not q[i - 1] < qn < q[i + 1]:
                    j = i + int(d)
                    qn = q[i] + d * (q[j] - q[i]) / (pos[j] - pos[i])
                q[i] = qn
                pos[i] += d

    def value(self) -> Optional[float]:
        """The current estimate (None before any observation)."""
        if self.n == 0:
            return None
        if self.n < 5:
            return percentile(self._q, self.p * 100.0)
        return self._q[2]


def summarize_requests(records: List[Dict[str, Any]]
                       ) -> Optional[Dict[str, Any]]:
    """Aggregate ``kind="request"`` records into the SLO summary dict
    (None when the stream has no request records — training-only runs
    don't grow a serving block). See module docstring for semantics."""
    reqs = [r for r in records if r.get("kind") == "request"]
    if not reqs:
        return None
    terminal = [r for r in reqs if r.get("finish_reason") != "retried"]
    out: Dict[str, Any] = {
        "requests": len(terminal),
        "retried_attempts": len(reqs) - len(terminal),
        "finish_reasons": dict(collections.Counter(
            r.get("finish_reason") or "?" for r in terminal)),
        "new_tokens_total": sum(r.get("new_tokens") or 0
                                for r in terminal),
    }
    # latency percentiles exclude shed records: a shed does no work and
    # records wall_ms=0 by construction, so counting it would make p50
    # wall IMPROVE exactly when overload is worst. Timeouts stay in —
    # their latency was genuinely experienced.
    latency = [r for r in terminal if r.get("finish_reason") != "shed"]
    for key in ("ttft_ms", "tpot_ms", "wall_ms"):
        vals = [r.get(key) for r in latency]
        for p in (50, 95, 99):
            v = percentile(vals, p)
            out[f"{key}_p{p}"] = round(v, 4) if v is not None else None
    # serving-throughput aggregates (ISSUE 12): how much of the paged
    # pool the prefix cache deduplicated, how often COW actually forked,
    # and what fraction of speculative drafts the target model accepted
    hit = sum(r.get("prefix_hit_blocks") or 0 for r in terminal)
    reserved = sum(r.get("blocks_reserved") or 0 for r in terminal)
    out["prefix_hit_blocks"] = hit
    out["block_sharing_ratio"] = (round(hit / reserved, 4)
                                  if reserved else None)
    out["cow_forks"] = sum(r.get("cow_forks") or 0 for r in terminal)
    out["prefill_chunks"] = sum(r.get("prefill_chunks") or 0
                                for r in terminal)
    # weighted by draft volume, not a mean of per-request ratios — a
    # 2-draft request must not average equally with a 500-draft one
    proposed = sum(r.get("draft_proposed") or 0 for r in terminal)
    accepted = sum(r.get("draft_accepted") or 0 for r in terminal)
    out["draft_accept_rate"] = (round(accepted / proposed, 4)
                                if proposed else None)
    # retention + KV-capacity accounting (ISSUE 14): retained-LRU hits
    # ride the decode_tick stream as per-tick deltas; the hit RATE is
    # retained hits over all adopted prefix blocks (what fraction of
    # sharing came from blocks no live sequence held). kv_bytes_per_token
    # and quant_dtype are gauges — the last tick's value wins.
    ticks = [r for r in records if r.get("kind") == "decode_tick"]
    if ticks:
        rh = sum(r.get("retained_hits") or 0 for r in ticks)
        out["retained_hits"] = rh
        # rate over the SAME stream's adoption deltas (tick records
        # carry prefix_hit_blocks deltas too) — a terminal-record
        # denominator would mix attempt populations and could exceed 1
        tick_hits = sum(r.get("prefix_hit_blocks") or 0 for r in ticks)
        out["retention_hit_rate"] = (round(rh / tick_hits, 4)
                                     if tick_hits else None)
        out["retained_blocks"] = next(
            (r.get("retained_blocks") for r in reversed(ticks)
             if r.get("retained_blocks") is not None), None)
        out["kv_bytes_per_token"] = next(
            (r.get("kv_bytes_per_token") for r in reversed(ticks)
             if r.get("kv_bytes_per_token") is not None), None)
        out["quant_dtype"] = next(
            (r.get("quant_dtype") for r in reversed(ticks)
             if r.get("quant_dtype") is not None), None)
        # tensor-parallel mesh width (ISSUE 15): a gauge like the rest —
        # with tp > 1 the kv_bytes_per_token above is PER SHARD
        out["tp_degree"] = next(
            (r.get("tp_degree") for r in reversed(ticks)
             if r.get("tp_degree") is not None), None)
    dl = [r for r in terminal if r.get("deadline_s") is not None]
    met = [r for r in dl
           if r.get("finish_reason") in GOODPUT_REASONS
           and r.get("wall_ms") is not None
           and r["wall_ms"] <= r["deadline_s"] * 1e3]
    out["deadline_requests"] = len(dl)
    out["deadline_met"] = len(met)
    out["goodput_pct"] = (round(100.0 * len(met) / len(dl), 2)
                          if dl else None)
    out["goodput_tokens"] = sum(r.get("new_tokens") or 0 for r in met)
    out["shed"] = out["finish_reasons"].get("shed", 0)
    out["timeout"] = out["finish_reasons"].get("timeout", 0)
    return out


def summarize_scale(records: List[Dict[str, Any]]
                    ) -> Optional[Dict[str, Any]]:
    """Aggregate the autoscaler's ``kind="scale"`` events (ISSUE 13
    satellite): how often capacity moved, which way, why, and where it
    ended up — so an elastic run is judgeable as numbers next to its
    latency percentiles. None when the stream has no scale events
    (fixed-capacity fleets don't grow the block)."""
    evs = [r for r in records if r.get("kind") == "scale"]
    if not evs:
        return None
    actions = collections.Counter(r.get("action") or "?" for r in evs)
    return {
        "events": len(evs),
        "up": actions.get("up", 0),
        "down": actions.get("down", 0),
        "replace": actions.get("replace", 0),
        "reasons": dict(collections.Counter(
            r.get("reason") or "?" for r in evs)),
        "final_replicas": evs[-1].get("replicas_after"),
        "max_replicas_seen": max((r.get("replicas_after") or 0)
                                 for r in evs),
    }


def summarize_handoffs(records: List[Dict[str, Any]]
                       ) -> Optional[Dict[str, Any]]:
    """Aggregate the fleet's ``kind="kv_handoff"`` events (ISSUE 18):
    how many finished prefills streamed their KV pages to a decode
    replica, how much hit the wire, and at which quantization — the
    disaggregation run's cost ledger next to its latency percentiles.
    None when the stream has no handoffs (colocated fleets don't grow
    the block)."""
    evs = [r for r in records if r.get("kind") == "kv_handoff"]
    if not evs:
        return None
    wire = [int(r.get("wire_bytes") or 0) for r in evs]
    blocks = [int(r.get("blocks") or 0) for r in evs]
    ms = [float(r["transfer_ms"]) for r in evs
          if r.get("transfer_ms") is not None]
    return {
        "handoffs": len(evs),
        "blocks": sum(blocks),
        "wire_bytes": sum(wire),
        "mean_blocks": round(sum(blocks) / len(evs), 2),
        "mean_wire_bytes": round(sum(wire) / len(evs), 1),
        "transfer_ms_mean": (round(sum(ms) / len(ms), 3)
                             if ms else None),
        "transfer_ms_p95": percentile(ms, 95) if ms else None,
        "by_quant": dict(collections.Counter(
            r.get("quant") or "?" for r in evs)),
    }
