"""Anomaly detection + flight recorder for the training hot loop
(ISSUE 4 tentpole, part 2).

The telemetry stream (PR 2) and the trace ring (:mod:`.trace`) answer
questions a human asks *while watching*. Production runs misbehave at
step 40k with nobody watching — by the time someone looks, the JSONL has
scrolled past the interesting window and the trace ring has been
overwritten. This module watches the stream mechanically and, the moment
a run goes sideways, freezes the evidence to disk.

**Detector** (:meth:`AnomalyDetector.observe`, fed every telemetry step
record by the Trainer): rolling *robust* statistics — median + MAD, not
mean + stddev, because the contaminated samples the detector exists to
catch would drag a mean-based threshold toward themselves — over the
recent window, checking five conditions:

- ``nonfinite``   — the NaN/Inf health sentinel tripped (or the loss was
  NaN-sanitized to None): the run is poisoned *now*; later records only
  get worse.
- ``retrace_burst`` — ``retrace_count`` climbed ≥ N inside the window:
  something is feeding a stream of fresh shapes and every step pays a
  recompile.
- ``drain_stall``  — a pipelined drain blocked longer than the absolute
  threshold AND 3x the rolling drain median: in the healthy device-bound
  steady state every drain legitimately blocks for ~one group's device
  time, so only a wait that is also an outlier vs the run's own drain
  baseline counts as a stall (the window emptied and the host sat on a
  dead pipeline).
- ``memory_high_water`` — ``bytes_in_use`` crossed a fraction of the
  device's ``bytes_limit``: the step after this one may be the OOM.
- ``slow_step``    — the call's host wall is a ≥ ``slow_step_zscore``
  robust-z outlier vs the window median (warmup-gated): preemption,
  host interference, a competing process.

**Flight recorder** (on trigger): one forensics bundle directory —
``verdict.json`` (what fired, on which record), ``telemetry_ring.jsonl``
(the last ``ring_size`` step records), ``trace_tail.json`` (the recent
span window as a Chrome trace, when a :class:`~.trace.Tracer` is bound),
and ``snapshot.json`` (trainer config, mesh, devices, jax version,
JAX_*/XLA_* env) — everything a postmortem needs, written once, cheap
enough to leave armed in production. Optionally (``arm_profiler=True``)
the next fused call is wrapped in a ``jax.profiler.trace`` capture into
the bundle, so the device-side view of the anomaly's neighborhood is
kept too.

Each detector kind fires **once** per run by default (``rearm=False``):
the first bundle holds the onset — the interesting record — and a
misbehaving run must not bury the disk in bundles. Detection is
observation, not control: training continues (the hard stops remain
``Trainer(nan_check=True)``), and a detector crash is logged, never
raised into the hot loop.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

__all__ = ["AnomalyDetector", "Verdict", "ANOMALY_KINDS"]

_log = logging.getLogger("paddle_tpu.anomaly")

ANOMALY_KINDS = ("nonfinite", "retrace_burst", "drain_stall",
                 "memory_high_water", "slow_step")

# Step-record keys whose sum approximates the call's host-observable wall.
# Stager-staged records (stage_ms present — the fused pipeline): dispatch +
# drain wait ONLY, because there host_stack_ms/shard_ms were measured on
# the STAGER thread (already-hidden cost, trainer.py's semantic-shift
# note) and counting them would flag hidden staging spikes as slow steps.
# Everything else — serial records AND the plain deferred-fetch loop,
# whose shard_ms is genuine main-thread critical path — counts the full
# host-side breakdown (absent keys are simply skipped: device_ms is None
# unfenced, drain_wait_ms is None serial).
_WALL_KEYS_STAGED = ("dispatch_ms", "drain_wait_ms")
_WALL_KEYS_MAIN = ("host_stack_ms", "shard_ms", "dispatch_ms",
                   "device_ms", "drain_wait_ms")


@dataclasses.dataclass
class Verdict:
    """One detector trigger: what fired, the observed value vs threshold,
    and (after the dump) where the forensics bundle landed."""
    kind: str
    step: Optional[int]
    value: Optional[float]
    threshold: Optional[float]
    detail: str
    bundle: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _robust_z(x: float, history) -> tuple:
    """Robust z-score of ``x`` against ``history``: (x - median) / (1.4826
    * MAD), with a 5%-of-median floor on the scale so a constant history
    (MAD = 0) cannot make every later sample an infinite outlier."""
    arr = np.asarray(history, np.float64)
    med = float(np.median(arr))
    mad = float(np.median(np.abs(arr - med)))
    scale = 1.4826 * mad
    floor = max(abs(med), 1e-6) * 0.05
    scale = max(scale, floor)
    return (x - med) / scale, med


class AnomalyDetector:
    """Rolling anomaly detector + one-shot flight recorder.

    Args:
      out_dir: where forensics bundles land (``anomaly_NNN_<kind>/``
        subdirectories; created on first trigger, so arming the detector
        never touches the filesystem).
      window: rolling-statistics window (records).
      warmup: minimum history before ``slow_step`` may fire (the first
        call carries compile time; early medians are noise).
      slow_step_zscore: robust-z threshold for ``slow_step`` (the value
        must also exceed 1.5x the window median — belt and braces against
        a tiny scale floor).
      retrace_burst: ``retrace_count`` increase within the window that
        flags a burst.
      drain_stall_ms: ``drain_wait_ms`` floor for a stall; the wait must
        ALSO exceed 3x the rolling drain median (≥3 prior drains), so a
        device-bound run whose every drain is a legitimate
        group-compute-time wait never trips it.
      memory_frac: fraction of the device ``bytes_limit`` that counts as
        high water.
      memory_bytes_limit: override the device-reported limit (None =
        sample ``device_memory_stats()['bytes_limit']`` lazily; backends
        reporting none — CPU — disable the memory check).
      ring_size: telemetry records kept for the bundle.
      trace_tail: trace events snapshotted into the bundle.
      arm_profiler: on trigger, arm a ``jax.profiler.trace`` capture for
        the next fused call (written under the bundle).
      rearm: allow a kind to fire more than once (default: first onset
        only).
    """

    def __init__(self, out_dir: str, window: int = 64, warmup: int = 8,
                 slow_step_zscore: float = 8.0, retrace_burst: int = 3,
                 drain_stall_ms: float = 5000.0,
                 memory_frac: float = 0.92,
                 memory_bytes_limit: Optional[int] = None,
                 ring_size: int = 256, trace_tail: int = 500,
                 arm_profiler: bool = False, rearm: bool = False):
        self.out_dir = out_dir
        self.window = int(window)
        self.warmup = int(warmup)
        self.slow_step_zscore = float(slow_step_zscore)
        self.retrace_burst = int(retrace_burst)
        self.drain_stall_ms = float(drain_stall_ms)
        self.memory_frac = float(memory_frac)
        self._mem_limit = memory_bytes_limit
        self.trace_tail = int(trace_tail)
        self.arm_profiler = bool(arm_profiler)
        self.rearm = bool(rearm)
        self._ring: collections.deque = collections.deque(
            maxlen=int(ring_size))
        self._walls: collections.deque = collections.deque(maxlen=window)
        self._drains: collections.deque = collections.deque(maxlen=window)
        self._retraces: collections.deque = collections.deque(maxlen=window)
        self._fired: set = set()
        self._tracer = None
        self._context_fn: Optional[Callable[[], Dict[str, Any]]] = None
        self._profiler_request: Optional[str] = None
        self.bundles: List[str] = []
        self.verdicts: List[Verdict] = []

    # -- wiring --------------------------------------------------------------

    def bind(self, tracer=None,
             context_fn: Optional[Callable[[], Dict[str, Any]]] = None
             ) -> None:
        """Attach the trace ring and the config/env snapshot source (the
        Trainer calls this at ``train()`` start)."""
        if tracer is not None:
            self._tracer = tracer
        if context_fn is not None:
            self._context_fn = context_fn

    def take_profiler_request(self) -> Optional[str]:
        """Pop the armed profiler-capture directory (None when unarmed).
        The Trainer polls this before each fused dispatch."""
        req, self._profiler_request = self._profiler_request, None
        return req

    def reset(self) -> None:
        """Re-arm every kind and clear the rolling state (bundles stay)."""
        self._fired.clear()
        self._ring.clear()
        self._walls.clear()
        self._drains.clear()
        self._retraces.clear()
        self._profiler_request = None

    # -- detection -----------------------------------------------------------

    def observe(self, rec: Dict[str, Any]) -> List[Verdict]:
        """Feed one telemetry step record; returns the verdicts triggered
        by it (usually empty). Never raises on malformed records — the
        detector must not be the thing that kills the run it watches."""
        if rec.get("kind", "step") != "step":
            return []
        self._ring.append(dict(rec))
        verdicts = []
        for check in (self._check_nonfinite, self._check_retrace_burst,
                      self._check_drain_stall, self._check_memory,
                      self._check_slow_step):
            try:
                v = check(rec)
            except Exception:
                _log.exception("anomaly check %s failed on record",
                               check.__name__)
                continue
            if v is not None and (self.rearm or v.kind not in self._fired):
                self._fired.add(v.kind)
                self._trigger(v, rec)
                verdicts.append(v)
        self._update_rolling(rec)
        return verdicts

    def _update_rolling(self, rec: Dict[str, Any]) -> None:
        wall = self._wall_ms(rec)
        if wall is not None:
            self._walls.append(wall)
        dw = rec.get("drain_wait_ms")
        if dw is not None and not rec.get("profiled"):
            self._drains.append(float(dw))
        rc = rec.get("retrace_count")
        if rc is not None:
            self._retraces.append(int(rc))

    @staticmethod
    def _wall_ms(rec: Dict[str, Any]) -> Optional[float]:
        if rec.get("profiled"):
            # anomaly-armed jax.profiler capture: its dispatch window
            # deliberately contains a compute fence — the flight recorder
            # must not trigger the detector that armed it
            return None
        keys = (_WALL_KEYS_STAGED if rec.get("stage_ms") is not None
                else _WALL_KEYS_MAIN)
        vals = [rec.get(k) for k in keys]
        vals = [v for v in vals if v is not None]
        return float(sum(vals)) if vals else None

    def _check_nonfinite(self, rec) -> Optional[Verdict]:
        bad = rec.get("nonfinite_count") or 0
        loss_nan = "loss" in rec and rec["loss"] is None
        if bad > 0 or loss_nan:
            return Verdict(
                kind="nonfinite", step=rec.get("step"),
                value=float(bad), threshold=0.0,
                detail=(f"NaN/Inf sentinel tripped: nonfinite_count={bad}"
                        + (", loss was NaN-sanitized" if loss_nan else "")))
        return None

    def _check_retrace_burst(self, rec) -> Optional[Verdict]:
        rc = rec.get("retrace_count")
        if rc is None or not self._retraces:
            return None
        rise = int(rc) - self._retraces[0]
        if rise >= self.retrace_burst:
            return Verdict(
                kind="retrace_burst", step=rec.get("step"),
                value=float(rise), threshold=float(self.retrace_burst),
                detail=(f"retrace_count rose by {rise} within the last "
                        f"{len(self._retraces)} records — a stream of "
                        f"fresh shapes is recompiling every step "
                        f"(ragged batches? dynamic lengths?)"))
        return None

    def _check_drain_stall(self, rec) -> Optional[Verdict]:
        dw = rec.get("drain_wait_ms")
        if dw is None or dw <= self.drain_stall_ms:
            return None
        # In the device-bound steady state EVERY drain legitimately blocks
        # for ~one group's device time, so an absolute wall alone would
        # flag healthy big-group runs. Require a baseline (>=3 prior
        # drains) and a 3x-median excess on top of the absolute floor.
        if len(self._drains) < 3:
            return None
        med = float(np.median(self._drains))
        if dw <= 3.0 * max(med, 1e-9):
            return None
        return Verdict(
            kind="drain_stall", step=rec.get("step"), value=float(dw),
            threshold=round(max(self.drain_stall_ms, 3.0 * med), 3),
            detail=(f"pipelined drain blocked {dw:.0f} ms fetching the "
                    f"call's losses ({dw / max(med, 1e-9):.1f}x the run's "
                    f"median drain of {med:.0f} ms) — the in-flight window "
                    f"ran dry (device stall? preempted neighbor?)"))

    def _check_memory(self, rec) -> Optional[Verdict]:
        cur = rec.get("bytes_in_use")
        if cur is None:
            return None
        if self._mem_limit is None:
            from .telemetry import device_memory_stats
            self._mem_limit = device_memory_stats().get("bytes_limit", 0)
        if not self._mem_limit:
            return None
        frac = cur / self._mem_limit
        if frac >= self.memory_frac:
            return Verdict(
                kind="memory_high_water", step=rec.get("step"),
                value=round(frac, 4), threshold=self.memory_frac,
                detail=(f"device memory at {100 * frac:.1f}% of "
                        f"bytes_limit ({cur} / {self._mem_limit}) — the "
                        f"next allocation spike may OOM"))
        return None

    def _check_slow_step(self, rec) -> Optional[Verdict]:
        wall = self._wall_ms(rec)
        if wall is None or len(self._walls) < self.warmup:
            return None
        z, med = _robust_z(wall, self._walls)
        if z >= self.slow_step_zscore and wall > 1.5 * med:
            return Verdict(
                kind="slow_step", step=rec.get("step"), value=round(wall, 3),
                threshold=round(self.slow_step_zscore, 2),
                detail=(f"call wall {wall:.1f} ms is a {z:.1f}-sigma robust "
                        f"outlier vs the window median {med:.1f} ms "
                        f"(preemption / host interference / IO stall?)"))
        return None

    # -- flight recorder -----------------------------------------------------

    def _trigger(self, verdict: Verdict, rec: Dict[str, Any]) -> None:
        try:
            self._dump_bundle(verdict, rec)
        except Exception:
            _log.exception("forensics-bundle dump failed for %s",
                           verdict.kind)
        self.verdicts.append(verdict)
        if self._tracer is not None:
            try:
                self._tracer.instant(f"ANOMALY:{verdict.kind}",
                                     step=verdict.step, value=verdict.value)
            except Exception:
                pass
        if self.arm_profiler and verdict.bundle:
            self._profiler_request = os.path.join(verdict.bundle,
                                                  "jax_profile")
        _log.warning("ANOMALY %s at step %s: %s%s", verdict.kind,
                     verdict.step, verdict.detail,
                     f" — forensics bundle: {verdict.bundle}"
                     if verdict.bundle else "")

    def _dump_bundle(self, verdict: Verdict, rec: Dict[str, Any]) -> str:
        seq = len(self.bundles)
        bundle = os.path.join(self.out_dir,
                              f"anomaly_{seq:03d}_{verdict.kind}")
        os.makedirs(bundle, exist_ok=True)
        verdict.bundle = bundle
        with open(os.path.join(bundle, "verdict.json"), "w") as f:
            json.dump({"ts": time.time(), "verdict": verdict.to_dict(),
                       "trigger_record": rec}, f, indent=2, default=str)
        with open(os.path.join(bundle, "telemetry_ring.jsonl"), "w") as f:
            for r in self._ring:
                f.write(json.dumps(r, default=str) + "\n")
        snapshot: Dict[str, Any] = {}
        if self._context_fn is not None:
            try:
                snapshot = self._context_fn()
            except Exception as e:
                snapshot = {"error": f"{type(e).__name__}: {e}"}
        with open(os.path.join(bundle, "snapshot.json"), "w") as f:
            json.dump(snapshot, f, indent=2, default=str)
        if self._tracer is not None:
            try:
                with open(os.path.join(bundle, "trace_tail.json"), "w") as f:
                    json.dump(self._tracer.chrome_trace(
                        self._tracer.tail(self.trace_tail)), f)
            except Exception:
                _log.exception("trace-tail dump failed")
        self.bundles.append(bundle)
        return bundle
