"""Anomaly detection + flight recorder for the training hot loop
(ISSUE 4 tentpole, part 2).

The telemetry stream (PR 2) and the trace ring (:mod:`.trace`) answer
questions a human asks *while watching*. Production runs misbehave at
step 40k with nobody watching — by the time someone looks, the JSONL has
scrolled past the interesting window and the trace ring has been
overwritten. This module watches the stream mechanically and, the moment
a run goes sideways, freezes the evidence to disk.

**Detector** (:meth:`AnomalyDetector.observe`, fed every telemetry step
record by the Trainer): rolling *robust* statistics — median + MAD, not
mean + stddev, because the contaminated samples the detector exists to
catch would drag a mean-based threshold toward themselves — over the
recent window, checking five conditions:

- ``nonfinite``   — the NaN/Inf health sentinel tripped (or the loss was
  NaN-sanitized to None): the run is poisoned *now*; later records only
  get worse.
- ``retrace_burst`` — ``retrace_count`` climbed ≥ N inside the window:
  something is feeding a stream of fresh shapes and every step pays a
  recompile.
- ``drain_stall``  — a pipelined drain blocked longer than the absolute
  threshold AND 3x the rolling drain median: in the healthy device-bound
  steady state every drain legitimately blocks for ~one group's device
  time, so only a wait that is also an outlier vs the run's own drain
  baseline counts as a stall (the window emptied and the host sat on a
  dead pipeline).
- ``memory_high_water`` — ``bytes_in_use`` crossed a fraction of the
  device's ``bytes_limit``: the step after this one may be the OOM.
- ``slow_step``    — the call's host wall is a ≥ ``slow_step_zscore``
  robust-z outlier vs the window median (warmup-gated): preemption,
  host interference, a competing process.

**Flight recorder** (on trigger): one forensics bundle directory —
``verdict.json`` (what fired, on which record), ``telemetry_ring.jsonl``
(the last ``ring_size`` step records), ``trace_tail.json`` (the recent
span window as a Chrome trace, when a :class:`~.trace.Tracer` is bound),
and ``snapshot.json`` (trainer config, mesh, devices, jax version,
JAX_*/XLA_* env) — everything a postmortem needs, written once, cheap
enough to leave armed in production. Optionally (``arm_profiler=True``)
the next fused call is wrapped in a ``jax.profiler.trace`` capture into
the bundle, so the device-side view of the anomaly's neighborhood is
kept too.

Each detector kind fires **once** per run by default (``rearm=False``):
the first bundle holds the onset — the interesting record — and a
misbehaving run must not bury the disk in bundles. Detection is
observation, not control: training continues (the hard stops remain
``Trainer(nan_check=True)``), and a detector crash is logged, never
raised into the hot loop.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

__all__ = ["AnomalyDetector", "ServingAnomalyDetector", "Verdict",
           "ANOMALY_KINDS", "SERVING_ANOMALY_KINDS"]

_log = logging.getLogger("paddle_tpu.anomaly")

ANOMALY_KINDS = ("nonfinite", "retrace_burst", "drain_stall",
                 "memory_high_water", "slow_step")

SERVING_ANOMALY_KINDS = ("tick_stall", "accept_collapse",
                         "prefix_hit_collapse", "retransmit_burst",
                         "queue_divergence")

# Step-record keys whose sum approximates the call's host-observable wall.
# Stager-staged records (stage_ms present — the fused pipeline): dispatch +
# drain wait ONLY, because there host_stack_ms/shard_ms were measured on
# the STAGER thread (already-hidden cost, trainer.py's semantic-shift
# note) and counting them would flag hidden staging spikes as slow steps.
# Everything else — serial records AND the plain deferred-fetch loop,
# whose shard_ms is genuine main-thread critical path — counts the full
# host-side breakdown (absent keys are simply skipped: device_ms is None
# unfenced, drain_wait_ms is None serial).
_WALL_KEYS_STAGED = ("dispatch_ms", "drain_wait_ms")
_WALL_KEYS_MAIN = ("host_stack_ms", "shard_ms", "dispatch_ms",
                   "device_ms", "drain_wait_ms")


@dataclasses.dataclass
class Verdict:
    """One detector trigger: what fired, the observed value vs threshold,
    and (after the dump) where the forensics bundle landed."""
    kind: str
    step: Optional[int]
    value: Optional[float]
    threshold: Optional[float]
    detail: str
    bundle: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _robust_z(x: float, history) -> tuple:
    """Robust z-score of ``x`` against ``history``: (x - median) / (1.4826
    * MAD), with a 5%-of-median floor on the scale so a constant history
    (MAD = 0) cannot make every later sample an infinite outlier."""
    arr = np.asarray(history, np.float64)
    med = float(np.median(arr))
    mad = float(np.median(np.abs(arr - med)))
    scale = 1.4826 * mad
    floor = max(abs(med), 1e-6) * 0.05
    scale = max(scale, floor)
    return (x - med) / scale, med


class AnomalyDetector:
    """Rolling anomaly detector + one-shot flight recorder.

    Args:
      out_dir: where forensics bundles land (``anomaly_NNN_<kind>/``
        subdirectories; created on first trigger, so arming the detector
        never touches the filesystem).
      window: rolling-statistics window (records).
      warmup: minimum history before ``slow_step`` may fire (the first
        call carries compile time; early medians are noise).
      slow_step_zscore: robust-z threshold for ``slow_step`` (the value
        must also exceed 1.5x the window median — belt and braces against
        a tiny scale floor).
      retrace_burst: ``retrace_count`` increase within the window that
        flags a burst.
      drain_stall_ms: ``drain_wait_ms`` floor for a stall; the wait must
        ALSO exceed 3x the rolling drain median (≥3 prior drains), so a
        device-bound run whose every drain is a legitimate
        group-compute-time wait never trips it.
      memory_frac: fraction of the device ``bytes_limit`` that counts as
        high water.
      memory_bytes_limit: override the device-reported limit (None =
        sample ``device_memory_stats()['bytes_limit']`` lazily; backends
        reporting none — CPU — disable the memory check).
      ring_size: telemetry records kept for the bundle.
      trace_tail: trace events snapshotted into the bundle.
      arm_profiler: on trigger, arm a ``jax.profiler.trace`` capture for
        the next fused call (written under the bundle).
      rearm: allow a kind to fire more than once (default: first onset
        only).
    """

    def __init__(self, out_dir: str, window: int = 64, warmup: int = 8,
                 slow_step_zscore: float = 8.0, retrace_burst: int = 3,
                 drain_stall_ms: float = 5000.0,
                 memory_frac: float = 0.92,
                 memory_bytes_limit: Optional[int] = None,
                 ring_size: int = 256, trace_tail: int = 500,
                 arm_profiler: bool = False, rearm: bool = False):
        self.out_dir = out_dir
        self.window = int(window)
        self.warmup = int(warmup)
        self.slow_step_zscore = float(slow_step_zscore)
        self.retrace_burst = int(retrace_burst)
        self.drain_stall_ms = float(drain_stall_ms)
        self.memory_frac = float(memory_frac)
        self._mem_limit = memory_bytes_limit
        self.trace_tail = int(trace_tail)
        self.arm_profiler = bool(arm_profiler)
        self.rearm = bool(rearm)
        self._ring: collections.deque = collections.deque(
            maxlen=int(ring_size))
        self._walls: collections.deque = collections.deque(maxlen=window)
        self._drains: collections.deque = collections.deque(maxlen=window)
        self._retraces: collections.deque = collections.deque(maxlen=window)
        self._fired: set = set()
        self._tracer = None
        self._context_fn: Optional[Callable[[], Dict[str, Any]]] = None
        self._profiler_request: Optional[str] = None
        self.bundles: List[str] = []
        self.verdicts: List[Verdict] = []

    # -- wiring --------------------------------------------------------------

    def bind(self, tracer=None,
             context_fn: Optional[Callable[[], Dict[str, Any]]] = None
             ) -> None:
        """Attach the trace ring and the config/env snapshot source (the
        Trainer calls this at ``train()`` start)."""
        if tracer is not None:
            self._tracer = tracer
        if context_fn is not None:
            self._context_fn = context_fn

    def take_profiler_request(self) -> Optional[str]:
        """Pop the armed profiler-capture directory (None when unarmed).
        The Trainer polls this before each fused dispatch."""
        req, self._profiler_request = self._profiler_request, None
        return req

    def reset(self) -> None:
        """Re-arm every kind and clear the rolling state (bundles stay)."""
        self._fired.clear()
        self._ring.clear()
        self._walls.clear()
        self._drains.clear()
        self._retraces.clear()
        self._profiler_request = None

    # -- detection -----------------------------------------------------------

    def observe(self, rec: Dict[str, Any]) -> List[Verdict]:
        """Feed one telemetry step record; returns the verdicts triggered
        by it (usually empty). Never raises on malformed records — the
        detector must not be the thing that kills the run it watches."""
        if rec.get("kind", "step") != "step":
            return []
        self._ring.append(dict(rec))
        verdicts = []
        for check in (self._check_nonfinite, self._check_retrace_burst,
                      self._check_drain_stall, self._check_memory,
                      self._check_slow_step):
            try:
                v = check(rec)
            except Exception:
                _log.exception("anomaly check %s failed on record",
                               check.__name__)
                continue
            if v is not None and (self.rearm or v.kind not in self._fired):
                self._fired.add(v.kind)
                self._trigger(v, rec)
                verdicts.append(v)
        self._update_rolling(rec)
        return verdicts

    def _update_rolling(self, rec: Dict[str, Any]) -> None:
        wall = self._wall_ms(rec)
        if wall is not None:
            self._walls.append(wall)
        dw = rec.get("drain_wait_ms")
        if dw is not None and not rec.get("profiled"):
            self._drains.append(float(dw))
        rc = rec.get("retrace_count")
        if rc is not None:
            self._retraces.append(int(rc))

    @staticmethod
    def _wall_ms(rec: Dict[str, Any]) -> Optional[float]:
        if rec.get("profiled"):
            # anomaly-armed jax.profiler capture: its dispatch window
            # deliberately contains a compute fence — the flight recorder
            # must not trigger the detector that armed it
            return None
        keys = (_WALL_KEYS_STAGED if rec.get("stage_ms") is not None
                else _WALL_KEYS_MAIN)
        vals = [rec.get(k) for k in keys]
        vals = [v for v in vals if v is not None]
        return float(sum(vals)) if vals else None

    def _check_nonfinite(self, rec) -> Optional[Verdict]:
        bad = rec.get("nonfinite_count") or 0
        loss_nan = "loss" in rec and rec["loss"] is None
        if bad > 0 or loss_nan:
            return Verdict(
                kind="nonfinite", step=rec.get("step"),
                value=float(bad), threshold=0.0,
                detail=(f"NaN/Inf sentinel tripped: nonfinite_count={bad}"
                        + (", loss was NaN-sanitized" if loss_nan else "")))
        return None

    def _check_retrace_burst(self, rec) -> Optional[Verdict]:
        rc = rec.get("retrace_count")
        if rc is None or not self._retraces:
            return None
        rise = int(rc) - self._retraces[0]
        if rise >= self.retrace_burst:
            return Verdict(
                kind="retrace_burst", step=rec.get("step"),
                value=float(rise), threshold=float(self.retrace_burst),
                detail=(f"retrace_count rose by {rise} within the last "
                        f"{len(self._retraces)} records — a stream of "
                        f"fresh shapes is recompiling every step "
                        f"(ragged batches? dynamic lengths?)"))
        return None

    def _check_drain_stall(self, rec) -> Optional[Verdict]:
        dw = rec.get("drain_wait_ms")
        if dw is None or dw <= self.drain_stall_ms:
            return None
        # In the device-bound steady state EVERY drain legitimately blocks
        # for ~one group's device time, so an absolute wall alone would
        # flag healthy big-group runs. Require a baseline (>=3 prior
        # drains) and a 3x-median excess on top of the absolute floor.
        if len(self._drains) < 3:
            return None
        med = float(np.median(self._drains))
        if dw <= 3.0 * max(med, 1e-9):
            return None
        return Verdict(
            kind="drain_stall", step=rec.get("step"), value=float(dw),
            threshold=round(max(self.drain_stall_ms, 3.0 * med), 3),
            detail=(f"pipelined drain blocked {dw:.0f} ms fetching the "
                    f"call's losses ({dw / max(med, 1e-9):.1f}x the run's "
                    f"median drain of {med:.0f} ms) — the in-flight window "
                    f"ran dry (device stall? preempted neighbor?)"))

    def _check_memory(self, rec) -> Optional[Verdict]:
        cur = rec.get("bytes_in_use")
        if cur is None:
            return None
        if self._mem_limit is None:
            from .telemetry import device_memory_stats
            self._mem_limit = device_memory_stats().get("bytes_limit", 0)
        if not self._mem_limit:
            return None
        frac = cur / self._mem_limit
        if frac >= self.memory_frac:
            return Verdict(
                kind="memory_high_water", step=rec.get("step"),
                value=round(frac, 4), threshold=self.memory_frac,
                detail=(f"device memory at {100 * frac:.1f}% of "
                        f"bytes_limit ({cur} / {self._mem_limit}) — the "
                        f"next allocation spike may OOM"))
        return None

    def _check_slow_step(self, rec) -> Optional[Verdict]:
        wall = self._wall_ms(rec)
        if wall is None or len(self._walls) < self.warmup:
            return None
        z, med = _robust_z(wall, self._walls)
        if z >= self.slow_step_zscore and wall > 1.5 * med:
            return Verdict(
                kind="slow_step", step=rec.get("step"), value=round(wall, 3),
                threshold=round(self.slow_step_zscore, 2),
                detail=(f"call wall {wall:.1f} ms is a {z:.1f}-sigma robust "
                        f"outlier vs the window median {med:.1f} ms "
                        f"(preemption / host interference / IO stall?)"))
        return None

    # -- flight recorder -----------------------------------------------------

    def _trigger(self, verdict: Verdict, rec: Dict[str, Any]) -> None:
        try:
            self._dump_bundle(verdict, rec)
        except Exception:
            _log.exception("forensics-bundle dump failed for %s",
                           verdict.kind)
        self.verdicts.append(verdict)
        if self._tracer is not None:
            try:
                self._tracer.instant(f"ANOMALY:{verdict.kind}",
                                     step=verdict.step, value=verdict.value)
            except Exception:
                pass
        if self.arm_profiler and verdict.bundle:
            self._profiler_request = os.path.join(verdict.bundle,
                                                  "jax_profile")
        _log.warning("ANOMALY %s at step %s: %s%s", verdict.kind,
                     verdict.step, verdict.detail,
                     f" — forensics bundle: {verdict.bundle}"
                     if verdict.bundle else "")

    def _dump_bundle(self, verdict: Verdict, rec: Dict[str, Any]) -> str:
        seq = len(self.bundles)
        bundle = os.path.join(self.out_dir,
                              f"anomaly_{seq:03d}_{verdict.kind}")
        os.makedirs(bundle, exist_ok=True)
        verdict.bundle = bundle
        with open(os.path.join(bundle, "verdict.json"), "w") as f:
            json.dump({"ts": time.time(), "verdict": verdict.to_dict(),
                       "trigger_record": rec}, f, indent=2, default=str)
        with open(os.path.join(bundle, "telemetry_ring.jsonl"), "w") as f:
            for r in self._ring:
                f.write(json.dumps(r, default=str) + "\n")
        snapshot: Dict[str, Any] = {}
        if self._context_fn is not None:
            try:
                snapshot = self._context_fn()
            except Exception as e:
                snapshot = {"error": f"{type(e).__name__}: {e}"}
        with open(os.path.join(bundle, "snapshot.json"), "w") as f:
            json.dump(snapshot, f, indent=2, default=str)
        if self._tracer is not None:
            try:
                with open(os.path.join(bundle, "trace_tail.json"), "w") as f:
                    json.dump(self._tracer.chrome_trace(
                        self._tracer.tail(self.trace_tail)), f)
            except Exception:
                _log.exception("trace-tail dump failed")
        self.bundles.append(bundle)
        return bundle


class ServingAnomalyDetector(AnomalyDetector):
    """Serving-fleet anomaly detector (ISSUE 17, tentpole part 3): the
    same one-shot/rearm flight-recorder machinery, watching PER-REPLICA
    serving signals instead of training step records. One detector
    serves the whole fleet; each kind fires once per *replica* (the
    one-shot keys are ``"<kind>@r<replica>"``), so replica 1's stall
    is not hidden by replica 0's.

    The fleet feeds three seams, all mode-blind (the same calls work
    for in-process and subprocess replicas, because they consume the
    evidence the fleet already holds — its own tick view, terminal
    request records, transport counters):

    - :meth:`observe_fleet_tick` — per fleet tick, per live replica:

      - ``tick_stall``: the replica has work (running/queued) but its
        engine tick counter has not advanced for ``stall_ticks``
        consecutive fleet ticks — a wedged child, a stalled engine, a
        replica about to be declared dead.
      - ``queue_divergence``: the replica's queue grew monotonically by
        ≥ ``queue_growth`` across the rolling window — arrival rate has
        diverged from service rate (the precursor of mass timeouts).

    - :meth:`observe_serving` — per terminal request record:

      - ``accept_collapse``: speculative draft accept-rate was healthy
        (> 2x ``accept_floor`` at least once) and the last
        ``accept_window`` draft-carrying requests ALL came in at or
        under the floor — the draft model has stopped predicting the
        target (distribution shift, corrupted draft state).
      - ``prefix_hit_collapse``: prefix-cache hits existed earlier but
        the last ``prefix_window`` requests saw none — retention was
        evicted or session affinity broke.

    - :meth:`observe_transport` — per tick, per process replica, fed
      the cumulative transport counters:

      - ``retransmit_burst``: retransmits rose by ≥
        ``retransmit_burst`` within the rolling window — the link to
        that child is flapping.

    On trigger the bundle directory (``anomaly_NNN_<kind>_r<replica>``)
    holds ``verdict.json``, the replica's last-N fleet-tick ring
    (``tick_ring.jsonl``) and terminal-record tail
    (``records_tail.jsonl``), plus whatever fleet-level evidence is
    bound via :meth:`bind_fleet`: transport counters
    (``transport.json``), heartbeat payloads (``heartbeats.json``), and
    the merged fleet trace tail (``fleet_trace_tail.json``)."""

    def __init__(self, out_dir: str, stall_ticks: int = 3,
                 accept_floor: float = 0.2, accept_window: int = 6,
                 prefix_window: int = 6, retransmit_burst: int = 3,
                 queue_growth: int = 6, queue_window: int = 8, **kw):
        super().__init__(out_dir, **kw)
        self.stall_ticks = int(stall_ticks)
        self.accept_floor = float(accept_floor)
        self.accept_window = int(accept_window)
        self.prefix_window = int(prefix_window)
        # base class reuses the name for training retraces; keep ours
        # distinct
        self.retransmit_burst_n = int(retransmit_burst)
        self.queue_growth = int(queue_growth)
        self.queue_window = int(queue_window)
        self._rep: Dict[Any, Dict[str, Any]] = {}
        self._fleet_ctx: Dict[str, Callable[[], Any]] = {}
        self._serving_replica: Optional[int] = None

    # -- wiring --------------------------------------------------------------

    def bind_fleet(self, heartbeats: Optional[Callable[[], Any]] = None,
                   trace_tail: Optional[Callable[[], Any]] = None,
                   transport: Optional[Callable[[], Any]] = None) -> None:
        """Attach fleet-level evidence sources (zero-arg callables,
        each sampled at trigger time; a failing source is recorded as
        its error, never raised)."""
        for name, fn in (("heartbeats", heartbeats),
                         ("trace_tail", trace_tail),
                         ("transport", transport)):
            if fn is not None:
                self._fleet_ctx[name] = fn

    def _rstate(self, replica) -> Dict[str, Any]:
        st = self._rep.get(replica)
        if st is None:
            st = self._rep[replica] = {
                "tick_ring": collections.deque(maxlen=self._ring.maxlen),
                "records": collections.deque(maxlen=self._ring.maxlen),
                "static_ticks": 0, "last_engine_ticks": None,
                "accepts": collections.deque(maxlen=self.accept_window),
                "accept_healthy": False,
                "prefix": collections.deque(maxlen=self.prefix_window),
                "prefix_hits_total": 0,
                "queued": collections.deque(maxlen=self.queue_window),
                "retransmits": collections.deque(maxlen=self.window),
            }
        return st

    def reset(self) -> None:
        super().reset()
        self._rep.clear()

    # -- detection seams -----------------------------------------------------

    def observe_fleet_tick(self, replica, *, tick: int,
                           engine_ticks: Optional[int], queued: int,
                           busy: bool) -> List[Verdict]:
        """Feed one fleet-tick view of one live replica: the fleet's
        tick index, the replica's engine tick counter (its locally
        reported progress), queue depth, and whether it holds work."""
        st = self._rstate(replica)
        row = {"tick": int(tick), "engine_ticks": engine_ticks,
               "queued": int(queued), "busy": bool(busy)}
        st["tick_ring"].append(row)
        out: List[Verdict] = []
        if busy and engine_ticks is not None \
                and engine_ticks == st["last_engine_ticks"]:
            st["static_ticks"] += 1
        else:
            st["static_ticks"] = 0
        st["last_engine_ticks"] = engine_ticks
        if st["static_ticks"] >= self.stall_ticks:
            v = self._maybe_fire(replica, Verdict(
                kind="tick_stall", step=int(tick),
                value=float(st["static_ticks"]),
                threshold=float(self.stall_ticks),
                detail=(f"replica {replica} holds work but its engine "
                        f"tick counter has been static for "
                        f"{st['static_ticks']} consecutive fleet ticks "
                        f"(wedged child? stalled engine?)")), row)
            if v:
                out.append(v)
        st["queued"].append(int(queued))
        qs = st["queued"]
        if (len(qs) == qs.maxlen
                and all(b >= a for a, b in zip(qs, list(qs)[1:]))
                and qs[-1] - qs[0] >= self.queue_growth):
            v = self._maybe_fire(replica, Verdict(
                kind="queue_divergence", step=int(tick),
                value=float(qs[-1] - qs[0]),
                threshold=float(self.queue_growth),
                detail=(f"replica {replica} queue grew monotonically "
                        f"{qs[0]} -> {qs[-1]} over the last {len(qs)} "
                        f"ticks — arrivals have diverged from service "
                        f"rate")), row)
            if v:
                out.append(v)
        return out

    def observe_serving(self, replica, rec: Dict[str, Any]
                        ) -> List[Verdict]:
        """Feed one terminal ``kind="request"`` (or per-tick
        ``decode_tick``) record attributed to ``replica``."""
        if rec.get("kind") not in ("request", "decode_tick"):
            return []
        if rec.get("finish_reason") == "retried":
            return []
        st = self._rstate(replica)
        st["records"].append(dict(rec))
        out: List[Verdict] = []
        proposed = rec.get("draft_proposed") or 0
        if proposed:
            rate = (rec.get("draft_accepted") or 0) / proposed
            st["accepts"].append(rate)
            if rate > 2.0 * self.accept_floor:
                st["accept_healthy"] = True
            acc = st["accepts"]
            if (st["accept_healthy"] and len(acc) == acc.maxlen
                    and max(acc) <= self.accept_floor):
                v = self._maybe_fire(replica, Verdict(
                    kind="accept_collapse", step=rec.get("rid"),
                    value=round(max(acc), 4),
                    threshold=self.accept_floor,
                    detail=(f"replica {replica} draft accept-rate "
                            f"collapsed: last {len(acc)} draft-carrying "
                            f"requests all ≤ {self.accept_floor:.0%} "
                            f"after a healthy phase — the draft model "
                            f"has stopped predicting the target")), rec)
                if v:
                    out.append(v)
        hits = rec.get("prefix_hit_blocks")
        if hits is not None:
            st["prefix"].append(int(hits))
            st["prefix_hits_total"] += int(hits)
            pf = st["prefix"]
            before = st["prefix_hits_total"] - sum(pf)
            if len(pf) == pf.maxlen and sum(pf) == 0 and before > 0:
                v = self._maybe_fire(replica, Verdict(
                    kind="prefix_hit_collapse", step=rec.get("rid"),
                    value=0.0, threshold=1.0,
                    detail=(f"replica {replica} prefix-cache hits "
                            f"vanished: {before} blocks hit earlier, "
                            f"zero across the last {len(pf)} requests "
                            f"(retention evicted? affinity broken?)")),
                    rec)
                if v:
                    out.append(v)
        return out

    def observe_transport(self, replica, stats: Dict[str, Any]
                          ) -> List[Verdict]:
        """Feed a process replica's cumulative transport counters (the
        ``transport_stats()`` dict) once per fleet tick."""
        cur = stats.get("retransmits")
        if cur is None:
            return []
        st = self._rstate(replica)
        ring = st["retransmits"]
        rise = int(cur) - ring[0] if ring else 0
        ring.append(int(cur))
        if rise >= self.retransmit_burst_n:
            v = self._maybe_fire(replica, Verdict(
                kind="retransmit_burst", step=None, value=float(rise),
                threshold=float(self.retransmit_burst_n),
                detail=(f"replica {replica} transport retransmits rose "
                        f"by {rise} within the last {len(ring)} ticks — "
                        f"the link to that child is flapping")),
                dict(stats))
            return [v] if v else []
        return []

    # -- flight recorder -----------------------------------------------------

    def _maybe_fire(self, replica, verdict: Verdict,
                    rec: Dict[str, Any]) -> Optional[Verdict]:
        key = f"{verdict.kind}@r{replica}"
        if not self.rearm and key in self._fired:
            return None
        self._fired.add(key)
        self._serving_replica = replica
        try:
            self._trigger(verdict, rec)
        finally:
            self._serving_replica = None
        return verdict

    def _dump_bundle(self, verdict: Verdict, rec: Dict[str, Any]) -> str:
        rep = self._serving_replica
        if rep is None:
            return super()._dump_bundle(verdict, rec)
        seq = len(self.bundles)
        bundle = os.path.join(
            self.out_dir, f"anomaly_{seq:03d}_{verdict.kind}_r{rep}")
        os.makedirs(bundle, exist_ok=True)
        verdict.bundle = bundle
        st = self._rstate(rep)
        with open(os.path.join(bundle, "verdict.json"), "w") as f:
            json.dump({"ts": time.time(), "replica": rep,
                       "verdict": verdict.to_dict(),
                       "trigger_record": rec}, f, indent=2, default=str)
        with open(os.path.join(bundle, "tick_ring.jsonl"), "w") as f:
            for r in st["tick_ring"]:
                f.write(json.dumps(r, default=str) + "\n")
        with open(os.path.join(bundle, "records_tail.jsonl"), "w") as f:
            for r in st["records"]:
                f.write(json.dumps(r, default=str) + "\n")
        for name, fn in self._fleet_ctx.items():
            try:
                payload = fn()
            except Exception as e:
                payload = {"error": f"{type(e).__name__}: {e}"}
            fname = ("fleet_trace_tail.json" if name == "trace_tail"
                     else f"{name}.json")
            with open(os.path.join(bundle, fname), "w") as f:
                json.dump(payload, f, default=str)
        self.bundles.append(bundle)
        return bundle
