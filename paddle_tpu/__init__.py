"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of 2017-era PaddlePaddle
(reference: QingshuChen/Paddle): layer library, sequence models without padding
waste, CRF/CTC structured costs, beam search, a full optimizer/LR-schedule suite,
sparse embedding training, evaluators, checkpoint/resume, and distributed training
via device meshes + XLA collectives instead of a parameter-server tier.
"""

__version__ = "0.1.0"

from . import core
from .core import (Module, Sequential, SeqBatch, initializers, make_mesh,
                   default_mesh, use_mesh)
from . import obs
from . import parallel
from . import inference
from .inference import export, infer, load_inference_model
from . import serve
from . import config_helpers
