"""ctypes bindings for the native host-pipeline kernels (packer.cpp).

The reference's host data path is C++ (``PyDataProvider2.cpp`` Argument
assembly); here the packing hot loops compile on first use with the
in-image g++ into a cached shared object. Everything has a pure-Python
fallback (``PADDLE_TPU_NO_NATIVE=1`` forces it), and the Python and native
paths are tested for exact equality.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

__all__ = ["lib", "available"]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_SRC = os.path.join(os.path.dirname(__file__), "packer.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")
_SO = os.path.join(_BUILD_DIR, "libpaddle_tpu_native.so")


def _build() -> Optional[str]:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if os.path.exists(_SO) and \
            os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    # pid-unique temp + atomic replace: concurrent cold builds (parallel
    # jobs / pytest workers) each write their own file and the last rename
    # wins with a complete .so either way
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
    except (OSError, subprocess.SubprocessError):
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return _SO


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first call; None when
    unavailable (no compiler) or disabled."""
    global _lib, _tried
    if os.environ.get("PADDLE_TPU_NO_NATIVE"):
        return None
    with _lock:
        if _tried:
            return _lib
        _tried = True
        so = _build()
        if so is None:
            return None
        try:
            L = ctypes.CDLL(so)
        except OSError:
            return None             # corrupt/partial .so: Python fallback
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        L.ptn_pack_first_fit.restype = ctypes.c_int32
        L.ptn_pack_first_fit.argtypes = [i64p, i64p, ctypes.c_int64,
                                         ctypes.c_int64, i32p, i32p]
        L.ptn_positions_from_segments.restype = None
        L.ptn_positions_from_segments.argtypes = [i32p, ctypes.c_int64,
                                                  ctypes.c_int64, i32p]
        u8p = ctypes.POINTER(ctypes.c_uint8)
        L.ptn_recordio_scan.restype = ctypes.c_int64
        L.ptn_recordio_scan.argtypes = [u8p, ctypes.c_int64,
                                        ctypes.c_int64, i64p]
        _lib = L
        return _lib


def available() -> bool:
    return lib() is not None
