// Native host-side data-pipeline kernels.
//
// The reference keeps its host data path native (ragged Argument assembly in
// C++: paddle/gserver/dataproviders/PyDataProvider2.cpp converts Python
// minibatches to packed Argument buffers; sequence bookkeeping lives in
// paddle/parameter/Argument.cpp). This library is the TPU-native analog for
// the two host-side hot loops of the packing pipeline:
//   - first-fit-decreasing bin packing of ragged sequences into fixed rows
//     (core/sequence.py pack_sequences), and
//   - per-token segment positions (positions_from_segments).
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).

#include <cstddef>
#include <cstdint>
#include <vector>

using std::size_t;

extern "C" {

// First-fit packing over sequences visited in the given order.
// order[i] gives the index of the i-th sequence to place (the Python side
// passes the stable length-descending order, matching pack_sequences).
// Outputs, indexed by ORIGINAL sequence index:
//   slot_out[j]   - row the sequence was placed in
//   offset_out[j] - starting column
// Returns the number of rows used.
int32_t ptn_pack_first_fit(const int64_t* lengths, const int64_t* order,
                           int64_t n, int64_t row_len,
                           int32_t* slot_out, int32_t* offset_out) {
  std::vector<int64_t> free_space;
  free_space.reserve(64);
  for (int64_t i = 0; i < n; ++i) {
    const int64_t idx = order[i];
    int64_t len = lengths[idx];
    if (len > row_len) len = row_len;  // truncation, as in pack_sequences
    int32_t slot = -1;
    for (size_t r = 0; r < free_space.size(); ++r) {
      if (free_space[r] >= len) { slot = static_cast<int32_t>(r); break; }
    }
    if (slot < 0) {
      free_space.push_back(row_len);
      slot = static_cast<int32_t>(free_space.size() - 1);
    }
    slot_out[idx] = slot;
    offset_out[idx] = static_cast<int32_t>(row_len - free_space[slot]);
    free_space[slot] -= len;
  }
  return static_cast<int32_t>(free_space.size());
}

// positions_from_segments: per-token position within its own segment.
// seg is [b, t] int32 row-major; out the same shape.
void ptn_positions_from_segments(const int32_t* seg, int64_t b, int64_t t,
                                 int32_t* out) {
  for (int64_t i = 0; i < b; ++i) {
    const int32_t* row = seg + i * t;
    int32_t* orow = out + i * t;
    int32_t pos = 0;
    int32_t prev = 0;
    for (int64_t j = 0; j < t; ++j) {
      const int32_t s = row[j];
      pos = (s == prev && s != 0) ? pos + 1 : 0;
      orow[j] = pos;
      prev = s;
    }
  }
}

}  // extern "C"

// -- recordio scan -----------------------------------------------------------
//
// Index recovery for the record file format (data/recordio.py:
// [u32 len][u32 crc32][payload] stream + JSON offset sidecar). The sidecar
// is a cache; when it is lost or stale the reader rebuilds it by scanning
// the raw bytes and CRC-checking every record — the role the reference's Go
// master performed when building its RecordIO chunk index
// (go/master/service.go:253). This is the hot loop of that recovery.

namespace {

// CRC-32 (IEEE 802.3, reflected 0xEDB88320) — the same polynomial as
// zlib.crc32 so native and Python paths agree bit-for-bit.
uint32_t crc32_update(uint32_t crc, const uint8_t* p, size_t n) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  crc = ~crc;
  for (size_t i = 0; i < n; ++i)
    crc = table[(crc ^ p[i]) & 0xFFu] ^ (crc >> 8);
  return ~crc;
}

}  // namespace

extern "C" {

// Scan a raw record buffer, recovering record offsets and verifying every
// CRC. Returns the record count, or -(1 + byte_offset) at the first
// corrupt/truncated record. offsets_out must hold max_records entries.
int64_t ptn_recordio_scan(const uint8_t* data, int64_t nbytes,
                          int64_t max_records, int64_t* offsets_out) {
  int64_t off = 0;
  int64_t n = 0;
  while (off < nbytes) {
    if (off + 8 > nbytes || n >= max_records) return -(1 + off);
    const uint32_t len = static_cast<uint32_t>(data[off]) |
                         (static_cast<uint32_t>(data[off + 1]) << 8) |
                         (static_cast<uint32_t>(data[off + 2]) << 16) |
                         (static_cast<uint32_t>(data[off + 3]) << 24);
    const uint32_t crc = static_cast<uint32_t>(data[off + 4]) |
                         (static_cast<uint32_t>(data[off + 5]) << 8) |
                         (static_cast<uint32_t>(data[off + 6]) << 16) |
                         (static_cast<uint32_t>(data[off + 7]) << 24);
    if (off + 8 + static_cast<int64_t>(len) > nbytes) return -(1 + off);
    if (crc32_update(0, data + off + 8, len) != crc) return -(1 + off);
    offsets_out[n++] = off;
    off += 8 + static_cast<int64_t>(len);
  }
  return n;
}

}  // extern "C"
