"""Plot training curves from trainer logs — the ``paddle.utils.plotcurve``
analog (reference: ``/root/reference/python/paddle/utils/plotcurve.py``,
which greps ``Pass=.. Batch=.. AvgCost=..`` lines out of ``paddle.INFO``
and plots the requested keys).

This framework's trainer logs ``pass P batch B cost=C k=v ...``
(``train/trainer.py`` log_period lines); this tool parses any ``key=float``
token from those lines, from a file or stdin, and writes a figure (or,
with no matplotlib, a gnuplot-style ``.dat`` table — the plot data is the
point; rendering is optional).

Usage::

    python -m paddle_tpu.utils.plotcurve -i train.log -o curve.png cost
    some_cmd | python -m paddle_tpu.utils.plotcurve -o curve.png cost error
"""

from __future__ import annotations

import argparse
import re
import sys
from typing import Dict, Iterable, List, Sequence, Tuple

__all__ = ["parse_log", "plot_curves", "main"]

# a trainer progress line: "... pass 0 batch 100 cost=0.6931 error=0.4 ..."
_LINE_RE = re.compile(r"pass\s+(\d+)\s+batch\s+(\d+)", re.IGNORECASE)
_KV_RE = re.compile(r"([A-Za-z_][\w.]*)=([-+.\deE]+)")


def parse_log(lines: Iterable[str], keys: Sequence[str]) -> Dict[
        str, List[Tuple[int, float]]]:
    """Extract ``key=value`` series from trainer log lines.

    Returns ``{key: [(global_batch_index, value), ...]}``; the x axis is the
    running line count of progress lines (the reference plots sequence
    position too — batch counters reset every pass)."""
    out: Dict[str, List[Tuple[int, float]]] = {k: [] for k in keys}
    x = 0
    for line in lines:
        if not _LINE_RE.search(line):
            continue
        kvs = dict(_KV_RE.findall(line))
        for k in keys:
            if k in kvs:
                try:
                    out[k].append((x, float(kvs[k])))
                except ValueError:
                    pass
        # x is the progress-line count, advanced on EVERY matching line so
        # the same log plotted with different key sets shares x coordinates
        x += 1
    return out


def plot_curves(series: Dict[str, List[Tuple[int, float]]], output,
                fmt: str = "png") -> str:
    """Render the parsed series. With matplotlib available writes a figure
    and returns "figure"; otherwise writes a plain-text table and returns
    "table"."""
    try:
        import matplotlib
        matplotlib.use("Agg")           # headless (remote session) safe
        import matplotlib.pyplot as plt
    except ImportError:
        rows = sorted({x for pts in series.values() for x, _ in pts})
        cols = {k: dict(pts) for k, pts in series.items()}
        close = isinstance(output, str)
        f = open(output, "w") if close else output

        def w(s):                      # caller streams may be binary
            try:
                f.write(s)
            except TypeError:
                f.write(s.encode())
        try:
            w("# x " + " ".join(series) + "\n")
            for x in rows:
                w(" ".join([str(x)] + [
                    format(cols[k].get(x, float("nan")), ".6g")
                    for k in series]) + "\n")
        finally:
            if close:                  # never close a caller-provided stream
                f.close()
        return "table"

    fig, ax = plt.subplots(figsize=(8, 5))
    for k, pts in series.items():
        if pts:
            xs, ys = zip(*pts)
            ax.plot(xs, ys, label=k)
    ax.set_xlabel("batch (cumulative)")
    ax.legend()
    ax.grid(True, alpha=0.3)
    fig.savefig(output, format=fmt, bbox_inches="tight")
    plt.close(fig)
    return "figure"


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Plot training curves from paddle_tpu trainer logs.")
    p.add_argument("-i", "--input", default=None,
                   help="log file (default: stdin)")
    p.add_argument("-o", "--output", default=None,
                   help="figure file (default: stdout)")
    p.add_argument("--format", default="png",
                   help="figure format (png|pdf|ps|eps|svg)")
    p.add_argument("key", nargs="*", default=["cost"],
                   help="keys to plot (default: cost)")
    args = p.parse_args(argv)
    keys = args.key or ["cost"]
    stream = open(args.input) if args.input else sys.stdin
    try:
        series = parse_log(stream, keys)
    finally:
        if args.input:
            stream.close()
    out = args.output or getattr(sys.stdout, "buffer", sys.stdout)
    kind = plot_curves(series, out, fmt=args.format)
    n = {k: len(v) for k, v in series.items()}
    print(f"plotted {n} as {kind}", file=sys.stderr)


if __name__ == "__main__":
    main()
