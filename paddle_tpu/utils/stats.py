"""Hierarchical timers / stats — the Stat.h analog.

Reference: ``/root/reference/paddle/utils/Stat.h:63,230`` (``StatSet`` with
``REGISTER_TIMER*`` macros, periodic ``printAllStatus``) used through the hot
loop. TPU-native notes: device work is async, so timers that should include
device time must fence via ``jax.block_until_ready`` (the ``sync`` flag); the
jax profiler (``start_trace``/``stop_trace``) is surfaced for kernel-level
traces (the analog of ``hl_profiler_start``/nvprof).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Optional

import jax

__all__ = ["StatSet", "BarrierStat", "global_stats", "timer",
           "profile_trace"]


class _Stat:
    __slots__ = ("total", "count", "max")

    def __init__(self):
        self.total = 0.0
        self.count = 0
        self.max = 0.0

    def add(self, dt: float):
        self.total += dt
        self.count += 1
        self.max = max(self.max, dt)


class StatSet:
    def __init__(self, name: str = "stats"):
        self.name = name
        self._stats: Dict[str, _Stat] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def time(self, key: str, sync=None):
        """Time a block; pass ``sync=array_or_pytree`` to block on device work."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if sync is not None:
                jax.block_until_ready(sync)
            dt = time.perf_counter() - t0
            with self._lock:
                self._stats.setdefault(key, _Stat()).add(dt)

    def add(self, key: str, dt: float):
        with self._lock:
            self._stats.setdefault(key, _Stat()).add(dt)

    def reset(self):
        with self._lock:
            self._stats.clear()

    def summary(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: {"total_s": s.total, "count": s.count,
                        "avg_ms": 1e3 * s.total / max(1, s.count),
                        "max_ms": 1e3 * s.max}
                    for k, s in self._stats.items()}

    def to_dict(self) -> Dict[str, object]:
        """JSON-ready export: name + the per-key summary (telemetry sinks
        and bench.py consume this)."""
        return {"name": self.name, "stats": self.summary()}

    def report(self, top_n: Optional[int] = None) -> str:
        """Sorted summary, heaviest total time first — the reference's
        ``printAllStatus`` table (``utils/Stat.h``). ``top_n`` caps the
        rows (None = all)."""
        rows = sorted(self.summary().items(),
                      key=lambda kv: kv[1]["total_s"], reverse=True)
        shown = rows if top_n is None else rows[:top_n]
        lines = [f"=== {self.name} ==="]
        for k, v in shown:
            lines.append(f"  {k:<30s} n={v['count']:<6d} "
                         f"avg={v['avg_ms']:8.2f}ms max={v['max_ms']:8.2f}ms "
                         f"total={v['total_s']:.2f}s")
        if top_n is not None and len(rows) > top_n:
            lines.append(f"  ... {len(rows) - top_n} more")
        return "\n".join(lines)


_global = StatSet("global")


def global_stats() -> StatSet:
    return _global


def timer(key: str, sync=None):
    return _global.time(key, sync=sync)


@contextlib.contextmanager
def profile_trace(logdir: str):
    """jax profiler trace (view in TensorBoard/Perfetto) — the GPU-profiler
    analog (``hl_profiler_start/end``)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class BarrierStat:
    """Distributed-imbalance telemetry — the ``BarrierStatSet`` analog
    (``utils/Stat.h:230``; the reference timed how unevenly trainers arrived
    at pserver barriers).

    On TPU the "barrier" is every collective: imbalance shows up as the
    spread of per-host step durations. Each host feeds its local step time
    into :meth:`update`; :meth:`gather` all-gathers the latest sample across
    hosts (one tiny psum-style collective, OUTSIDE the hot loop) and returns
    the spread statistics. Single-process runs report a spread of zero.
    """

    def __init__(self, name: str = "step_time"):
        self.name = name
        self._last: Optional[float] = None
        self._spreads = _Stat()

    def update(self, seconds: float) -> None:
        self._last = float(seconds)

    def gather(self) -> Dict[str, float]:
        """All-gather the latest sample across hosts; returns min/max/mean
        and relative spread ((max-min)/mean).

        Every host MUST call this the same number of times (SPMD contract);
        a host with no sample yet contributes a NaN sentinel rather than
        skipping the collective — an early return here would deadlock the
        other hosts inside the allgather."""
        import numpy as np
        local = np.float32(self._last if self._last is not None else np.nan)
        if jax.process_count() == 1:
            times = np.array([local])
        else:
            from jax.experimental import multihost_utils
            times = np.asarray(multihost_utils.process_allgather(local))
        times = times[np.isfinite(times)]
        if times.size == 0:
            return {}
        mn, mx, mean = float(times.min()), float(times.max()), \
            float(times.mean())
        spread = (mx - mn) / mean if mean else 0.0
        self._spreads.add(spread)
        return {f"{self.name}_min_s": mn, f"{self.name}_max_s": mx,
                f"{self.name}_mean_s": mean, f"{self.name}_spread": spread}

    def summary(self) -> Dict[str, float]:
        s = self._spreads
        return {"mean_spread": s.total / max(1, s.count),
                "max_spread": s.max, "samples": s.count}
