"""Utilities: timers/stats, logging (successor of paddle/utils)."""

from .stats import StatSet, global_stats, profile_trace, timer
