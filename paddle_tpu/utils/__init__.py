"""Utilities: timers/profiling (stats), flag/config system (flags), numeric
hardening (debug) — the paddle/utils tier."""

from . import debug, flags, gradcheck, interop, stats
from .flags import TrainerFlags, parse_flags
from .gradcheck import check_gradients
from .stats import (BarrierStat, StatSet, global_stats,
                    profile_trace, timer)
