"""Numeric hardening — the TPU analog of the reference's FP-exception traps
(``feenableexcept(FE_INVALID|FE_DIVBYZERO|FE_OVERFLOW)`` at trainer start,
``trainer/TrainerMain.cpp:36``; ``math/tests/test_FPException.cpp``).

On TPU there are no CPU FP traps; the equivalents are (a) XLA-level NaN
checking via ``jax.config.jax_debug_nans`` (recompiles with per-op checks)
and (b) cheap host-side finiteness asserts on the scalars/trees that already
cross to the host each step. :class:`~paddle_tpu.train.Trainer` wires (b) in
via its ``nan_check`` flag.
"""

from __future__ import annotations

from typing import Any

import numpy as np
import jax

__all__ = ["enable_nan_checks", "disable_nan_checks", "assert_finite",
           "nonfinite_leaves"]


def enable_nan_checks():
    """Turn on XLA-level NaN detection (every primitive checked; slow —
    debugging only, like running the reference under FP traps)."""
    jax.config.update("jax_debug_nans", True)


def disable_nan_checks():
    jax.config.update("jax_debug_nans", False)


def nonfinite_leaves(tree: Any) -> list:
    """Paths of leaves containing non-finite values (host-side)."""
    bad = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(leaf)
        if arr.dtype.kind == "f" and not np.isfinite(arr).all():
            bad.append(jax.tree_util.keystr(path))
    return bad


def assert_finite(tree: Any, name: str = "tree"):
    """Raise ``FloatingPointError`` naming the offending leaves."""
    bad = nonfinite_leaves(tree)
    if bad:
        raise FloatingPointError(
            f"non-finite values in {name}: {', '.join(bad[:8])}"
            + (f" (+{len(bad) - 8} more)" if len(bad) > 8 else ""))
