"""Framework interop — the ``torch2paddle.py`` converter analog
(reference: ``python/paddle/utils/torch2paddle.py``, which imported Lua
torch checkpoints into v1 parameter files).

Here the migration source is PyTorch: :func:`from_torch_state_dict` maps a
``torch.nn`` state dict onto a paddle_tpu params pytree, handling the layout
conventions that differ:

  - ``nn.Linear``: torch stores ``weight [out, in]``; our Linear is
    ``w [in, out]`` → transpose.
  - ``nn.Conv2d``: torch is OIHW; our Conv2D kernels are HWIO → permute.
  - ``nn.BatchNorm2d``: weight/bias -> scale/shift params; running stats ->
    the ``state`` collection.

The mapping is name-based: pass ``rules`` as ``(torch_prefix,
paddle_tpu_path)`` pairs; each rule moves one module's tensors.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import numpy as np

__all__ = ["from_torch_state_dict", "torch_tensor_to_numpy"]


def torch_tensor_to_numpy(t) -> np.ndarray:
    """Detach/ cpu / numpy, without importing torch at module scope."""
    return np.asarray(t.detach().cpu().numpy())


def _set_path(tree: Dict[str, Any], path: str, value: np.ndarray):
    parts = path.split("/")
    node = tree
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value


def from_torch_state_dict(state_dict,
                          rules: Sequence[Tuple[str, str]],
                          kinds: Dict[str, str]) -> Dict[str, Any]:
    """Convert a torch state dict to ``{"params": ..., "state": ...}``.

    ``rules`` maps torch module prefixes to paddle_tpu module paths (e.g.
    ``("fc1", "MnistMLP_0/Linear_0")``); ``kinds[torch_prefix]`` names the
    layer type: ``"linear"`` | ``"conv2d"`` | ``"batchnorm"``. Unknown
    prefixes in the state dict are ignored (convert what you map — the
    reference converter worked the same way).
    """
    params: Dict[str, Any] = {}
    state: Dict[str, Any] = {}
    for torch_prefix, pt_path in rules:
        kind = kinds[torch_prefix]
        get = lambda suffix: torch_tensor_to_numpy(
            state_dict[f"{torch_prefix}.{suffix}"])
        if kind == "linear":
            _set_path(params, f"{pt_path}/w", get("weight").T)
            if f"{torch_prefix}.bias" in state_dict:
                _set_path(params, f"{pt_path}/b", get("bias"))
        elif kind == "conv2d":
            # OIHW -> HWIO
            _set_path(params, f"{pt_path}/w",
                      get("weight").transpose(2, 3, 1, 0))
            if f"{torch_prefix}.bias" in state_dict:
                _set_path(params, f"{pt_path}/b", get("bias"))
        elif kind == "batchnorm":
            # affine=False BatchNorms have no weight/bias (mirror our
            # BatchNorm(use_scale_shift=False))
            if f"{torch_prefix}.weight" in state_dict:
                _set_path(params, f"{pt_path}/scale", get("weight"))
                _set_path(params, f"{pt_path}/shift", get("bias"))
            _set_path(state, f"{pt_path}/mean", get("running_mean"))
            _set_path(state, f"{pt_path}/var", get("running_var"))
        else:
            raise ValueError(f"unknown layer kind {kind!r}")
    return {"params": params, "state": state}
