"""Flag/config system — the gflags tier of the reference
(``paddle/utils/Flags.cpp:18-81``), kept "config is data" (the proto-config
tier, ``proto/TrainerConfig.proto``) by building every flag set from a plain
dataclass that serializes to JSON.

Usage::

    @dataclasses.dataclass
    class MyFlags(TrainerFlags):
        extra_knob: float = 1.0

    flags = parse_flags(MyFlags)          # CLI > env > json > defaults

Resolution order: command line (``--batch_size 64``) beats environment
(``PADDLE_TPU_BATCH_SIZE=64``) beats ``--flags_json file`` beats dataclass
defaults — so a saved config reproduces a run and the CLI can still poke one
knob (the reference's gflags-over-proto layering).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import typing
from typing import Any, Optional, Sequence, Type, TypeVar

__all__ = ["TrainerFlags", "parse_flags", "flags_to_json", "flags_from_json"]

T = TypeVar("T")

_ENV_PREFIX = "PADDLE_TPU_"


@dataclasses.dataclass
class TrainerFlags:
    """The canonical training knobs (mirrors ``Flags.cpp``: batch size, lr,
    passes, beam width, logging/saving cadence, seed, checkpoint dir)."""
    batch_size: int = 128
    learning_rate: float = 0.01
    num_passes: int = 1
    beam_size: int = 4
    log_period: int = 100
    saving_period: int = 0               # 0 = per-pass checkpoints only
    checkpoint_dir: str = ""
    checkpoint_keep: int = 3
    seed: int = 0
    resume: bool = False
    use_bf16: bool = True
    nan_check: bool = False
    param_stats_period: int = 0          # --show_parameter_stats_period


def _base_type(tp):
    if typing.get_origin(tp) is typing.Union:       # Optional[X]
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def _is_optional(tp) -> bool:
    return (typing.get_origin(tp) is typing.Union
            and type(None) in typing.get_args(tp))


def _coerce(tp, raw):
    if raw is None:
        if _is_optional(tp):      # JSON null for an Optional field
            return None
        raise ValueError(f"null is not valid for a non-Optional {tp} flag")
    tp = _base_type(tp)
    if tp is bool:
        if isinstance(raw, bool):
            return raw
        return str(raw).lower() in ("1", "true", "yes", "on")
    return tp(raw)


def parse_flags(cls: Type[T] = TrainerFlags,
                argv: Optional[Sequence[str]] = None) -> T:
    """Build ``cls`` from CLI args / env vars / ``--flags_json`` / defaults.

    ``argv`` defaults to ``sys.argv[1:]``; pass ``[]`` explicitly to ignore
    the command line (e.g. in tests).
    """
    assert dataclasses.is_dataclass(cls), "flags must be a dataclass"
    # get_type_hints resolves PEP563 string annotations in the defining
    # module's namespace (a bare eval here would NameError on user types).
    hints = typing.get_type_hints(cls)
    parser = argparse.ArgumentParser(prog=cls.__name__, allow_abbrev=False)
    parser.add_argument("--flags_json", type=str, default=None,
                        help="JSON file with flag defaults")
    for f in dataclasses.fields(cls):
        tp = _base_type(hints[f.name])
        if tp is bool:
            parser.add_argument(f"--{f.name}", type=str, default=None,
                                metavar="BOOL")
        else:
            parser.add_argument(f"--{f.name}", type=tp, default=None)
    ns = parser.parse_args(sys.argv[1:] if argv is None else list(argv))

    values: dict = {}
    if ns.flags_json:
        with open(ns.flags_json) as fh:
            file_vals = json.load(fh)
        for f in dataclasses.fields(cls):
            if f.name in file_vals:
                values[f.name] = _coerce(hints[f.name], file_vals[f.name])
    for f in dataclasses.fields(cls):
        env = os.environ.get(_ENV_PREFIX + f.name.upper())
        if env is not None:
            values[f.name] = _coerce(hints[f.name], env)
    for f in dataclasses.fields(cls):
        cli = getattr(ns, f.name)
        if cli is not None:
            values[f.name] = _coerce(hints[f.name], cli)
    out = cls(**values)
    # Which fields were explicitly set (CLI/env/json, any source) — lets
    # consumers implement "explicit flag beats script settings()" without
    # guessing from defaults (cli.run_config_script uses this).
    object.__setattr__(out, "_explicit", frozenset(values))
    return out


def flags_to_json(flags) -> str:
    return json.dumps(dataclasses.asdict(flags), indent=2, sort_keys=True)


def flags_from_json(cls: Type[T], text: str) -> T:
    hints = typing.get_type_hints(cls)
    return cls(**{k: _coerce(hints[k], v)
                  for k, v in json.loads(text).items()})
