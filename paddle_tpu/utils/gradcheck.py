"""Whole-model numeric gradient checking — the ``--job=checkgrad`` analog
(reference: ``Trainer::checkGradient``, ``trainer/Trainer.cpp``; per-layer
version ``gserver/tests/test_LayerGrad.cpp`` with ``checkgrad_eps``,
``utils/Flags.cpp:68``).

Against autodiff this becomes a sanity oracle for hand-written VJPs (Pallas
custom_vjp kernels, custom losses): compare directional central differences
of the loss against the autodiff gradient along random directions.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["check_gradients"]


def check_gradients(loss_fn: Callable, params, num_directions: int = 4,
                    eps: float = 1e-2, rtol: float = 2e-2,
                    atol: float = 1e-4,
                    rng: Optional[np.random.RandomState] = None) -> float:
    """Verify ``jax.grad(loss_fn)(params)`` against central differences.

    The first probe direction is the (normalized) gradient itself — the
    high-signal probe, robust to f32 evaluation noise — followed by random
    unit directions. For each direction ``d``:
    ``(loss(p + eps*d) - loss(p - eps*d)) / (2 eps)`` must match
    ``<grad, d>``. Raises ``AssertionError`` on mismatch; returns the worst
    relative error.
    """
    rng = rng or np.random.RandomState(0)
    grad = jax.grad(loss_fn)(params)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    glades = jax.tree_util.tree_leaves(grad)
    worst = 0.0
    for i in range(num_directions):
        if i == 0:
            ds = [np.asarray(g, np.float32) for g in glades]
        else:
            ds = [rng.normal(size=np.shape(l)).astype(np.float32)
                  for l in leaves]
        norm = np.sqrt(sum(float((d ** 2).sum()) for d in ds)) or 1.0
        ds = [d / norm for d in ds]
        analytic = sum(float(np.vdot(np.asarray(g, np.float64), d))
                       for g, d in zip(glades, ds))
        def shift(sign):
            moved = [jnp.asarray(np.asarray(l, np.float32) + sign * eps * d)
                     for l, d in zip(leaves, ds)]
            return float(loss_fn(jax.tree_util.tree_unflatten(treedef,
                                                              moved)))
        numeric = (shift(+1.0) - shift(-1.0)) / (2.0 * eps)
        denom = max(abs(analytic), abs(numeric), atol)
        rel = abs(analytic - numeric) / denom
        worst = max(worst, rel)
        assert rel <= rtol or abs(analytic - numeric) <= atol, (
            f"gradient check failed: analytic={analytic:.6g} "
            f"numeric={numeric:.6g} rel={rel:.3g}")
    return worst
