"""Ulysses-style all-to-all sequence parallelism — the second long-context
strategy next to :mod:`.ring` (the build brief names both; the 2017
reference has neither, SURVEY.md §5).

Where ring attention keeps time sharded and rotates K/V shards around the
ICI ring, the all-to-all scheme re-shards: heads are scattered and time
gathered (`lax.all_to_all`), every device computes ordinary full-sequence
attention for its H/n heads, then the layout is swapped back. Two
all-to-alls per layer instead of n-1 ppermutes; preferable when
heads >= devices and the per-device full-T working set fits HBM — the
standard trade-off between the two schemes.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from .ring import _NEG, wrap_seq_parallel

__all__ = ["ulysses_attention", "make_ulysses_attention"]


def ulysses_attention(q, k, v, segments=None, *, axis_name: str,
                      axis_size: int, causal: bool = False,
                      scale: Optional[float] = None):
    """All-to-all sequence-parallel attention — call INSIDE shard_map.

    q, k, v: local shards [B, T/n, H, D], time sharded over ``axis_name``.
    Requires ``H % n == 0``. ``segments``: optional local [B, T/n]
    packed-sequence ids (1-based, 0 = padding); all-gathered over the seq
    axis (ids are O(T) ints — negligible next to the k/v all-to-alls) so
    each device's full-sequence attention masks across packed-sequence
    boundaries. Returns the local [B, T/n, H, D] output shard.
    """
    n = axis_size
    h = q.shape[2]
    assert h % n == 0, f"heads {h} must divide by seq-axis size {n}"
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])

    def scatter_heads(x):
        # [B, T/n, H, D] -> [B, T, H/n, D]: split heads n ways, gather time
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qg = scatter_heads(q).astype(jnp.float32) * scale
    kg = scatter_heads(k).astype(jnp.float32)
    vg = scatter_heads(v).astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qg, kg)
    if causal:
        t = s.shape[-1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    if segments is not None:
        seg_g = lax.all_gather(segments, axis_name, axis=1, tiled=True)
        sm = (seg_g[:, :, None] == seg_g[:, None, :]) \
            & (seg_g[:, :, None] > 0) & (seg_g[:, None, :] > 0)
        s = jnp.where(sm[:, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vg).astype(q.dtype)
    # [B, T, H/n, D] -> [B, T/n, H, D]: split time n ways, gather heads
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def make_ulysses_attention(mesh: Mesh, seq_axis: str = "seq",
                           batch_axis: Optional[str] = None,
                           causal: bool = False, with_segments: bool = False):
    """:func:`ulysses_attention` over global arrays — same surface as
    :func:`.ring.make_ring_attention` so models can switch strategies by
    config (shared wrapper: :func:`.ring.wrap_seq_parallel`)."""
    return wrap_seq_parallel(ulysses_attention, mesh, seq_axis, batch_axis,
                             causal, with_segments)
