"""Explicit Megatron-style tensor parallelism with SEQUENCE-PARALLEL
residuals for :class:`~paddle_tpu.models.transformer.TransformerLM`.

Two ways to run Megatron tp in this framework:

1. **Compiler-chosen** (``ShardingRules`` + pjit): shard the qkv/ffn
   weights column/row-wise and let XLA's SPMD partitioner insert the
   activation syncs. Simple, but the partitioner keeps the residual
   stream replicated and pays a full all-reduce per sublayer — 2B wire
   bytes each — and constraining the residuals seq-sharded
   (``TransformerLM(residual_sharding=...)``) does not reliably lower to
   reduce-scatter (measured on the CPU backend: the reshard splits into
   all-reduce + all-gather, WORSE than plain tp — 3.1 vs 2.0 GB/device
   per d512 step, experiments/scaling_projection.py r5 notes).

2. **Explicit** (this module): shard_map the whole LM and write the
   Megatron-SP collectives by hand — ``all_gather`` the LayerNorm'd
   seq-shard into each sublayer, ``psum_scatter`` the row-parallel
   partial sums back to seq-shards. The AG+RS pair moves the same wire
   bytes as the all-reduce it replaces (AR == RS+AG); the win is that the
   residual stream, LayerNorms, embeddings, and their gradients compute
   and LIVE on T/tp rows per device — activation memory and the
   unshardable-under-pjit elementwise work drop by the tp factor,
   which is what unlocks long sequences at large tp. This is the public
   Megatron-LM sequence-parallel recipe (Korthikanti et al. 2022)
   realized with XLA collectives; the transpose rules
   (all_gather <-> psum_scatter) make the backward the mirrored recipe
   automatically.

The function consumes a STANDARD ``TransformerLM`` variables tree (same
names, same math — the oracle test pins logits and grads against the
unsharded model) so checkpoints move freely between the pjit and explicit
paths. Requires dense FFN blocks (no MoE — expert parallelism is its own
axis, ``nn/moe.py``), no dropout, and ``num_heads % tp == 0``,
``T % tp == 0``, ``ffn_hidden % tp == 0``.

Reference lineage: the 2017 reference's model-parallel story is per-layer
device placement (``ParallelNeuralNetwork.h:36``); intra-layer tensor
parallelism postdates it — this is an "exceeds" item on the same axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .sharding import ShardingRules

__all__ = ["megatron_sp_rules", "make_megatron_sp_lm_apply"]


def megatron_sp_rules(model_axis: str = "model") -> ShardingRules:
    """The param-tree layout both tp paths share: qkv/ffn1 column-parallel,
    wo/ffn2 row-parallel, everything else (LN, embeddings, biases of
    row-parallel layers) replicated. ``model_axis`` names the mesh axis
    carrying the tensor-parallel degree (callers with a non-standard
    axis name — e.g. ``DecodeEngine(tp_axis=)`` — get matching specs)."""
    m = model_axis
    return ShardingRules([
        ("*/attn/wq", P(None, m)), ("*/attn/wk", P(None, m)),
        ("*/attn/wv", P(None, m)), ("*/attn/wo", P(m, None)),
        ("*/ffn1/w", P(None, m)), ("*/ffn1/b", P(m)),
        ("*/ffn2/w", P(m, None)),
    ])


def _layernorm(x, p, eps=1e-6):
    """Mirror of nn.layers.LayerNorm.forward (f32 stats, cast back)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(dtype)


def make_megatron_sp_lm_apply(model, mesh: Mesh, data_axis: str = "data",
                              model_axis: str = "model",
                              use_flash: bool = False,
                              with_loss: bool = False,
                              comm_dtype=None, remat=None):
    """Build ``apply_fn(variables, ids) -> logits`` running ``model`` (a
    dense ``TransformerLM``) as explicit tp+sp over ``mesh``.

    ``variables`` is the standard tree, its leaves laid out per
    :func:`megatron_sp_rules` (use ``parallel.shard_tree``); ``ids`` is the
    global [B, T] batch sharded ``P(data_axis, None)``. Returns global
    logits [B, T, vocab] in seq-sharded layout ``P(data, model, None)``.

    ``with_loss=True`` returns ``loss_fn(variables, ids, targets) ->
    scalar`` computing the mean next-token cross-entropy INSIDE the
    shard_map (per-shard sums + psum). Use this form for training: it
    keeps every [*, vocab] tensor seq-sharded — emitting global logits
    from the shard_map makes XLA assemble them with a [B, T, vocab]
    all-gather, which at d512/V32k is 2.1 GB/step of pure waste
    (measured, experiments/scaling_projection.py r5).

    ``comm_dtype`` (e.g. ``jnp.bfloat16``) casts the tensors crossing the
    AG/RS collectives, halving tp activation wire vs the f32 the policy's
    accumulate-in-f32 Linears otherwise put on it — the standard Megatron
    practice (activations are bf16-precision products anyway; local math
    stays in the original dtype). Default ``None`` = exact.

    Local math follows the ACTIVE dtype policy at trace time
    (``core.dtypes.current_policy()``), exactly as the pjit path's Linears
    do: matmul operands are ``cast_compute``'d and accumulate in
    ``accum_dtype`` (``preferred_element_type``), so under
    ``use_policy(bfloat16_compute)`` the explicit path reproduces the pjit
    numerics instead of silently running f32.

    ``remat`` (None | "dots" | "full", see
    :func:`paddle_tpu.models.transformer.remat_policy`) runs the layer loop
    as ONE ``jax.checkpoint``-wrapped ``lax.scan`` over the stacked
    per-layer shard params — layer-boundary seq-shards are all that's saved
    across the stack, composing sequence-parallel activation memory with
    rematerialization for long-context training."""
    try:
        from jax import shard_map as _shard_map
    except ImportError:                      # older jax
        from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(fn, **kw):
        if not use_flash:
            return _shard_map(fn, **kw)
        # pallas_call's out_shapes carry no varying-axes info, so
        # shard_map's vma check rejects the flash path — disable it there
        # via the shared no-check wrapper (the einsum path keeps the
        # check; the oracle tests pin both)
        from .overlap import shard_map_compat
        return shard_map_compat(fn, **kw)

    from ..nn import activations
    gelu = activations.get("gelu")

    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    tp = sizes[model_axis]
    H = model.blocks[0].attn.num_heads
    D = model.emb.dim
    hd = D // H
    L = len(model.blocks)
    assert H % tp == 0, f"num_heads {H} must divide by tp {tp}"
    assert model.blocks[0].moe_experts == 0, "MoE blocks: use the ep axis"
    root_name = model._name
    scale = 1.0 / float(hd) ** 0.5

    def _ag(z):
        """Sequence all-gather, optionally compressing the wire dtype.
        The optimization_barrier pins the downcast to the operand side —
        XLA's simplifier otherwise reorders convert across the collective
        and cancels the pair, silently restoring f32 wire (observed on
        the CPU backend)."""
        with jax.named_scope("sp_allgather"):
            if comm_dtype is None:
                return lax.all_gather(z, model_axis, axis=1, tiled=True)
            zb = lax.optimization_barrier(z.astype(comm_dtype))
            return lax.all_gather(zb, model_axis, axis=1,
                                  tiled=True).astype(z.dtype)

    def _rs(part):
        """Sequence reduce-scatter of row-parallel partial sums."""
        with jax.named_scope("sp_reduce_scatter"):
            if comm_dtype is None:
                return lax.psum_scatter(part, model_axis,
                                        scatter_dimension=1, tiled=True)
            pb = lax.optimization_barrier(part.astype(comm_dtype))
            return lax.psum_scatter(pb, model_axis, scatter_dimension=1,
                                    tiled=True).astype(part.dtype)

    from ..core.dtypes import current_policy

    def _dot(a, b):
        """Policy-cast matmul — the pjit path's Linear/MHA projection math
        (``cast_compute`` operands, accumulate in ``accum_dtype``). The
        policy is read HERE, at trace time, exactly as nn.layers.Linear
        reads it in forward() — a policy activated after this factory ran
        (build at setup, trace under ``use_policy``) still applies."""
        pol = current_policy()
        return jnp.dot(pol.cast_compute(a), pol.cast_compute(b),
                       preferred_element_type=pol.accum_dtype)

    def _attend_local(q, k, v):
        """Causal self-attention on this device's head group; q/k/v
        [B, T, h_local, hd]."""
        if use_flash:
            from ..nn.pallas_attention import flash_attention
            ctx = flash_attention(jnp.moveaxis(q, 2, 1),
                                  jnp.moveaxis(k, 2, 1),
                                  jnp.moveaxis(v, 2, 1), None, True)
            return jnp.moveaxis(ctx, 1, 2)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        logits = logits.astype(jnp.float32)
        T = q.shape[1]
        cm = jnp.tril(jnp.ones((T, T), bool))
        logits = jnp.where(cm[None, None], logits, -1e9)
        # softmax weights drop to the policy's compute dtype, mirroring
        # MultiHeadAttention's xla path (the context einsum re-promotes
        # against the f32-accumulated v operand); trace-time policy read
        w = jax.nn.softmax(logits, axis=-1).astype(
            current_policy().compute_dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", w, v)

    def _block_local(x, bp):
        """One transformer block on this device's shards — the Megatron-SP
        AG -> column -> row -> RS recipe for both sublayers. named_scope
        annotations expose the tp regions in profiler traces."""
        # attention sublayer: AG(seq) -> column qkv -> row wo -> RS(seq)
        with jax.named_scope("tp_attn"):
            z = _layernorm(x, bp["ln1"])
            zg = _ag(z)
            hl = H // tp
            q = _dot(zg, bp["attn"]["wq"]).reshape(*zg.shape[:2], hl, hd)
            k = _dot(zg, bp["attn"]["wk"]).reshape(*zg.shape[:2], hl, hd)
            v = _dot(zg, bp["attn"]["wv"]).reshape(*zg.shape[:2], hl, hd)
            ctx = _attend_local(q, k, v).reshape(*zg.shape[:2], hl * hd)
            part = _dot(ctx, bp["attn"]["wo"])     # partial over model
            x = x + _rs(part)
        # FFN sublayer: AG(seq) -> column ffn1 -> row ffn2 -> RS(seq)
        with jax.named_scope("tp_ffn"):
            z = _layernorm(x, bp["ln2"])
            zg = _ag(z)
            h1 = gelu(_dot(zg, bp["ffn1"]["w"]) + bp["ffn1"]["b"])
            part = _dot(h1, bp["ffn2"]["w"])
            return x + _rs(part) + bp["ffn2"]["b"]

    def _forward_local(params, ids):
        """Per-device body. ``params``: this device's shards (column/row
        slices per megatron_sp_rules); ``ids``: [B_local, T] (full seq).
        Returns this device's seq-shard of the logits [B_l, T/tp, V]."""
        root = params[root_name]
        midx = lax.axis_index(model_axis)
        T = ids.shape[1]
        assert T % tp == 0, f"seq len {T} must divide by tp {tp}"
        Tl = T // tp
        # ---- embed: each device embeds only ITS seq slice (sp) ----------
        with jax.named_scope("sp_embed"):
            sl = lax.dynamic_slice_in_dim(ids, midx * Tl, Tl, axis=1)
            emb_w = root["emb"]["w"]
            pos_w = root["pos"]["w"]
            valid = (sl >= 0) & (sl < emb_w.shape[0])  # Embedding.forward's
            x = jnp.take(emb_w, jnp.clip(sl, 0, emb_w.shape[0] - 1), axis=0)
            x = x * valid[..., None].astype(x.dtype)   # zero-for-padding
            x = x + jnp.take(pos_w, jnp.arange(Tl) + midx * Tl,
                             axis=0)[None]
        # (the residual stream stays in the embedding-table dtype — the
        # pjit path never casts it; only matmul operands drop to the
        # policy's compute dtype inside _dot)
        # ---- blocks ------------------------------------------------------
        if remat is None:
            for i in range(L):
                x = _block_local(x, root[f"block{i}"])
        else:
            from ..models.transformer import remat_policy
            stacked = jax.tree_util.tree_map(
                lambda *ls: jnp.stack(ls),
                *[root[f"block{i}"] for i in range(L)])

            def body(h, bp):
                return _block_local(h, bp), None

            body = jax.checkpoint(body, policy=remat_policy(remat))
            x, _ = lax.scan(body, x, stacked)
        # ---- head: final LN + tied readout on the local seq rows --------
        with jax.named_scope("sp_head"):
            z = _layernorm(x, root["ln_f"])
            return z @ emb_w.T.astype(z.dtype)

    rules = megatron_sp_rules()

    if with_loss:
        def loss_kernel(params, ids, targets):
            lg = _forward_local(params, ids)             # [B_l, Tl, V]
            midx = lax.axis_index(model_axis)
            Tl = lg.shape[1]
            tl = lax.dynamic_slice_in_dim(targets, midx * Tl, Tl, axis=1)
            lg32 = lg.astype(jnp.float32)
            lse = jax.nn.logsumexp(lg32, axis=-1)
            picked = jnp.take_along_axis(lg32, tl[..., None],
                                         axis=-1)[..., 0]
            local_sum = jnp.sum(lse - picked)
            local_cnt = jnp.asarray(tl.size, jnp.float32)
            total = lax.psum(local_sum, (data_axis, model_axis))
            cnt = lax.psum(local_cnt, (data_axis, model_axis))
            return total / cnt

        def loss_fn(variables, ids, targets):
            params = variables["params"]
            in_specs = (rules(params), P(data_axis, None),
                        P(data_axis, None))
            fn = shard_map(loss_kernel, mesh=mesh, in_specs=in_specs,
                           out_specs=P())
            return fn(params, ids, targets)

        return loss_fn

    def apply_fn(variables, ids):
        params = variables["params"]
        in_specs = (rules(params), P(data_axis, None))
        fn = shard_map(_forward_local, mesh=mesh, in_specs=in_specs,
                       out_specs=P(data_axis, model_axis, None))
        return fn(params, ids)

    return apply_fn
