"""Parameter-sharding rules: the TPU-native ``ParallelNeuralNetwork``.

The reference pins layers to devices via a per-layer ``device`` config attr
(``/root/reference/paddle/gserver/gradientmachines/ParallelNeuralNetwork.h:36``,
``proto/ModelConfig.proto`` LayerConfig.device). Here the analog is a table of
``(path pattern, PartitionSpec)`` rules mapping parameter-tree paths onto mesh
axes; XLA's SPMD partitioner turns the layout into compute+collectives. E.g.::

    rules = ShardingRules([
        ("*/hidden/w", P(None, "model")),   # column-parallel
        ("*/hidden/b", P("model")),
        ("*/out/w",    P("model", None)),   # row-parallel
    ])                                       # everything else replicated

Shardings for optimizer state are not declared anywhere: ``Trainer`` builds
them by running ``optimizer.init`` EAGERLY on already-committed params —
eager ``zeros_like`` on a sharded array inherits its sharding, so every
param-shaped slot gets the param's layout and scalars stay replicated (the
analog of the pserver's blockwise-sharded optimizer state,
``pserver/ParameterServer2.h:73``). Under jit the zeros would be
value-independent constants and land on one device.
"""

from __future__ import annotations

import contextlib
import fnmatch
import threading
from typing import List, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ShardingRules", "spec_tree", "named_shardings", "shard_tree",
           "sharded_init", "tp_shard_scope", "current_tp_shard",
           "tp_constrain"]


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


class ShardingRules:
    """Ordered ``(fnmatch pattern, PartitionSpec)`` rules; first match wins,
    default replicated. Patterns match the slash-joined parameter path
    (e.g. ``encoder/Linear_0/w``)."""

    def __init__(self, rules: Sequence[Tuple[str, P]], default: P = P()):
        self.rules = list(rules)
        self.default = default

    def spec_for(self, path: str) -> P:
        for pat, spec in self.rules:
            if fnmatch.fnmatchcase(path, pat):
                return spec
        return self.default

    def __call__(self, tree):
        return spec_tree(tree, self)


def spec_tree(tree, rules: Union[ShardingRules, Sequence[Tuple[str, P]]]):
    """Map a params pytree to a same-structure pytree of PartitionSpecs."""
    if not isinstance(rules, ShardingRules):
        rules = ShardingRules(rules)
    return jax.tree_util.tree_map_with_path(
        lambda path, _: rules.spec_for(_path_str(path)), tree)


def named_shardings(mesh: Mesh, specs):
    """PartitionSpec pytree -> NamedSharding pytree on ``mesh``."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))


def shard_tree(mesh: Mesh, tree, specs=None):
    """Commit a pytree to the mesh with the given specs (default replicated).
    ``specs`` may be a spec pytree or ShardingRules."""
    if specs is None:
        specs = jax.tree_util.tree_map(lambda _: P(), tree)
    elif isinstance(specs, ShardingRules):
        specs = specs(tree)
    return jax.device_put(tree, named_shardings(mesh, specs))


# ---------------------------------------------------------------------------
# shard-in-scope (ISSUE 15): a trace-time tensor-parallel context
# ---------------------------------------------------------------------------
#
# The serving decode programs are written once, mesh-oblivious
# (`models/transformer.py` prefill/decode_step/decode_span), and the
# engine runs them either on one device or over a tp mesh. The seam is
# this scope: the engine enters `tp_shard_scope(mesh, axis)` around its
# traced program bodies, and the layers sprinkle `tp_constrain` at the
# points whose placement must be PINNED (the head-sharded KV pools and
# projections, the replicated residual/logits) — identity no-ops when no
# scope is active, so the single-device path stays byte-identical.
# Everything not constrained is left to the SPMD partitioner, which is
# the whole reason one set of program bodies serves both worlds.

_TP_TLS = threading.local()


def _tp_stack() -> list:
    if not hasattr(_TP_TLS, "stack"):
        _TP_TLS.stack = []
    return _TP_TLS.stack


@contextlib.contextmanager
def tp_shard_scope(mesh: Mesh, axis: str = "model"):
    """Activate a tensor-parallel shard scope for code traced inside:
    :func:`tp_constrain` calls become real ``with_sharding_constraint``\\ s
    on ``mesh``'s ``axis``. Thread-local (trace-time state, like
    ``core.dtypes.current_policy``)."""
    _tp_stack().append((mesh, axis))
    try:
        yield
    finally:
        _tp_stack().pop()


def current_tp_shard() -> Optional[Tuple[Mesh, str]]:
    """The innermost active ``(mesh, axis)`` shard scope, or None."""
    stack = _tp_stack()
    return stack[-1] if stack else None


def tp_constrain(x, dim: Optional[int] = None):
    """Constrain every array leaf of ``x`` to carry the scope's tp axis
    on dimension ``dim`` (``None`` = fully replicated). Identity when no
    :func:`tp_shard_scope` is active — layers call this unconditionally
    and single-device traces are unchanged. ``dim`` indexes each leaf's
    OWN axes, so a quantized KV pool's ``(values [..., hd], scales
    [...])`` tuple constrains both leaves with one call."""
    scope = current_tp_shard()
    if scope is None:
        return x
    mesh, axis = scope

    def one(leaf):
        if dim is None:
            spec = P()
        else:
            # TRIMMED spec (trailing replicated dims omitted): the
            # partitioner normalizes constraint outputs to this form,
            # and some jax versions hash trimmed vs padded specs as
            # DIFFERENT shardings — a padded input spec would retrace
            # the engine's second call on a no-op layout change
            parts: List[Optional[str]] = [None] * dim + [axis]
            spec = P(*parts)
        return jax.lax.with_sharding_constraint(
            leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(one, x)


def sharded_init(model, rng, *args, mesh: Mesh, rules=None, **kwargs):
    """Initialize a model with parameters created directly in their sharded
    layout (no host-memory full copy — required once a model outgrows one
    chip). Returns ``(variables, param_specs)``.

    ``rules`` may be a :class:`ShardingRules`, a PartitionSpec pytree
    matching the params tree, or None (replicated). Runs ``model.init``
    under jit with ``out_shardings`` derived from it, so each parameter is
    materialized already partitioned.
    """

    def init_fn(r):
        return model.init(r, *args, **kwargs)

    shapes = jax.eval_shape(init_fn, rng)
    if rules is None or isinstance(rules, (ShardingRules, list, tuple)):
        param_specs = spec_tree(shapes["params"], rules or ShardingRules([]))
    else:
        param_specs = rules
    out_specs = {c: (param_specs if c == "params"
                     else jax.tree_util.tree_map(lambda _: P(), shapes[c]))
                 for c in shapes}
    out_sh = {c: named_shardings(mesh, s) for c, s in out_specs.items()}
    variables = jax.jit(init_fn, out_shardings=out_sh)(rng)
    return variables, param_specs
