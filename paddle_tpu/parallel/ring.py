"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

Long sequences are sharded along time; each device holds a [B, T/n, H, D]
slice of q/k/v. Attention against the full sequence is computed blockwise:
devices rotate their k/v shards around the ring with ``lax.ppermute`` (ICI
neighbor exchanges, overlapped with the block matmuls by XLA's async
collectives) while accumulating a streaming softmax (flash-attention style
log-sum-exp running max/sum), so the full [T, T] score matrix never
materializes and memory stays O(T/n * T/n) per step.

The 2017 reference has no sequence parallelism (SURVEY.md §5 records its
absence); this is the forward-looking capability row. Design follows the
public blockwise/ring-attention recipe (psum-free: only neighbor ppermute).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ring_attention", "make_ring_attention"]

_NEG = -1e30  # finite "-inf": keeps fully-masked rows NaN-free


def ring_attention(q, k, v, segments=None, *, axis_name: str, axis_size: int,
                   causal: bool = False, scale: Optional[float] = None):
    """Blockwise ring attention — call INSIDE shard_map.

    q, k, v: local shards [B, Tlocal, H, D], time sharded over ``axis_name``
    (axis static size ``axis_size``). ``segments``: optional local [B,
    Tlocal] packed-sequence ids (``core.sequence`` convention: 1-based,
    0 = padding); the k-side ids rotate around the ring with their k/v
    shard, confining attention within each packed sub-sequence. Returns the
    local output shard [B, Tlocal, H, D]. Softmax statistics accumulate in
    float32. Rows with no visible key (padding) return an unspecified
    finite value — mask downstream.
    """
    n = axis_size
    idx = lax.axis_index(axis_name)
    b, tl, h, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    qf = q.astype(jnp.float32) * scale
    q_pos = idx * tl + jnp.arange(tl)                       # global positions

    # Derive the accumulators from q (zeroed) so they carry q's exact
    # device-varying axes — plain constants would trip shard_map's
    # varying-axes check on the scan carry (constants are "unvarying", the
    # updated accumulators vary over the ring axis and any batch axes).
    zero_rows = jnp.swapaxes(jnp.sum(qf, axis=-1) * 0.0, 1, 2)  # [B, H, Tl]
    acc0 = qf * 0.0                                             # [B, Tl, H, D]
    m0 = zero_rows + _NEG                                       # running max
    l0 = zero_rows                                              # running sum
    perm = [(j, (j + 1) % n) for j in range(n)]
    carry0 = (k, v, acc0, m0, l0)
    if segments is not None:
        carry0 = carry0 + (segments,)

    def step(carry, i):
        if segments is not None:
            kb, vb, acc, m, l, seg_kb = carry
        else:
            kb, vb, acc, m, l = carry
        src = (idx - i) % n                 # ring owner of the block we hold
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32))
        if causal:
            k_pos = src * tl + jnp.arange(tl)
            s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, _NEG)
        if segments is not None:
            sm = (segments[:, :, None] == seg_kb[:, None, :]) \
                & (segments[:, :, None] > 0) & (seg_kb[:, None, :] > 0)
            s = jnp.where(sm[:, None], s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = (acc * jnp.swapaxes(corr, 1, 2)[..., None]
                   + jnp.einsum("bhqk,bkhd->bqhd", p,
                                vb.astype(jnp.float32)))
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        out = (kb, vb, acc_new, m_new, l_new)
        if segments is not None:
            out = out + (lax.ppermute(seg_kb, axis_name, perm),)
        return out, None

    carry, _ = lax.scan(step, carry0, jnp.arange(n))
    acc, m, l = carry[2], carry[3], carry[4]
    out = acc / jnp.maximum(jnp.swapaxes(l, 1, 2)[..., None], 1e-30)
    return out.astype(q.dtype)


def wrap_seq_parallel(attn_fn, mesh: Mesh, seq_axis: str,
                      batch_axis: Optional[str], causal: bool,
                      with_segments: bool = False):
    """Shared shard_map wrapper for sequence-parallel attention kernels
    (ring and Ulysses expose the same surface): takes GLOBAL [B, T, H, D]
    arrays (time sharded over ``seq_axis``, optionally batch over
    ``batch_axis``) and returns the global output. With
    ``with_segments=True`` the wrapped fn takes a fourth global [B, T]
    packed-sequence id argument (sharded over time like q/k/v)."""
    try:
        from jax import shard_map
    except ImportError:            # older jax
        from jax.experimental.shard_map import shard_map

    n = dict(zip(mesh.axis_names, mesh.devices.shape))[seq_axis]
    spec = P(batch_axis, seq_axis, None, None)
    seg_spec = P(batch_axis, seq_axis)
    fn = functools.partial(attn_fn, axis_name=seq_axis, axis_size=n,
                           causal=causal)
    in_specs = (spec, spec, spec) + ((seg_spec,) if with_segments else ())
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=spec)


def make_ring_attention(mesh: Mesh, seq_axis: str = "seq",
                        batch_axis: Optional[str] = None,
                        causal: bool = False, with_segments: bool = False):
    """:func:`ring_attention` over global arrays (see
    :func:`wrap_seq_parallel`)."""
    return wrap_seq_parallel(ring_attention, mesh, seq_axis, batch_axis,
                             causal, with_segments)
