"""Bucketed gradient-sync overlap: hide the dp all-reduce under the backward.

The reference Paddle's entire pserver tier existed to pipeline gradient
communication against computation — parameters were split into blocks,
each block's gradient shipped to its server the moment the backward
produced it (``pserver/ParameterServer2.h:73``, SURVEY §3.3). The
XLA-era default collapses all of that into GSPMD: the partitioner
inserts one all-reduce per gradient tensor wherever the backward
computes it, then the backend's combiner/scheduler typically merges and
sinks them into one monolithic sync after the full backward — the
exposed-communication gap ``Trainer.attribution_report()`` measures
(``comm.grad_allreduce.exposed_ms_today`` vs ``exposed_ms_if_overlapped``).

This module makes the sync OURS again, pserver-style but on-device:

- **Buckets** (:func:`partition_buckets`): parameter leaves are grouped
  in *reverse layer order* (output side first — the order the backward
  completes them) into byte-budgeted, dtype-homogeneous buckets.
- **The marker** (:func:`sync_tangent`): a ``custom_vjp`` identity
  wrapped around each bucket's leaves. Forward: nothing. Backward: the
  bucket's cotangents are raveled into ONE flat buffer and ``psum``-ed
  over the dp axis the moment the bucket's backward slice completes —
  one all-reduce per bucket, anchored *inside* the backward where the
  scheduler can float it under the remaining backward compute, instead
  of one giant post-backward sync.
- **The manual-dp region**: explicit ``lax.psum`` needs a bound axis
  name, so the Trainer runs the forward+backward of each microbatch
  inside a ``shard_map`` over the dp axis (other mesh axes stay ``auto``
  — GSPMD keeps partitioning tensor-parallel math; the Megatron
  composition). Inside, each device differentiates its LOCAL loss sum;
  the markers' psums are the only dp gradient communication in the
  program. ``grad_sync="fused"`` is the same machinery with a single
  bucket — the one-big-all-reduce baseline the HLO gate compares
  against (fused = 1 grad all-reduce, bucketed >= 2).
- **The in-scan path** (:func:`sync_scan_slice`): a remat
  scan-over-layers stack accumulates its stacked-leaf gradient across
  the *whole* scan transpose — a top-level bucket marker on those
  leaves could not fire until the last layer. The model hooks the
  marker onto the per-layer parameter slice INSIDE the scan body
  (``TransformerLM._scan_blocks``), so each layer's slice is all-reduced
  within its own backward iteration. Activated by the Trainer through
  :func:`scan_sync_scope`; a no-op everywhere else (init, eval, implicit
  mode).

Numerics: all-reduce is an elementwise sum over the same replica group,
so bucket granularity does not change any element's reduction — bucketed
and fused are bit-exact in f32 (pinned by tests/test_overlap.py on a
2-device mesh). With ``grad_accum > 1`` the Trainer accumulates LOCAL
gradients across microbatches and syncs the accumulated tree once per
optimizer step (:func:`apply_bucket_sync`) — never per microbatch.

Known semantic deltas vs the implicit GSPMD path (documented, not bugs):
module-state updates (BN running stats) and dropout masks are computed
per device shard inside the manual region — torch-DDP semantics rather
than global-batch semantics. The Trainer warns once when a non-empty
state tree meets an explicit sync mode.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import functools
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = [
    "GRAD_SYNC_SCOPE", "GRAD_SYNC_MODES", "Bucket",
    "partition_buckets", "sync_tangent", "mark_buckets",
    "apply_bucket_sync", "scan_sync_scope", "current_scan_sync",
    "sync_scan_slice", "resolve_grad_sync", "shard_map_compat",
]

# Every explicit-sync psum is traced under this jax.named_scope, so the
# compiled HLO's collectives carry scope=('grad_sync', '<tag>') metadata —
# obs.attribution classifies them as gradient all-reduces by this name
# (robust even where transform-wrapper metadata would hide the backward
# flag) and the bench gate counts them per mode.
GRAD_SYNC_SCOPE = "grad_sync"

GRAD_SYNC_MODES = (None, "bucketed", "fused")


def shard_map_compat(fn, **kw):
    """``shard_map`` across jax versions: new-style ``jax.shard_map`` with
    ``check_vma`` vs the experimental spelling with ``check_rep``. The
    manual region always disables the replication check: per-device grad
    sums are *deliberately* device-varying until the marker psums them."""
    try:
        from jax import shard_map as _sm
    except ImportError:                      # older jax
        from jax.experimental.shard_map import shard_map as _sm
    try:
        return _sm(fn, check_vma=False, **kw)
    except TypeError:                        # older jax spells it check_rep
        return _sm(fn, check_rep=False, **kw)


# ---------------------------------------------------------------------------
# bucket partition
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Bucket:
    """One gradient-sync bucket: a tag (its HLO scope suffix), the leaf
    paths it covers (slash-joined, reverse layer order), and its size."""
    tag: str
    paths: Tuple[str, ...]
    bytes: int
    dtype: str

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self) | {"paths": list(self.paths)}


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _leaf_entries(params) -> List[Tuple[str, Any]]:
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    return [(_path_str(path), leaf) for path, leaf in flat]


def partition_buckets(params, bucket_mb: float = 4.0,
                      exclude: Sequence[str] = ()) -> List[Bucket]:
    """Partition a params tree's leaves into gradient-sync buckets.

    Leaves are taken in REVERSE flatten order — the flatten order follows
    module construction (input side first), so reversing approximates the
    order the backward pass completes gradients: the first bucket closes
    (and its all-reduce can start) earliest. Consecutive leaves of one
    dtype are grouped until the bucket exceeds ``bucket_mb`` megabytes
    (a dtype change always cuts: each bucket concatenates into one flat
    psum buffer). ``exclude`` is a list of fnmatch patterns over
    slash-joined leaf paths — the leaves a model syncs in-scan
    (:func:`sync_scan_slice`) must not be double-synced by a bucket.

    Non-inexact leaves (no cotangent) are skipped. Every bucket holds at
    least one leaf, however large the leaf; ``bucket_mb`` is a budget,
    not a splitter (a single tensor is never sliced across buckets —
    slicing would forfeit the "fires when its producers finish" anchor).
    """
    if bucket_mb <= 0:
        raise ValueError(f"bucket_mb must be > 0, got {bucket_mb}")
    budget = int(bucket_mb * 2 ** 20)
    entries = _leaf_entries(params)
    buckets: List[Bucket] = []
    cur: List[Tuple[str, Any]] = []
    cur_bytes = 0

    def close():
        nonlocal cur, cur_bytes
        if cur:
            dt = str(np.dtype(cur[0][1].dtype))
            buckets.append(Bucket(
                tag=f"bucket{len(buckets)}",
                paths=tuple(p for p, _ in cur),
                bytes=cur_bytes, dtype=dt))
            cur, cur_bytes = [], 0

    for path, leaf in reversed(entries):
        dt = getattr(leaf, "dtype", None)
        if dt is None or not jnp.issubdtype(dt, jnp.inexact):
            continue
        if any(fnmatch.fnmatchcase(path, pat) for pat in exclude):
            continue
        nbytes = int(np.prod(np.shape(leaf), dtype=np.int64)) * \
            np.dtype(dt).itemsize
        if cur and (str(np.dtype(cur[0][1].dtype)) != str(np.dtype(dt))
                    or cur_bytes + nbytes > budget):
            close()
        cur.append((path, leaf))
        cur_bytes += nbytes
    close()
    return buckets


# ---------------------------------------------------------------------------
# the custom_vjp identity marker
# ---------------------------------------------------------------------------

def _flat_psum(gs: Tuple[Any, ...], axis_name, tag: str) -> Tuple[Any, ...]:
    """All-reduce a tuple of same-dtype cotangents as ONE flat buffer:
    ravel + concatenate, a single ``lax.psum`` (one HLO all-reduce — the
    per-leaf form would emit one op per leaf and hand the backend the
    same fragmented schedule we are replacing), then slice/reshape back.
    Traced under ``named_scope(grad_sync/<tag>)`` for attribution."""
    with jax.named_scope(f"{GRAD_SYNC_SCOPE}/{tag}"):
        if len(gs) == 1:
            g = gs[0]
            return (lax.psum(g, axis_name),)
        flat = [jnp.ravel(g) for g in gs]
        buf = lax.psum(jnp.concatenate(flat), axis_name)
        outs, off = [], 0
        for g, f in zip(gs, flat):
            n = int(f.shape[0])
            outs.append(jnp.reshape(lax.slice(buf, (off,), (off + n,)),
                                    jnp.shape(g)))
            off += n
        return tuple(outs)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def sync_tangent(xs: Tuple[Any, ...], axis_name, tag: str = "bucket"):
    """Identity on a tuple of arrays whose COTANGENTS are all-reduced over
    ``axis_name`` (one flat psum per call) the moment the backward has
    produced all of them. Must be traced where ``axis_name`` is bound —
    inside the Trainer's manual-dp ``shard_map`` region."""
    return xs


def _sync_fwd(xs, axis_name, tag):
    return xs, None


def _sync_bwd(axis_name, tag, _res, gs):
    return (_flat_psum(tuple(gs), axis_name, tag),)


sync_tangent.defvjp(_sync_fwd, _sync_bwd)


def mark_buckets(params, buckets: Sequence[Bucket], axis_name):
    """Wrap each bucket's leaves in one :func:`sync_tangent` marker;
    returns the same tree with marked leaves (unbucketed leaves pass
    through untouched). Applied to the params at the top of the loss
    function, so the markers' backward psums fire as the backward
    completes each bucket."""
    flat = jax.tree_util.tree_flatten_with_path(params)
    leaves = {_path_str(path): leaf for path, leaf in flat[0]}
    for b in buckets:
        marked = sync_tangent(tuple(leaves[p] for p in b.paths),
                              axis_name, b.tag)
        for p, v in zip(b.paths, marked):
            leaves[p] = v
    return jax.tree_util.tree_unflatten(
        flat[1], [leaves[_path_str(path)] for path, _ in flat[0]])


def apply_bucket_sync(grads, buckets: Sequence[Bucket], axis_name):
    """Forward (non-autodiff) bucket sync of an already-accumulated
    gradient tree — the ``grad_accum > 1`` path: local gradients are
    accumulated across microbatches and all-reduced ONCE per optimizer
    step, one psum per bucket. Same flat-buffer arithmetic as the marker
    backward, so fused-vs-bucketed stays bit-exact. Leaves outside every
    bucket pass through unsynced (the in-scan set is never routed here:
    accumulation disables in-scan marking)."""
    flat = jax.tree_util.tree_flatten_with_path(grads)
    leaves = {_path_str(path): leaf for path, leaf in flat[0]}
    for b in buckets:
        synced = _flat_psum(tuple(leaves[p] for p in b.paths),
                            axis_name, b.tag)
        for p, v in zip(b.paths, synced):
            leaves[p] = v
    return jax.tree_util.tree_unflatten(
        flat[1], [leaves[_path_str(path)] for path, _ in flat[0]])


# ---------------------------------------------------------------------------
# the in-scan sync hook (trace-time context, model side)
# ---------------------------------------------------------------------------

_tls = threading.local()


def _scan_stack() -> list:
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


class scan_sync_scope:
    """Trace-time context the Trainer opens around the model forward when
    per-layer in-scan sync should engage: ``axis_name`` is the dp axis
    (or None for an explicit no-op scope). The model's scan body asks
    :func:`current_scan_sync` / :func:`sync_scan_slice`."""

    def __init__(self, axis_name: Optional[str]):
        self.axis_name = axis_name

    def __enter__(self):
        _scan_stack().append(self.axis_name)
        return self

    def __exit__(self, *exc):
        _scan_stack().pop()
        return False


def current_scan_sync() -> Optional[str]:
    stack = _scan_stack()
    return stack[-1] if stack else None


def sync_scan_slice(tree, tag: str = "scan_layer"):
    """Model-side hook: wrap a scan body's PER-LAYER parameter slice in a
    sync marker when an in-scan scope is active (one all-reduce per layer
    iteration of the scan transpose — the remat'd stack's gradients
    participate in the overlap instead of waiting for the whole scan
    backward). Identity when no scope is active (init, eval, implicit
    sync, accumulation).

    Leaves are grouped by dtype — one marker (one flat psum) per dtype
    group, mirroring :func:`partition_buckets`' rule: the flat buffer
    cannot mix dtypes (``concatenate`` would silently promote and the
    cotangents would come back wrong-typed). Non-inexact leaves (no
    cotangent) pass through unmarked."""
    axis = current_scan_sync()
    if axis is None:
        return tree
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups: Dict[str, List[int]] = {}
    for i, leaf in enumerate(leaves):
        dt = getattr(leaf, "dtype", None)
        if dt is None or not jnp.issubdtype(dt, jnp.inexact):
            continue
        groups.setdefault(str(np.dtype(dt)), []).append(i)
    out = list(leaves)
    for dt, idxs in sorted(groups.items()):
        gtag = tag if len(groups) == 1 else f"{tag}_{dt}"
        synced = sync_tangent(tuple(out[i] for i in idxs), axis, gtag)
        for i, v in zip(idxs, synced):
            out[i] = v
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# mode resolution + graceful fallback
# ---------------------------------------------------------------------------

def resolve_grad_sync(mode: Optional[str], mesh, dp_axis: str,
                      param_specs=None) -> Tuple[Optional[str], Optional[str]]:
    """Decide whether an explicit sync mode can engage on this mesh.

    Returns ``(active_mode, reason)``: ``active_mode`` is the requested
    mode, or None with a human-readable ``reason`` when the request must
    degrade to the implicit GSPMD sync — no dp axis, a 1-device dp axis
    (nothing to sync), or parameters sharded over the dp axis itself
    (FSDP-style layouts: their "grads" are shards, not replicas; the
    implicit partitioner sync is already correct and minimal there).
    Degrading is deliberate: ``grad_sync=`` must never crash a config
    that trains fine without it."""
    if mode is None:
        return None, None
    if mode not in GRAD_SYNC_MODES:
        raise ValueError(
            f"grad_sync must be one of {GRAD_SYNC_MODES}, got {mode!r}")
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if dp_axis not in sizes:
        return None, f"mesh has no {dp_axis!r} axis (axes: {list(sizes)})"
    if sizes[dp_axis] <= 1:
        return None, f"dp axis {dp_axis!r} has a single device"
    if param_specs is not None:
        for spec in jax.tree_util.tree_leaves(
                param_specs, is_leaf=lambda x: isinstance(x, P)):
            if isinstance(spec, P) and any(
                    dp_axis == ax or (isinstance(ax, (tuple, list))
                                      and dp_axis in ax)
                    for ax in spec if ax is not None):
                return None, (f"param_sharding shards parameters over the "
                              f"dp axis {dp_axis!r} (FSDP-style layout)")
    return mode, None
