"""Microbatch pipeline parallelism over the ``pipe`` mesh axis.

The reference's closest machinery is update-during-backward overlap
(``TrainerInternal.cpp:70`` ``doPipelineUpdate``) — true pipeline
parallelism did not exist in 2017; this is a forward-looking "exceeds" item
completing the parallelism matrix (dp / tp / sp / ep / **pp**).

Scheme: GPipe-style. Each device owns one stage's parameters; microbatches
enter at stage 0, activations hop stage-to-stage with ``lax.ppermute``
(neighbor ICI transfers), and the last stage collects outputs. The schedule
is the classic ``M + S - 1`` step wavefront with bubbles at the ends;
everything is differentiable (``ppermute`` has a transpose rule), so
``jax.grad`` through the pipeline just works — the backward pass is the
reverse wavefront XLA derives automatically.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["pipeline_apply", "make_pipeline", "pipeline_loss_apply",
           "make_pipeline_loss", "pipeline_grads_1f1b",
           "make_pipeline_1f1b"]


def _wavefront(stage_fn: Callable, stage_params, x, axis_name: str,
               axis_size: int, comm_dtype=None):
    """The shared GPipe M + S - 1-tick wavefront — call INSIDE shard_map.
    Runs microbatches through the stage ring (ppermute hops) and returns
    the last stage's [M, ...] output buffer (meaningful ONLY on stage
    S-1; other devices hold zeros/garbage). ``comm_dtype`` (e.g. bf16)
    compresses the hop wire; local compute and the output buffer keep the
    stage dtype."""
    S = axis_size
    stage = lax.axis_index(axis_name)
    M = x.shape[0]
    if comm_dtype is not None:
        x = x.astype(comm_dtype)

    def body(t, carry):
        act, outbuf = carry
        # stage 0 injects microbatch t (zeros past the end — bubble)
        inject = jnp.where(t < M, x[jnp.clip(t, 0, M - 1)],
                           jnp.zeros_like(x[0]))
        act_in = jnp.where(stage == 0, inject, act)
        y = stage_fn(stage_params, act_in)
        # hop to the next stage around the ring; optimization_barrier
        # pins the downcast to the send side (XLA otherwise reorders
        # convert across the collective and cancels it)
        send = y if comm_dtype is None \
            else lax.optimization_barrier(y.astype(comm_dtype))
        act_next = lax.ppermute(send, axis_name,
                                [(i, (i + 1) % S) for i in range(S)])
        # the last stage finishes microbatch m = t - (S - 1)
        m = t - (S - 1)
        write = (stage == S - 1) & (m >= 0) & (m < M)
        outbuf = jnp.where(write,
                           outbuf.at[jnp.clip(m, 0, M - 1)].set(y),
                           outbuf)
        return act_next, outbuf

    # Derive the buffers from the (device-varying) probe output so they
    # carry the pipe axis in their varying-axes set — plain zeros constants
    # would trip shard_map's carry check (same trick as ring.py's
    # accumulators).
    y0 = stage_fn(stage_params, x[0])      # shape probe for buffers
    act0 = y0 * 0.0 if comm_dtype is None else (y0 * 0.0).astype(comm_dtype)
    outbuf0 = jnp.broadcast_to((y0 * 0.0)[None], (M,) + y0.shape)
    _, outbuf = lax.fori_loop(0, M + S - 1, body, (act0, outbuf0))
    return outbuf


def pipeline_apply(stage_fn: Callable, stage_params, x, axis_name: str,
                   axis_size: int):
    """Run the S-stage pipeline — call INSIDE shard_map.

    ``stage_params``: THIS device's stage parameters (the [S, ...] stack
    sharded over ``axis_name``, leading axis squeezed). ``x``: the full
    microbatch stack [M, mb, ...], replicated (only stage 0 reads it).
    Returns the final outputs [M, mb, ...] (replicated via a psum
    broadcast from the last stage — a full-tensor sync; for TRAINING use
    :func:`pipeline_loss_apply`, which closes the loss on the last stage
    and syncs only a scalar).
    """
    outbuf = _wavefront(stage_fn, stage_params, x, axis_name, axis_size)
    stage = lax.axis_index(axis_name)
    # broadcast the last stage's buffer to every device. jnp.where, not a
    # multiplicative mask: non-finite values in a bubble device's buffer
    # would poison the psum through NaN * 0 == NaN.
    sel = jnp.where(stage == axis_size - 1, outbuf, jnp.zeros_like(outbuf))
    return lax.psum(sel, axis_name)


def make_pipeline(mesh: Mesh, stage_fn: Callable, pipe_axis: str = "pipe"):
    """Wrap :func:`pipeline_apply` in shard_map over ``mesh``.

    Takes GLOBAL arrays: ``stage_params`` with a leading [S, ...] stage axis
    (sharded over ``pipe_axis``) and microbatches ``x [M, mb, ...]``
    (replicated). ``stage_fn(params_one_stage, act)`` must keep the
    activation shape (homogeneous pipeline; the usual transformer-stack
    case)."""
    try:
        from jax import shard_map
    except ImportError:            # older jax
        from jax.experimental.shard_map import shard_map

    S = dict(zip(mesh.axis_names, mesh.devices.shape))[pipe_axis]

    def inner(stage_params, x):
        def squeeze(a):
            assert a.shape[0] == 1, (
                f"stage stack must have exactly {S} stages (the pipe-axis "
                f"size); got a shard of {a.shape[0]} stages per device")
            return a[0]
        squeezed = jax.tree_util.tree_map(squeeze, stage_params)
        return pipeline_apply(stage_fn, squeezed, x, pipe_axis, S)

    return shard_map(
        inner, mesh=mesh,
        in_specs=(P(pipe_axis), P()),
        out_specs=P())


def pipeline_loss_apply(stage_fn: Callable, stage_params, x,
                        final_fn: Callable, final_params, extras,
                        axis_name: str, axis_size: int, reduce_axes=(),
                        comm_dtype=None):
    """GPipe wavefront + ON-LAST-STAGE loss — call INSIDE shard_map.

    Same wavefront as :func:`pipeline_apply`, but instead of broadcasting
    the completed [M, mb, ...] output stack to every stage (a full-tensor
    ``psum`` over the pipe axis — measured 1.07 GB/step of wire for a
    d1024 LM, experiments/scaling_projection.py r5), the loss closes on
    the last stage: ``final_fn(final_params, outbuf, *extras)`` maps the
    stack to a scalar, non-last stages contribute zero, and only the
    SCALAR crosses the wire. Every device traces ``final_fn`` (bubble
    devices run it on zeros — wasted FLOPs that overlap the bubble, no
    wire); grads for ``stage_params``, ``final_params``, and ``x`` all
    flow (the masked psum routes the cotangent to the last stage)."""
    outbuf = _wavefront(stage_fn, stage_params, x, axis_name, axis_size,
                        comm_dtype=comm_dtype)
    stage = lax.axis_index(axis_name)
    S = axis_size
    # Double-where, not val * mask: bubble devices would run final_fn on a
    # zero buffer, and a non-finite val there (0/0 counts, log 0, ...)
    # poisons the psum through NaN * 0 == NaN — and even with an outer
    # where, the BACKWARD multiplies the zeroed cotangent into final_fn's
    # inf/NaN partials (0 * inf == NaN again). So bubble devices evaluate
    # final_fn on a safe all-ones buffer (finite value AND finite partials
    # for the 0/0-normalisation class), and the outer where discards it.
    is_last = stage == S - 1
    safe = jnp.where(is_last, outbuf, jnp.ones_like(outbuf))
    val = final_fn(final_params, safe, *extras)
    sel = jnp.where(is_last, val, jnp.zeros_like(val))
    # reduce_axes: batch-sharding axes of x/extras (dp x pp) whose partial
    # losses must also sum into the global scalar
    return lax.psum(sel, (axis_name,) + tuple(reduce_axes))


def make_pipeline_loss(mesh: Mesh, stage_fn: Callable, final_fn: Callable,
                       pipe_axis: str = "pipe", x_spec: P = P(),
                       extra_specs=(), reduce_axes=(), comm_dtype=None):
    """Wrap :func:`pipeline_loss_apply` in shard_map over ``mesh``.

    Returns ``fn(stage_params, final_params, x, *extras) -> scalar``.
    ``stage_params``: [S, ...] stacks sharded over ``pipe_axis``;
    ``final_params``: replicated pytree consumed by the last-stage loss
    (head weights, tied embeddings — its grads psum over the mesh);
    ``x``: the [M, mb, ...] microbatch stack (``x_spec`` may shard mb over
    a data axis for dp x pp — name that axis in ``reduce_axes`` so the
    per-group partial losses sum into the global scalar); ``extras``:
    per-microbatch aux arrays (targets, masks) with specs
    ``extra_specs``."""
    try:
        from jax import shard_map
    except ImportError:            # older jax
        from jax.experimental.shard_map import shard_map

    S = dict(zip(mesh.axis_names, mesh.devices.shape))[pipe_axis]

    def inner(stage_params, final_params, x, *extras):
        def squeeze(a):
            assert a.shape[0] == 1, (
                f"stage stack must have exactly {S} stages (the pipe-axis "
                f"size); got a shard of {a.shape[0]} stages per device")
            return a[0]
        squeezed = jax.tree_util.tree_map(squeeze, stage_params)
        return pipeline_loss_apply(stage_fn, squeezed, x, final_fn,
                                   final_params, extras, pipe_axis, S,
                                   reduce_axes=reduce_axes,
                                   comm_dtype=comm_dtype)

    return shard_map(
        inner, mesh=mesh,
        in_specs=(P(pipe_axis), P(), x_spec) + tuple(extra_specs),
        out_specs=P())


def _call_loss(loss_fn, out, mb_index):
    """loss_fn may take (out) or (out, microbatch_index) — the index form
    lets per-microbatch targets live in closure arrays."""
    import inspect
    try:
        n = len(inspect.signature(loss_fn).parameters)
    except (TypeError, ValueError):
        n = 1
    return loss_fn(out, mb_index) if n >= 2 else loss_fn(out)


def pipeline_grads_1f1b(stage_fn: Callable, loss_fn: Callable, stage_params,
                        x, axis_name: str, axis_size: int):
    """One-forward-one-backward (1F1B) training schedule — call INSIDE
    shard_map. Returns ``(total_loss, param_grads)`` for
    ``sum_m loss_fn(pipeline(x[m]))``.

    Where GPipe (``pipeline_apply`` + ``jax.grad``) runs all M forwards then
    all M backwards and therefore holds M microbatches of saved activations
    per stage, 1F1B interleaves: on the standard half-step grid, stage ``s``
    runs forward #i at t = s + 2i and backward #i at t = 2S-1-s + 2i, so at
    most S microbatches are ever in flight and the stage-input buffer is a
    fixed ``[S, ...]`` ring regardless of M — the schedule that makes
    M >> S gradient accumulation memory-feasible. The backward recomputes
    the stage forward from its saved INPUT (`jax.vjp` at backward time):
    boundary-only saving + in-stage rematerialisation, the standard
    memory/FLOP trade.

    Compute cost: off-tick events are skipped via ``lax.cond`` (HLO
    conditional), so each device executes exactly M forwards and M
    recompute-vjp passes over the whole schedule — the ideal 1F1B budget
    plus the rematerialisation forward, NOT ``T = 2M+2S-2`` copies of each
    (the pre-round-4 version ran every event on every tick and masked the
    results, ~3x the FLOPs).
    """
    S = axis_size
    stage = lax.axis_index(axis_name)
    M = x.shape[0]
    T = 2 * M + 2 * S - 2                     # last event: t = 2M + 2S - 3
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]

    # Probes derive every buffer from device-varying values (shard_map
    # varying-axes rule, same trick as pipeline_apply / ring.py).
    y0 = stage_fn(stage_params, x[0])
    act0 = y0 * 0.0                               # inter-stage activation
    cot0 = y0 * 0.0                               # inter-stage cotangent
    abuf0 = jnp.broadcast_to((y0 * 0.0)[None], (S,) + y0.shape)
    grad0 = jax.tree_util.tree_map(jnp.zeros_like, stage_params)
    loss0 = jnp.sum(y0) * 0.0

    def body(t, carry):
        fwd_act, bwd_cot, abuf, gacc, lacc = carry

        # Off-tick events SKIP their stage compute via lax.cond (HLO
        # conditional executes one branch): per tick a device runs at most
        # one forward and one recompute-vjp, so total stage executions are
        # M fwd + M recompute + M bwd per device — the ideal 1F1B compute
        # budget — not T = 2M+2S-2 of each. The predicates are
        # device-varying (stage enters them), which shard_map's varying-axes
        # tracking allows for collective-free branches; the ppermute hops
        # stay outside, executed by every device every tick.

        # -- forward event: t == stage + 2*fi -------------------------------
        df = t - stage
        fi = df // 2
        fwd_on = (df >= 0) & (df % 2 == 0) & (fi < M)
        f_in = jnp.where(stage == 0, x[jnp.clip(fi, 0, M - 1)], fwd_act)

        def do_fwd(abuf):
            return stage_fn(stage_params, f_in), abuf.at[fi % S].set(f_in)

        send_f, abuf = lax.cond(fwd_on, do_fwd,
                                lambda abuf: (act0, abuf), abuf)

        # -- backward event: t == 2S-1-stage + 2*bi -------------------------
        db = t - (2 * S - 1 - stage)
        bi = db // 2
        bwd_on = (db >= 0) & (db % 2 == 0) & (bi < M)
        b_in = abuf[jnp.clip(bi, 0, M - 1) % S]

        def fwd_loss(p, a):
            out = stage_fn(p, a)
            # last stage closes the loss; others forward the cotangent.
            # loss_fn takes (out_mb, mb_index) so per-microbatch targets
            # (labels, masks) can be indexed from closure state.
            l = _call_loss(loss_fn, out, jnp.clip(bi, 0, M - 1))
            return jnp.where(stage == S - 1, l, jnp.sum(out * bwd_cot)), l

        def do_bwd(_):
            val, vjp, l = jax.vjp(fwd_loss, stage_params, b_in, has_aux=True)
            dparams, dact = vjp(jnp.ones_like(val))
            return dparams, dact, l

        dparams, send_b, l = lax.cond(
            bwd_on, do_bwd, lambda _: (grad0, cot0, loss0), None)
        gacc = jax.tree_util.tree_map(lambda g, d: g + d, gacc, dparams)
        lacc = lacc + jnp.where(stage == S - 1, l, 0.0)

        # -- hops ------------------------------------------------------------
        fwd_act_next = lax.ppermute(send_f, axis_name, fwd_perm)
        bwd_cot_next = lax.ppermute(send_b, axis_name, bwd_perm)
        return fwd_act_next, bwd_cot_next, abuf, gacc, lacc

    _, _, _, grads, loss = lax.fori_loop(
        0, T, body, (act0, cot0, abuf0, grad0, loss0))
    return lax.psum(loss, axis_name), grads


def make_pipeline_1f1b(mesh: Mesh, stage_fn: Callable, loss_fn: Callable,
                       pipe_axis: str = "pipe"):
    """Wrap :func:`pipeline_grads_1f1b` in shard_map over ``mesh``.

    Takes GLOBAL arrays (``stage_params`` [S, ...] sharded over
    ``pipe_axis``; ``x`` [M, mb, ...] replicated) and returns
    ``(total_loss, grads)`` with grads in the same stage-stacked sharded
    layout as the params — ready for any :mod:`paddle_tpu.optim` rule.
    ``loss_fn(out_mb) -> scalar`` is the per-microbatch loss applied at the
    last stage (sum-reduced over microbatches)."""
    try:
        from jax import shard_map
    except ImportError:            # older jax
        from jax.experimental.shard_map import shard_map

    S = dict(zip(mesh.axis_names, mesh.devices.shape))[pipe_axis]

    def inner(stage_params, x):
        squeezed = jax.tree_util.tree_map(lambda a: a[0], stage_params)
        loss, grads = pipeline_grads_1f1b(stage_fn, loss_fn, squeezed, x,
                                          pipe_axis, S)
        return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

    return shard_map(inner, mesh=mesh, in_specs=(P(pipe_axis), P()),
                     out_specs=(P(), P(pipe_axis)))
