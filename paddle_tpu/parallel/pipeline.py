"""Microbatch pipeline parallelism over the ``pipe`` mesh axis.

The reference's closest machinery is update-during-backward overlap
(``TrainerInternal.cpp:70`` ``doPipelineUpdate``) — true pipeline
parallelism did not exist in 2017; this is a forward-looking "exceeds" item
completing the parallelism matrix (dp / tp / sp / ep / **pp**).

Scheme: GPipe-style. Each device owns one stage's parameters; microbatches
enter at stage 0, activations hop stage-to-stage with ``lax.ppermute``
(neighbor ICI transfers), and the last stage collects outputs. The schedule
is the classic ``M + S - 1`` step wavefront with bubbles at the ends;
everything is differentiable (``ppermute`` has a transpose rule), so
``jax.grad`` through the pipeline just works — the backward pass is the
reverse wavefront XLA derives automatically.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["pipeline_apply", "make_pipeline"]


def pipeline_apply(stage_fn: Callable, stage_params, x, axis_name: str,
                   axis_size: int):
    """Run the S-stage pipeline — call INSIDE shard_map.

    ``stage_params``: THIS device's stage parameters (the [S, ...] stack
    sharded over ``axis_name``, leading axis squeezed). ``x``: the full
    microbatch stack [M, mb, ...], replicated (only stage 0 reads it).
    Returns the final outputs [M, mb, ...] (replicated via a psum
    broadcast from the last stage).
    """
    S = axis_size
    stage = lax.axis_index(axis_name)
    M = x.shape[0]

    def body(t, carry):
        act, outbuf = carry
        # stage 0 injects microbatch t (zeros past the end — bubble)
        inject = jnp.where(t < M, x[jnp.clip(t, 0, M - 1)],
                           jnp.zeros_like(x[0]))
        act_in = jnp.where(stage == 0, inject, act)
        y = stage_fn(stage_params, act_in)
        # hop to the next stage around the ring
        act_next = lax.ppermute(y, axis_name,
                                [(i, (i + 1) % S) for i in range(S)])
        # the last stage finishes microbatch m = t - (S - 1)
        m = t - (S - 1)
        write = (stage == S - 1) & (m >= 0) & (m < M)
        outbuf = jnp.where(write,
                           outbuf.at[jnp.clip(m, 0, M - 1)].set(y),
                           outbuf)
        return act_next, outbuf

    # Derive the buffers from the (device-varying) probe output so they
    # carry the pipe axis in their varying-axes set — plain zeros constants
    # would trip shard_map's carry check (same trick as ring.py's
    # accumulators).
    y0 = stage_fn(stage_params, x[0])      # shape probe for buffers
    act0 = y0 * 0.0
    outbuf0 = jnp.broadcast_to((y0 * 0.0)[None], (M,) + y0.shape)
    _, outbuf = lax.fori_loop(0, M + S - 1, body, (act0, outbuf0))
    # broadcast the last stage's buffer to every device
    mask = (stage == S - 1).astype(outbuf.dtype)
    return lax.psum(outbuf * mask, axis_name)


def make_pipeline(mesh: Mesh, stage_fn: Callable, pipe_axis: str = "pipe"):
    """Wrap :func:`pipeline_apply` in shard_map over ``mesh``.

    Takes GLOBAL arrays: ``stage_params`` with a leading [S, ...] stage axis
    (sharded over ``pipe_axis``) and microbatches ``x [M, mb, ...]``
    (replicated). ``stage_fn(params_one_stage, act)`` must keep the
    activation shape (homogeneous pipeline; the usual transformer-stack
    case)."""
    try:
        from jax import shard_map
    except ImportError:            # older jax
        from jax.experimental.shard_map import shard_map

    S = dict(zip(mesh.axis_names, mesh.devices.shape))[pipe_axis]

    def inner(stage_params, x):
        def squeeze(a):
            assert a.shape[0] == 1, (
                f"stage stack must have exactly {S} stages (the pipe-axis "
                f"size); got a shard of {a.shape[0]} stages per device")
            return a[0]
        squeezed = jax.tree_util.tree_map(squeeze, stage_params)
        return pipeline_apply(stage_fn, squeezed, x, pipe_axis, S)

    return shard_map(
        inner, mesh=mesh,
        in_specs=(P(pipe_axis), P()),
        out_specs=P())
