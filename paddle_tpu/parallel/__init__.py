"""Model / tensor / sequence parallelism over the device mesh.

The reference's model parallelism is ``ParallelNeuralNetwork`` — layers pinned
to devices, executed by per-device worker threads with task queues
(``/root/reference/paddle/gserver/gradientmachines/ParallelNeuralNetwork.h:36-53``)
— and its collectives are NCCL ops (``paddle/operators/nccl_op.cc:66``).
TPU-native, both collapse into *sharding annotations*: parameters are laid out
over mesh axes with :mod:`jax.sharding`, XLA's SPMD partitioner inserts the
all-gathers/reduce-scatters over ICI, and one jit'd step runs everywhere.

This package owns:
  - :mod:`.sharding` — pattern-based parameter sharding rules and helpers to
    build sharded train states (``ShardingRules``, ``sharded_init``).
  - :mod:`.ring` — ring attention over ``ppermute`` for the ``seq`` mesh axis
    (sequence/context parallelism; exceeds the 2017 reference, SURVEY.md §5).
  - :mod:`.overlap` — bucketed gradient-sync overlap: explicit, byte-budgeted
    per-bucket grad all-reduces anchored inside the backward pass
    (``Trainer(grad_sync="bucketed")``; the pserver gradient-pipelining
    story, XLA-era).
"""

from .sharding import (ShardingRules, spec_tree, named_shardings,
                       shard_tree, sharded_init, tp_shard_scope,
                       current_tp_shard, tp_constrain)
from .overlap import (Bucket, partition_buckets, sync_tangent,
                      mark_buckets, apply_bucket_sync, sync_scan_slice,
                      scan_sync_scope, resolve_grad_sync)
from .ring import ring_attention, make_ring_attention
from .ulysses import ulysses_attention, make_ulysses_attention
from .multihost import (initialize, is_initialized,
                        host_sharded_reader, multihost_mesh,
                        HostHeartbeat, detect_dead_hosts, plan_reform,
                        reform, ReformPlan)
from .pipeline import (pipeline_apply, make_pipeline,
                       pipeline_loss_apply, make_pipeline_loss,
                       pipeline_grads_1f1b, make_pipeline_1f1b)
from .megatron import megatron_sp_rules, make_megatron_sp_lm_apply

__all__ = [
    "ShardingRules", "spec_tree", "named_shardings", "shard_tree",
    "sharded_init", "tp_shard_scope", "current_tp_shard", "tp_constrain",
    "ring_attention", "make_ring_attention",
    "ulysses_attention", "make_ulysses_attention", "initialize",
    "pipeline_apply", "make_pipeline", "pipeline_grads_1f1b",
    "make_pipeline_1f1b", "pipeline_loss_apply", "make_pipeline_loss",
    "megatron_sp_rules", "make_megatron_sp_lm_apply",
    "is_initialized", "host_sharded_reader", "multihost_mesh",
    "HostHeartbeat", "detect_dead_hosts", "plan_reform", "reform",
    "ReformPlan",
    "Bucket", "partition_buckets", "sync_tangent", "mark_buckets",
    "apply_bucket_sync", "sync_scan_slice", "scan_sync_scope",
    "resolve_grad_sync",
]
