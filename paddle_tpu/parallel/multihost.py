"""Multi-host orchestration — the TPU-native replacement for the reference's
pserver/etcd tier (``go/pserver``, ``go/master``; C++
``pserver/ParameterServer2``).

On TPU pods there is no parameter-server tier to run: every host executes the
same SPMD program and XLA moves gradients over ICI/DCN. What remains of the
reference's distributed stack is exactly three host-side concerns, and this
module wires them together:

  1. process bring-up — :func:`initialize` (the ``paddle.init(trainer_id,
     pservers=...)`` analog) over ``jax.distributed``;
  2. disjoint data feeding — :func:`host_sharded_reader` (the Go master's
     task-queue role, done as deterministic modulo sharding);
  3. single-writer checkpoints — already enforced by
     ``train.checkpoint.save_checkpoint`` (process 0 writes, everyone loads).

Recovery model: JAX jobs are gang-scheduled, so elastic recovery =
restart-all + ``Trainer(..., resume=True)`` from the shared checkpoint dir —
the capability the reference implements with etcd leases and task requeue
(``go/master/service.go:313``), collapsed into deterministic data + CRC'd
checkpoints.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

import jax

from ..core import mesh as mesh_lib
from ..data import reader as reader_lib

__all__ = ["initialize", "is_initialized", "host_sharded_reader",
           "multihost_mesh"]

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bring up the JAX distributed runtime (the ``paddle.init`` /
    ``--trainer_id --pservers`` analog, ``v2/__init__.py:119``).

    No-op when running single-process (the common case in tests and on a
    single host) — call unconditionally at program start. Arguments default
    to the standard env vars (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``) so launch scripts stay
    config-only.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None:
        return                      # single-process run; nothing to do
    kw = {}
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    jax.distributed.initialize(coordinator_address, **kw)
    _initialized = True


def is_initialized() -> bool:
    return _initialized


def host_sharded_reader(reader_fn: Callable) -> Callable:
    """Give this host its disjoint slice of the global stream (the Go
    master's GetTask role): ``sharded(reader, host_count, host_id)`` with the
    live process topology."""
    return reader_lib.sharded(reader_fn, mesh_lib.host_count(),
                              mesh_lib.host_id())


def multihost_mesh(**axis_sizes) -> "mesh_lib.Mesh":
    """Build a mesh spanning every device of every host (``make_mesh`` with
    the global device set — on a pod slice ``jax.devices()`` already includes
    remote-host devices). Axis sizes follow ``core.mesh.make_mesh``; the
    ``data`` axis defaults to all devices."""
    return mesh_lib.make_mesh(axis_sizes or {mesh_lib.DATA_AXIS: -1})
