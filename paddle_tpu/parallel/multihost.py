"""Multi-host orchestration — the TPU-native replacement for the reference's
pserver/etcd tier (``go/pserver``, ``go/master``; C++
``pserver/ParameterServer2``).

On TPU pods there is no parameter-server tier to run: every host executes the
same SPMD program and XLA moves gradients over ICI/DCN. What remains of the
reference's distributed stack is exactly three host-side concerns, and this
module wires them together:

  1. process bring-up — :func:`initialize` (the ``paddle.init(trainer_id,
     pservers=...)`` analog) over ``jax.distributed``;
  2. disjoint data feeding — :func:`host_sharded_reader` (the Go master's
     task-queue role, done as deterministic modulo sharding);
  3. single-writer checkpoints — already enforced by
     ``train.checkpoint.save_checkpoint`` (process 0 writes, everyone loads).

Recovery model: JAX jobs are gang-scheduled, so elastic recovery =
restart-all + ``Trainer(..., resume=True)`` from the shared checkpoint dir —
the capability the reference implements with etcd leases and task requeue
(``go/master/service.go:313``), collapsed into deterministic data + CRC'd
checkpoints. ISSUE 10 adds the missing watchdog half: per-host heartbeat
files under the (shared) checkpoint root (:class:`HostHeartbeat` — the
etcd-lease analog done as mtime'd files on the storage every host already
mounts), :func:`detect_dead_hosts` to probe them, and :func:`plan_reform`
/ :func:`reform` to rebuild the mesh and data sharding over the SURVIVING
host count, so a restart-all after host loss resumes with fewer replicas
instead of hanging on the dead host's collectives.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import jax

from ..core import mesh as mesh_lib
from ..data import reader as reader_lib

__all__ = ["initialize", "is_initialized", "host_sharded_reader",
           "multihost_mesh", "HostHeartbeat", "heartbeat_path",
           "write_heartbeat", "read_heartbeats", "retire_heartbeat",
           "detect_dead_hosts",
           "ReformPlan", "plan_reform", "reform"]

_log = logging.getLogger("paddle_tpu.multihost")

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bring up the JAX distributed runtime (the ``paddle.init`` /
    ``--trainer_id --pservers`` analog, ``v2/__init__.py:119``).

    No-op when running single-process (the common case in tests and on a
    single host) — call unconditionally at program start. Arguments default
    to the standard env vars (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``) so launch scripts stay
    config-only.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None:
        return                      # single-process run; nothing to do
    kw = {}
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    jax.distributed.initialize(coordinator_address, **kw)
    _initialized = True


def is_initialized() -> bool:
    return _initialized


def host_sharded_reader(reader_fn: Callable) -> Callable:
    """Give this host its disjoint slice of the global stream (the Go
    master's GetTask role): ``sharded(reader, host_count, host_id)`` with the
    live process topology."""
    return reader_lib.sharded(reader_fn, mesh_lib.host_count(),
                              mesh_lib.host_id())


def multihost_mesh(**axis_sizes) -> "mesh_lib.Mesh":
    """Build a mesh spanning every device of every host (``make_mesh`` with
    the global device set — on a pod slice ``jax.devices()`` already includes
    remote-host devices). Axis sizes follow ``core.mesh.make_mesh``; the
    ``data`` axis defaults to all devices."""
    return mesh_lib.make_mesh(axis_sizes or {mesh_lib.DATA_AXIS: -1})


# ---------------------------------------------------------------------------
# dead-host detection + reformed-mesh restart (ISSUE 10)
# ---------------------------------------------------------------------------

HEARTBEAT_DIRNAME = "heartbeats"


def heartbeat_path(root: str, host_id: int) -> str:
    return os.path.join(root, HEARTBEAT_DIRNAME, f"host-{host_id:05d}.json")


def write_heartbeat(root: str, host_id: Optional[int] = None,
                    seq: int = 0, now: Optional[float] = None,
                    extra: Optional[Dict] = None) -> str:
    """Write one heartbeat file atomically (tmp + rename, the checkpoint
    writer's recipe — a reader never sees a torn beat). The payload
    carries provenance a watchdog can act on: host id, PID, wall-clock
    ``ts``, and a monotonically increasing ``seq`` (distinguishes a live
    host whose clock skews from a dead host whose file merely exists).
    ``extra`` merges additional JSON-safe payload fields (reserved keys
    win) — the serving fleet rides its per-replica load report on the
    beat, so a cross-process router could balance on the same evidence
    it health-checks."""
    host_id = mesh_lib.host_id() if host_id is None else int(host_id)
    path = heartbeat_path(root, host_id)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = dict(extra or {})
    payload.update({"host_id": host_id, "pid": os.getpid(),
                    "ts": time.time() if now is None else float(now),
                    "seq": int(seq)})
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


def retire_heartbeat(root: str, host_id: int) -> Optional[str]:
    """Retire a released/dead host's heartbeat file (ISSUE 13 satellite):
    RENAME it aside (``host-NNNNN.json.retired``, a fresh numbered
    suffix when one already exists — the PR-10 quarantine rule, never
    delete), so :func:`detect_dead_hosts` and any watchdog scanning the
    root stop re-reporting a ghost that left the fleet on purpose.
    Returns the retired path (None when there was no beat to retire).
    The bytes stay on disk for forensics; ``read_heartbeats`` skips the
    suffix by construction (it only reads ``host-*.json``)."""
    path = heartbeat_path(root, host_id)
    if not os.path.exists(path):
        return None
    dest = f"{path}.retired"
    n = 0
    while os.path.exists(dest):
        n += 1
        dest = f"{path}.retired.{n}"
    try:
        os.replace(path, dest)
    except OSError:
        _log.exception("failed to retire heartbeat %s", path)
        return None
    return dest


def read_heartbeats(root: str) -> Dict[int, Dict]:
    """All readable heartbeat payloads under ``root``, keyed by host id,
    each annotated with ``_mtime`` (the heartbeat FILE's mtime — the
    shared filesystem's clock, stamped by the storage, not by the
    writer's possibly-skewed wall clock). Torn/garbled files are skipped
    (the writer is atomic; garbage means a foreign file), missing dir
    means no beats yet."""
    d = os.path.join(root, HEARTBEAT_DIRNAME)
    if not os.path.isdir(d):
        return {}
    out: Dict[int, Dict] = {}
    for name in sorted(os.listdir(d)):
        if not (name.startswith("host-") and name.endswith(".json")):
            continue
        path = os.path.join(d, name)
        try:
            with open(path) as f:
                payload = json.load(f)
            payload["_mtime"] = os.path.getmtime(path)
            out[int(payload["host_id"])] = payload
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out


def detect_dead_hosts(root: str, timeout_s: float,
                      expected_hosts: Optional[Sequence[int]] = None,
                      now: Optional[float] = None) -> List[int]:
    """Hosts whose heartbeat is stale (older than ``timeout_s``) or
    missing entirely. ``expected_hosts`` defaults to every host that has
    EVER beaten under ``root`` (a host that never joined can't be
    declared dead from silence alone); pass the known topology — e.g.
    ``range(host_count())`` — to also catch never-joined hosts.

    Clock provenance: in production (``now=None``) staleness is the
    heartbeat FILE's mtime vs this reader's clock — ONE clock pair per
    reader, uniform across every host's file, so a beating host with a
    skewed wall clock can never be declared dead (its payload ``ts``
    would be skewed; the storage-stamped mtime is not). With an explicit
    ``now`` (deterministic tests, offline log analysis) the payload
    ``ts`` is compared instead."""
    beats = read_heartbeats(root)
    use_payload_ts = now is not None
    now = time.time() if now is None else float(now)

    def age(b):
        return now - float(b["ts"] if use_payload_ts else b["_mtime"])

    expected = (sorted(beats) if expected_hosts is None
                else sorted(int(h) for h in expected_hosts))
    return [h for h in expected
            if h not in beats or age(beats[h]) > timeout_s]


class HostHeartbeat:
    """Keep this host's heartbeat file fresh on a daemon thread (the etcd
    lease analog: liveness = a recent write to storage every peer can
    read). ``start()`` beats immediately then every ``interval_s``;
    ``stop()`` joins the thread. The resilience supervisor starts one per
    supervised run (``run_resilient(heartbeat_interval_s=...)``); a
    standalone watchdog combines :func:`detect_dead_hosts` +
    :func:`plan_reform`."""

    def __init__(self, root: str, interval_s: float = 10.0,
                 host_id: Optional[int] = None):
        self.root = root
        self.interval_s = float(interval_s)
        self.host_id = mesh_lib.host_id() if host_id is None else int(host_id)
        self.seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> str:
        self.seq += 1
        return write_heartbeat(self.root, self.host_id, seq=self.seq)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.beat()
            except OSError:
                # a missed beat must never kill training — the watchdog
                # treats staleness as the signal, and one blip is below
                # any sane timeout
                _log.exception("heartbeat write failed (host %d)",
                               self.host_id)
            self._stop.wait(self.interval_s)

    def start(self) -> "HostHeartbeat":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"paddle_tpu.multihost.heartbeat-{self.host_id}")
            self._thread.start()
        return self

    def stop(self, join_timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


@dataclasses.dataclass
class ReformPlan:
    """The surviving topology after host loss: who is alive, who is
    dead, and the contiguous re-ranking the restart uses. Built by
    :func:`plan_reform` from heartbeat evidence; consumed after
    restart-all (the new, smaller process set re-initializes
    ``jax.distributed`` with ``num_processes=len(survivors)`` and each
    survivor's ``process_id=rank_of[old_id]``)."""
    survivors: List[int]
    dead: List[int]
    rank_of: Dict[int, int]

    @property
    def host_count(self) -> int:
        return len(self.survivors)

    def sharded_reader(self, reader_fn: Callable,
                       host_id: Optional[int] = None) -> Callable:
        """This survivor's disjoint slice of the global stream under the
        REFORMED topology — ``sharded(reader, survivors, new_rank)``.
        The shard boundaries move (items a dead host would have read are
        redistributed), which is exactly the elastic-resume semantics:
        fewer replicas, full coverage."""
        host_id = mesh_lib.host_id() if host_id is None else int(host_id)
        if host_id not in self.rank_of:
            raise ValueError(
                f"host {host_id} is not a survivor of this reform "
                f"(survivors: {self.survivors}, dead: {self.dead})")
        return reader_lib.sharded(reader_fn, self.host_count,
                                  self.rank_of[host_id])

    def mesh(self, **axis_sizes) -> "mesh_lib.Mesh":
        """Mesh over the surviving processes' devices. Call AFTER
        restart-all: ``jax.devices()`` then spans exactly the survivors,
        and the default ``data`` axis absorbs the smaller device count
        (fewer dp replicas, same program)."""
        return mesh_lib.make_mesh(axis_sizes or {mesh_lib.DATA_AXIS: -1})


def plan_reform(root: str, timeout_s: float,
                expected_hosts: Optional[Sequence[int]] = None,
                now: Optional[float] = None) -> ReformPlan:
    """Decide the post-loss topology from heartbeat evidence: dead =
    stale/missing beats, survivors = the rest, re-ranked contiguously in
    old-host-id order (deterministic — every survivor computes the same
    plan from the same files, no coordinator needed)."""
    beats = read_heartbeats(root)
    expected = (sorted(beats) if expected_hosts is None
                else sorted(int(h) for h in expected_hosts))
    dead = set(detect_dead_hosts(root, timeout_s,
                                 expected_hosts=expected, now=now))
    survivors = [h for h in expected if h not in dead]
    if not survivors:
        raise RuntimeError(
            f"no surviving hosts under {root} (expected {expected}, all "
            f"heartbeats stale past {timeout_s}s)")
    return ReformPlan(survivors=survivors, dead=sorted(dead),
                      rank_of={h: r for r, h in enumerate(survivors)})


def reform(root: str, timeout_s: float = 60.0,
           expected_hosts: Optional[Sequence[int]] = None,
           **axis_sizes):
    """One-call reformed-mesh restart for a survivor: probe heartbeats,
    plan the smaller topology, and return ``(mesh, plan)`` — the mesh
    over the surviving devices plus the plan whose ``sharded_reader``
    re-shards the data stream. Combine with ``Trainer(mesh=mesh,
    resume=True)`` over the shared checkpoint dir for the full
    restart-all recovery: fewer replicas, same training state."""
    plan = plan_reform(root, timeout_s, expected_hosts=expected_hosts)
    if plan.dead:
        _log.warning(
            "reforming mesh without dead hosts %s: %d -> %d hosts (data "
            "re-sharded over the survivors)", plan.dead,
            len(plan.survivors) + len(plan.dead), len(plan.survivors))
    return plan.mesh(**axis_sizes), plan

