"""Iteration-level (continuous-batching) request scheduling — the Orca
move (PAPERS.md [S2]): scheduling decisions happen between DECODE TICKS,
not between whole requests.

Classic static batching gangs requests: a batch runs until its LONGEST
member finishes, so every short request's slot sits idle (masked lanes
burning a full tick's work) while the straggler decodes. Iteration-level
scheduling admits a queued request into a slot the moment one frees and
evicts a finished request the moment its last token lands — the decode
tick's fixed ``[S]`` shape never changes (the engine's active mask
absorbs churn), so the scheduler is pure host bookkeeping between
compiled calls.

Host/device overlap reuses the PR-3 host-pipeline move at tick scale:
``decode_tick`` dispatches async, the host does its admission staging
(prompt padding, table edits) and request bookkeeping UNDER the in-flight
call, and the token fetch that closes the tick is the drain.

Per-request telemetry (the serving SLO vocabulary): **TTFT** (time to
first token — submit to prefill's greedy token) and **TPOT** (time per
output token — mean inter-token gap over the decode ticks), emitted as
one ``kind="request"`` record per completed request.

SLO-aware admission (ISSUE 11): because admission happens between ticks,
the QUEUE ORDER is the whole scheduling policy surface — exactly Orca's
point. ``order="fcfs"`` admits in arrival order, ``order="sjf"``
shortest-job-first by decode budget (short requests stop dying behind
stragglers — the goodput-under-deadline win the bench fleet gate
measures), ``order="priority"`` by descending ``priority`` tier. On top,
``shed=True`` rejects a deadline-carrying request AT SUBMIT when the
predicted completion time already blows its deadline
(``finish_reason="shed"``) — under overload the queue stops growing and
p99 for admitted requests stays bounded, instead of every request
timing out after burning a slot reservation.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Any, Dict, List, Optional

__all__ = ["Request", "ContinuousBatchingScheduler", "ORDERS"]

# queue-order policies: arrival order, shortest-decode-budget-first,
# descending priority tier (ties broken by arrival in all three)
ORDERS = ("fcfs", "sjf", "priority")


@dataclasses.dataclass
class Request:
    """One generation request and its lifecycle timestamps.

    ``deadline_s`` (optional) is a wall-clock budget from submit: a
    request still running (or still queued) past it is evicted between
    ticks with ``finish_reason="timeout"`` and its blocks freed — a
    stuck/long request can no longer occupy a slot and its worst-case
    block reservation forever (ISSUE 10). ``finish_reason`` is
    ``"length"`` | ``"eos"`` | ``"timeout"`` | ``"shed"`` (rejected at
    submit) | ``"retried"`` (attempt abandoned and resubmitted on
    another fleet replica — never terminal), surfaced in the per-request
    telemetry record. ``priority``/``retries`` carry the SLO tier and
    the fleet resubmission lineage; ``seq`` is the scheduler-local
    arrival index the order policies tie-break on."""
    rid: int
    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int] = None
    deadline_s: Optional[float] = None
    priority: int = 0
    retries: int = 0
    seq: int = 0
    tokens: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    submit_ts: float = 0.0
    first_token_ts: Optional[float] = None
    finish_ts: Optional[float] = None
    finish_reason: Optional[str] = None
    # ISSUE 12 telemetry: prefix-sharing / COW / speculation / chunked-
    # prefill attribution, copied from the engine's per-slot stats at
    # finish (None for requests that never took a slot)
    prefix_hit_blocks: Optional[int] = None
    blocks_reserved: Optional[int] = None
    cow_forks: Optional[int] = None
    prefill_chunks: Optional[int] = None
    draft_proposed: Optional[int] = None
    draft_accepted: Optional[int] = None

    @property
    def done(self) -> bool:
        return self.finish_ts is not None

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.first_token_ts is None:
            return None
        return (self.first_token_ts - self.submit_ts) * 1e3

    @property
    def tpot_ms(self) -> Optional[float]:
        """Mean inter-token time over tokens after the first; None until
        finished or with a single token."""
        if self.finish_ts is None or len(self.tokens) < 2:
            return None
        return ((self.finish_ts - self.first_token_ts) * 1e3
                / (len(self.tokens) - 1))

    def record(self) -> Dict[str, Any]:
        return {
            "kind": "request", "rid": self.rid,
            "prompt_len": len(self.prompt),
            "new_tokens": len(self.tokens),
            "slot": self.slot,
            "finish_reason": self.finish_reason,
            "deadline_s": self.deadline_s,
            "priority": self.priority,
            "retries": self.retries,
            "ttft_ms": round(self.ttft_ms, 4)
            if self.ttft_ms is not None else None,
            "tpot_ms": round(self.tpot_ms, 4)
            if self.tpot_ms is not None else None,
            # `is not None`, not truthiness: a fake-clock run can finish
            # at ts exactly 0.0 and must still record its wall time
            "wall_ms": round((self.finish_ts - self.submit_ts) * 1e3, 4)
            if self.finish_ts is not None else None,
            "prefix_hit_blocks": self.prefix_hit_blocks,
            "blocks_reserved": self.blocks_reserved,
            "cow_forks": self.cow_forks,
            "prefill_chunks": self.prefill_chunks,
            # raw counts ride along so aggregates can weight by volume
            # (a 2-draft request must not average equally with a
            # 500-draft one)
            "draft_proposed": self.draft_proposed,
            "draft_accepted": self.draft_accepted,
            "draft_accept_rate": round(
                self.draft_accepted / self.draft_proposed, 4)
            if self.draft_proposed else None,
        }


class ContinuousBatchingScheduler:
    """Drives a :class:`~paddle_tpu.serve.engine.DecodeEngine` over a
    request queue.

    ``policy="continuous"`` (default) admits between every tick;
    ``policy="static"`` is the gang baseline — a batch is admitted only
    when EVERY slot is free and runs until all its members finish (the
    differential the bench serving gate measures: on ragged lengths
    continuous wins exactly the idle-lane ticks static burns).

    ``order`` picks the admission policy over the queue (see module
    docstring); ``shed=True`` enables submit-time load shedding, which
    needs a tick-time estimate: pass ``est_tick_s`` as the cold-start
    prior (the scheduler keeps an EMA over observed inter-step clock
    deltas thereafter; with no estimate and no observations, nothing is
    shed — reject-fast needs evidence).
    """

    def __init__(self, engine, telemetry=None, policy: str = "continuous",
                 order: str = "fcfs", shed: bool = False,
                 est_tick_s: Optional[float] = None,
                 clock=time.perf_counter, tracer=None,
                 role: str = "both", metrics=None):
        if policy not in ("continuous", "static"):
            raise ValueError(f"policy must be 'continuous'|'static', "
                             f"got {policy!r}")
        if order not in ORDERS:
            raise ValueError(f"order must be one of {ORDERS}, got {order!r}")
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"role must be 'both'|'prefill'|'decode', "
                             f"got {role!r}")
        self.engine = engine
        self.telemetry = (telemetry if telemetry is not None
                          else engine.telemetry)
        # distributed request tracing (ISSUE 17): a Tracer sharing the
        # scheduler's clock. Spans carry the GLOBAL rid as their flow
        # id, so the fleet merge links a request's queue wait, prefill
        # chunks, and decode ticks across replicas. None = zero
        # overhead (every call site guards on it).
        self.tracer = tracer
        # typed metrics registry handle (ISSUE 19): a MetricsHub or a
        # replica-scoped facade. Queue-depth gauges per step, one
        # labeled finished counter per terminal reason (shed included),
        # one eviction counter per deadline sweep hit. Same contract as
        # tracer: None = zero overhead.
        self.metrics = metrics
        self.policy = policy
        self.order = order
        # prefill/decode disaggregation (ISSUE 18): a "prefill"-role
        # scheduler runs admission + prefill only — the moment a
        # request's first token lands it exports the slot's KV pages
        # into `handoffs` (the fleet streams them to a decode replica)
        # instead of decoding. A "decode" scheduler additionally accepts
        # `adopt()`ed sequences. "both" (default) is the colocated
        # baseline, byte-identical to pre-disagg behavior.
        self.role = role
        self.shed = shed
        self.est_tick_s = est_tick_s
        # injectable wall clock: deadlines are tested deterministically
        # with a fake clock; production uses perf_counter
        self._clock = clock
        self.queue: List[Request] = []
        self.running: Dict[int, Request] = {}       # slot -> request
        # chunked prefill in flight: slot -> request (the slot is
        # reserved; one chunk advances per step, between decode ticks)
        self.prefilling: Dict[int, Request] = {}
        self.completed: List[Request] = []
        # the last refusal's structured reason ("blocks"|"width"), for
        # router placement/shedding — None while admission is flowing
        self.last_backpressure: Optional[str] = None
        # finished prefills awaiting transfer: (request, meta, kpages,
        # vpages) tuples the fleet drains via pop_handoffs() each tick
        self.handoffs: List[tuple] = []
        self._rid = itertools.count()
        self._seq = itertools.count()
        self._last_step_ts: Optional[float] = None
        self._was_busy = False

    # -- load model --------------------------------------------------------

    def pending_new_tokens(self) -> int:
        """Decode tokens still owed: remaining budget of every running
        slot plus the full budget of every queued request — the
        scheduler's load number (one token per active slot per tick, so
        this is a tick-denominated backlog)."""
        run = sum(r.max_new_tokens - len(r.tokens)
                  for r in self.running.values())
        return (run + sum(r.max_new_tokens for r in self.queue)
                + sum(r.max_new_tokens for r in self.prefilling.values()))

    def load_report(self) -> Dict[str, Any]:
        """The load payload a replica publishes (heartbeat extras and
        the process-replica tick reply both carry it — ISSUE 13): the
        router balances and the autoscaler senses on exactly this
        evidence, whichever side of a process boundary the scheduler
        lives on."""
        return {"pending_new_tokens": self.pending_new_tokens(),
                "running": len(self.running),
                "queued": len(self.queue),
                "prefilling": len(self.prefilling),
                "prefill_backlog": self.prefill_backlog(),
                "role": self.role}

    def prefill_backlog(self) -> int:
        """Prompt tokens not yet prefilled — the PREFILL-role load
        number (pending_new_tokens is decode-denominated and would
        misplace prefill work onto a replica that never decodes).
        Role-aware routing places prefill on the least of this."""
        return (sum(len(r.prompt) for r in self.queue)
                + sum(len(r.prompt) for r in self.prefilling.values()))

    def predicted_completion_s(self, max_new_tokens: int
                               ) -> Optional[float]:
        """Predicted submit-to-finish seconds for a new request under the
        current backlog, or None without a tick-time estimate. The model
        is deliberately coarse — service rate is ``max_slots`` tokens per
        tick (the full-batch upper bound), so the queue delay is
        ``backlog / max_slots`` ticks and the run time ``max_new`` ticks.
        It is an underestimate under partial occupancy, which biases
        shedding conservative (shed less, queue more)."""
        if self.est_tick_s is None:
            return None
        ticks = (self.pending_new_tokens() / max(1, self.engine.max_slots)
                 + max_new_tokens)
        return ticks * self.est_tick_s

    # -- submission --------------------------------------------------------

    def submit(self, prompt: List[int], max_new_tokens: int,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               priority: int = 0, rid: Optional[int] = None,
               submit_ts: Optional[float] = None,
               retries: int = 0) -> Request:
        """Queue one request. ``rid``/``submit_ts``/``retries`` are for
        the fleet path: a resubmitted request keeps its GLOBAL id and its
        ORIGINAL submit time, so TTFT/wall/deadline are end-to-end truth
        (a user's deadline does not reset because a replica died). When
        ``rid`` is supplied the caller owns id uniqueness."""
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if deadline_s is not None and deadline_s < 0:
            raise ValueError("deadline_s must be >= 0")
        now = self._clock()
        req = Request(rid=next(self._rid) if rid is None else rid,
                      prompt=list(prompt),
                      max_new_tokens=max_new_tokens, eos_id=eos_id,
                      deadline_s=deadline_s, priority=priority,
                      retries=retries, seq=next(self._seq),
                      submit_ts=now if submit_ts is None else submit_ts)
        if len(req.prompt) + max_new_tokens > self.engine.context_width:
            raise ValueError(
                f"prompt {len(req.prompt)} + max_new_tokens "
                f"{max_new_tokens} exceeds slot capacity "
                f"{self.engine.context_width}")
        if self.shed and deadline_s is not None:
            est = self.predicted_completion_s(max_new_tokens)
            waited = max(0.0, now - req.submit_ts)
            if est is not None and waited + est > deadline_s:
                # reject-fast: no slot, no blocks, no staging — overload
                # degrades goodput gracefully instead of collapsing p99
                self._finish(req, "shed")
                return req
        # stage the padded prefill array now — admission-path host prep
        # off the tick loop's critical path (the PR-3 staging move)
        req._staged = self.engine.stage_prompt(req.prompt)
        self.queue.append(req)
        return req

    # -- the tick loop -----------------------------------------------------

    def _finish(self, req: Request, reason: str) -> None:
        """Common completion path: stamp reason + timestamp, copy the
        engine's per-slot sharing/speculation stats into the request,
        free the slot's blocks (when running or mid-prefill), record
        telemetry."""
        req.finish_ts = self._clock()
        req.finish_reason = reason
        slot = req.slot
        if slot is not None and (self.running.get(slot) is req
                                 or self.prefilling.get(slot) is req):
            st = self.engine.slot_stats[slot]
            req.prefix_hit_blocks = st.get("prefix_hit_blocks")
            req.blocks_reserved = st.get("blocks_reserved")
            req.cow_forks = st.get("cow_forks")
            req.prefill_chunks = st.get("prefill_chunks")
            req.draft_proposed = st.get("draft_proposed")
            req.draft_accepted = st.get("draft_accepted")
            self.running.pop(slot, None)
            self.prefilling.pop(slot, None)
            self.engine.evict(slot)            # blocks back to the pool
        self.completed.append(req)
        if self.metrics is not None:
            self.metrics.counter(
                "sched_requests_finished",
                "terminal requests by finish reason",
                reason=reason).inc()
        if self.tracer is not None:
            self.tracer.complete("finish", req.finish_ts * 1e6,
                                 flow_step=req.rid, rid=req.rid,
                                 reason=reason,
                                 new_tokens=len(req.tokens))
        if self.telemetry is not None:
            self.telemetry.emit_event(req.record())

    def _emit_evict(self, req: Request, where: str,
                    blocks_freed: int) -> None:
        """One ``kind="evict"`` record per deadline eviction — BOTH the
        running-slot case and the queued-drop case are visible (ISSUE 11
        satellite: a queued request dying of backpressure starvation must
        show up in telemetry, not just slot evictions)."""
        if self.metrics is not None:
            self.metrics.counter(
                "sched_evictions",
                "deadline evictions by where the request was caught",
                where=where).inc()
        if self.telemetry is not None:
            self.telemetry.emit_event({
                "kind": "evict", "rid": req.rid, "where": where,
                "blocks_freed": blocks_freed,
                "deadline_s": req.deadline_s,
                "queued": len(self.queue), "running": len(self.running),
            })

    def _expire(self) -> None:
        """Deadline sweep, run BETWEEN ticks (the same boundary where
        admissions/evictions already happen — the compiled tick shape
        never changes). A running slot past its deadline is evicted and
        its block reservation freed; a queued request past its deadline
        is dropped before ever taking a slot."""
        now = self._clock()

        def expired(req):
            return (req.deadline_s is not None
                    and now - req.submit_ts > req.deadline_s)

        for slot, req in list(self.running.items()):
            if expired(req):
                self._emit_evict(req, "running",
                                 self.engine.cache.owned_count(slot))
                self._finish(req, "timeout")
        for slot, req in list(self.prefilling.items()):
            if expired(req):
                self._emit_evict(req, "prefilling",
                                 self.engine.cache.owned_count(slot))
                self._finish(req, "timeout")
        for req in [r for r in self.queue if expired(r)]:
            self.queue.remove(req)
            self._emit_evict(req, "queued", 0)
            self._finish(req, "timeout")

    def _admit_order(self) -> List[Request]:
        """The queue in admission order under the active policy. FCFS is
        the queue itself; SJF sorts by decode budget (the dominant cost —
        prefill is one tick regardless of prompt length); priority sorts
        by descending tier. Arrival breaks every tie, so equal-key
        requests never starve each other."""
        if self.order == "sjf":
            return sorted(self.queue, key=lambda r: (r.max_new_tokens,
                                                     r.seq))
        if self.order == "priority":
            return sorted(self.queue, key=lambda r: (-r.priority, r.seq))
        return list(self.queue)

    def _admit(self) -> None:
        self.last_backpressure = None    # cleared even on the gang wait
        if self.policy == "static" and (self.running or self.prefilling):
            return                       # gang: wait for the whole batch
        free = self.engine.free_slots()
        for req in self._admit_order():
            if not free:
                break
            # a decode tick appends the pending token BEFORE sampling, so
            # the cache must hold prompt + all generated tokens except
            # the last sampled one: reserve prompt + max_new - 1
            target = max(len(req.prompt) + req.max_new_tokens - 1,
                         len(req.prompt))
            probe = self.engine.admit_probe(target, include_slots=False)
            if not probe.ok:
                # pool backpressure: stop in strict policy order (no
                # smaller-request bypass — bypass would starve the head)
                self.last_backpressure = probe.reason
                if self.tracer is not None:
                    self.tracer.instant("backpressure", rid=req.rid,
                                        reason=probe.reason,
                                        queued=len(self.queue))
                break
            self.queue.remove(req)
            if self.tracer is not None:
                # retroactive queue-wait span: submit_ts -> now, in the
                # shared clock's time base
                self.tracer.complete("queue_wait", req.submit_ts * 1e6,
                                     self.tracer.now_us(),
                                     flow_step=req.rid, rid=req.rid)
            slot = free.pop(0)
            self.engine.begin_prefill(slot, req.prompt,
                                      reserve_len=target,
                                      staged=getattr(req, "_staged",
                                                     None))
            req.slot = slot
            self.prefilling[slot] = req
            # one prefill call now: the whole prompt on a legacy
            # engine (admission behavior unchanged), the first chunk
            # on a chunked one — the rest interleave with decode ticks
            self._advance_prefill(slot)

    def _advance_prefill(self, slot: int) -> None:
        """One compiled prefill call for a reserved slot; promotes the
        request to running when its first token lands."""
        req = self.prefilling[slot]
        if self.tracer is not None:
            with self.tracer.span("prefill_chunk", rid=req.rid,
                                  slot=slot):
                tok = self.engine.prefill_step(slot)
        else:
            tok = self.engine.prefill_step(slot)
        if tok is None:
            return
        del self.prefilling[slot]
        req.tokens.append(tok)
        req.first_token_ts = self._clock()
        self.running[slot] = req
        self._maybe_finish(slot, tok)
        if self.role == "prefill" and not req.done:
            # disaggregation: the prompt's KV and the pending first
            # token leave for a decode replica. A request TERMINAL at
            # its first token (eos, max_new=1) finished above on this
            # replica — shipping zero decode work would be pure wire
            # cost.
            self._hand_off(slot, req)

    def _hand_off(self, slot: int, req: Request) -> None:
        """Export a just-prefilled slot for transfer and release it
        locally. The request leaves this scheduler WITHOUT a completed
        record — the adopting decode replica authors the terminal
        record, carrying the ORIGINAL submit/first-token stamps so
        TTFT/wall stay end-to-end truth. Prefill-side attribution
        (prefix hits, chunks) rides the meta and is merged into the
        decode slot's stats."""
        meta, kpages, vpages = self.engine.export_slot(slot)
        st = self.engine.slot_stats[slot]
        meta.update({
            "rid": req.rid, "prompt": list(req.prompt),
            "max_new_tokens": req.max_new_tokens,
            "eos_id": req.eos_id, "deadline_s": req.deadline_s,
            "priority": req.priority, "retries": req.retries,
            "submit_ts": req.submit_ts,
            "first_token": req.tokens[0],
            "first_token_ts": req.first_token_ts,
            "prefill_stats": {
                "prefix_hit_blocks": st.get("prefix_hit_blocks", 0),
                "shared_len": st.get("shared_len", 0),
                "cow_forks": st.get("cow_forks", 0),
                "prefill_chunks": st.get("prefill_chunks", 0)}})
        del self.running[slot]
        self.engine.evict(slot)
        req.slot = None
        self.handoffs.append((req, meta, kpages, vpages))
        if self.tracer is not None:
            now_us = self._clock() * 1e6
            self.tracer.complete("handoff_out", now_us,
                                 flow_step=req.rid, rid=req.rid,
                                 blocks=meta["blocks"])

    def pop_handoffs(self) -> List[tuple]:
        """Drain finished prefills awaiting transfer (fleet-facing)."""
        out, self.handoffs = self.handoffs, []
        return out

    def adopt(self, meta: Dict[str, Any], kpages,
              vpages) -> Optional[Request]:
        """Decode-side admission of a streamed prefill: capacity-check,
        import the pages into a free slot, and enter the request
        directly in ``running`` with its first token already generated.
        Returns None (nothing changed) when this replica can't take it
        yet — no free slot or pool backpressure; the fleet retries or
        re-routes."""
        free = self.engine.free_slots()
        if not free:
            self.last_backpressure = "slots"
            return None
        prompt = [int(t) for t in meta["prompt"]]
        max_new = int(meta["max_new_tokens"])
        target = max(len(prompt) + max_new - 1, len(prompt))
        probe = self.engine.admit_probe(target, include_slots=False)
        if not probe.ok:
            self.last_backpressure = probe.reason
            return None
        req = Request(
            rid=int(meta["rid"]), prompt=prompt,
            max_new_tokens=max_new, eos_id=meta.get("eos_id"),
            deadline_s=meta.get("deadline_s"),
            priority=int(meta.get("priority") or 0),
            retries=int(meta.get("retries") or 0),
            seq=next(self._seq), submit_ts=meta["submit_ts"])
        slot = free[0]
        if not self.engine.adopt_slot(slot, prompt,
                                      int(meta["first_token"]),
                                      kpages, vpages,
                                      reserve_len=target):
            self.last_backpressure = "blocks"
            return None
        req.slot = slot
        req.tokens = [int(meta["first_token"])]
        req.first_token_ts = meta.get("first_token_ts")
        self.running[slot] = req
        # end-to-end attribution: the decode slot's stats START from
        # the prefill side's (prefix hits happened over there); _finish
        # copies them into the terminal record as usual
        self.engine.slot_stats[slot].update(
            meta.get("prefill_stats") or {})
        self.last_backpressure = None
        if self.tracer is not None:
            self.tracer.complete("adopt", self._clock() * 1e6,
                                 flow_step=req.rid, rid=req.rid,
                                 slot=slot, blocks=meta.get("blocks"))
        return req

    def _maybe_finish(self, slot: int, tok: int) -> None:
        req = self.running[slot]
        if req.eos_id is not None and tok == req.eos_id:
            self._finish(req, "eos")
        elif len(req.tokens) >= req.max_new_tokens:
            self._finish(req, "length")

    def step(self) -> bool:
        """Expire deadlines, admit, run one decode tick, collect
        finished requests. Returns True while work remains."""
        now = self._clock()
        if self._last_step_ts is not None and self._was_busy:
            # EMA over inter-step deltas: the shed predictor's tick-time
            # evidence (deterministic under a fake clock — the injected
            # advances ARE the observations). Only deltas between
            # consecutive BUSY steps count: after an idle lull the gap
            # is think time, not tick time, and folding it in would make
            # the predictor shed against an empty engine.
            dt = now - self._last_step_ts
            if dt > 0:
                self.est_tick_s = (dt if self.est_tick_s is None
                                   else 0.7 * self.est_tick_s + 0.3 * dt)
        self._last_step_ts = now
        self._expire()
        # chunked prefill: ONE chunk per already-prefilling slot per
        # step, BETWEEN decode ticks — a 4k-token admit becomes many
        # cheap calls instead of one monolithic stall of every running
        # slot (fresh admissions below run their first chunk inside
        # _admit)
        for slot in list(self.prefilling):
            self._advance_prefill(slot)
        self._admit()
        if self.running:
            active = len(self.running)
            t0 = (self.tracer.now_us()
                  if self.tracer is not None else None)
            self.engine.decode_tick()
            # the tick may retire several tokens per slot (speculative
            # accepts); feed them through the same finish rules one at
            # a time so eos/length semantics match the sequential
            # engine exactly
            accepted = self.engine.last_accepted
            n_tok = 0
            for slot, req in list(self.running.items()):
                for tok in accepted.get(slot, ()):
                    req.tokens.append(tok)
                    n_tok += 1
                    self._maybe_finish(slot, tok)
                    if req.done:
                        break
            if t0 is not None:
                self.tracer.complete("decode_tick", t0,
                                     self.tracer.now_us(),
                                     active=active, tokens=n_tok)
        if self.metrics is not None:
            self.metrics.gauge("sched_queue_depth",
                               "requests queued for admission").set(
                len(self.queue))
            self.metrics.gauge("sched_running",
                               "requests holding a decode slot").set(
                len(self.running))
            self.metrics.gauge("sched_prefilling",
                               "slots mid chunked prefill").set(
                len(self.prefilling))
        self._was_busy = bool(self.queue or self.running
                              or self.prefilling)
        return self._was_busy

    def run(self, max_ticks: int = 100000) -> List[Request]:
        """Drive ticks until the queue drains; returns completed
        requests in completion order."""
        for _ in range(max_ticks):
            if not self.step():
                break
        else:
            raise RuntimeError(f"scheduler did not drain in "
                               f"{max_ticks} ticks")
        return self.completed
