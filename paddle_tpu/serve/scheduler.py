"""Iteration-level (continuous-batching) request scheduling — the Orca
move (PAPERS.md [S2]): scheduling decisions happen between DECODE TICKS,
not between whole requests.

Classic static batching gangs requests: a batch runs until its LONGEST
member finishes, so every short request's slot sits idle (masked lanes
burning a full tick's work) while the straggler decodes. Iteration-level
scheduling admits a queued request into a slot the moment one frees and
evicts a finished request the moment its last token lands — the decode
tick's fixed ``[S]`` shape never changes (the engine's active mask
absorbs churn), so the scheduler is pure host bookkeeping between
compiled calls.

Host/device overlap reuses the PR-3 host-pipeline move at tick scale:
``decode_tick`` dispatches async, the host does its admission staging
(prompt padding, table edits) and request bookkeeping UNDER the in-flight
call, and the token fetch that closes the tick is the drain.

Per-request telemetry (the serving SLO vocabulary): **TTFT** (time to
first token — submit to prefill's greedy token) and **TPOT** (time per
output token — mean inter-token gap over the decode ticks), emitted as
one ``kind="request"`` record per completed request.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Any, Dict, List, Optional

__all__ = ["Request", "ContinuousBatchingScheduler"]


@dataclasses.dataclass
class Request:
    """One generation request and its lifecycle timestamps.

    ``deadline_s`` (optional) is a wall-clock budget from submit: a
    request still running (or still queued) past it is evicted between
    ticks with ``finish_reason="timeout"`` and its blocks freed — a
    stuck/long request can no longer occupy a slot and its worst-case
    block reservation forever (ISSUE 10). ``finish_reason`` is
    ``"length"`` | ``"eos"`` | ``"timeout"``, surfaced in the
    per-request telemetry record."""
    rid: int
    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int] = None
    deadline_s: Optional[float] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    submit_ts: float = 0.0
    first_token_ts: Optional[float] = None
    finish_ts: Optional[float] = None
    finish_reason: Optional[str] = None

    @property
    def done(self) -> bool:
        return self.finish_ts is not None

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.first_token_ts is None:
            return None
        return (self.first_token_ts - self.submit_ts) * 1e3

    @property
    def tpot_ms(self) -> Optional[float]:
        """Mean inter-token time over tokens after the first; None until
        finished or with a single token."""
        if self.finish_ts is None or len(self.tokens) < 2:
            return None
        return ((self.finish_ts - self.first_token_ts) * 1e3
                / (len(self.tokens) - 1))

    def record(self) -> Dict[str, Any]:
        return {
            "kind": "request", "rid": self.rid,
            "prompt_len": len(self.prompt),
            "new_tokens": len(self.tokens),
            "slot": self.slot,
            "finish_reason": self.finish_reason,
            "deadline_s": self.deadline_s,
            "ttft_ms": round(self.ttft_ms, 4)
            if self.ttft_ms is not None else None,
            "tpot_ms": round(self.tpot_ms, 4)
            if self.tpot_ms is not None else None,
            "wall_ms": round((self.finish_ts - self.submit_ts) * 1e3, 4)
            if self.finish_ts else None,
        }


class ContinuousBatchingScheduler:
    """Drives a :class:`~paddle_tpu.serve.engine.DecodeEngine` over a
    request queue.

    ``policy="continuous"`` (default) admits between every tick;
    ``policy="static"`` is the gang baseline — a batch is admitted only
    when EVERY slot is free and runs until all its members finish (the
    differential the bench serving gate measures: on ragged lengths
    continuous wins exactly the idle-lane ticks static burns).
    """

    def __init__(self, engine, telemetry=None, policy: str = "continuous",
                 clock=time.perf_counter):
        if policy not in ("continuous", "static"):
            raise ValueError(f"policy must be 'continuous'|'static', "
                             f"got {policy!r}")
        self.engine = engine
        self.telemetry = (telemetry if telemetry is not None
                          else engine.telemetry)
        self.policy = policy
        # injectable wall clock: deadlines are tested deterministically
        # with a fake clock; production uses perf_counter
        self._clock = clock
        self.queue: List[Request] = []
        self.running: Dict[int, Request] = {}       # slot -> request
        self.completed: List[Request] = []
        self._rid = itertools.count()

    # -- submission --------------------------------------------------------

    def submit(self, prompt: List[int], max_new_tokens: int,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None) -> Request:
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if deadline_s is not None and deadline_s < 0:
            raise ValueError("deadline_s must be >= 0")
        req = Request(rid=next(self._rid), prompt=list(prompt),
                      max_new_tokens=max_new_tokens, eos_id=eos_id,
                      deadline_s=deadline_s, submit_ts=self._clock())
        if len(req.prompt) + max_new_tokens > self.engine.context_width:
            raise ValueError(
                f"prompt {len(req.prompt)} + max_new_tokens "
                f"{max_new_tokens} exceeds slot capacity "
                f"{self.engine.context_width}")
        # stage the padded prefill array now — admission-path host prep
        # off the tick loop's critical path (the PR-3 staging move)
        req._staged = self.engine.stage_prompt(req.prompt)
        self.queue.append(req)
        return req

    # -- the tick loop -----------------------------------------------------

    def _finish(self, req: Request, reason: str) -> None:
        """Common completion path: stamp reason + timestamp, free the
        slot's blocks (when running), record telemetry."""
        req.finish_ts = self._clock()
        req.finish_reason = reason
        if req.slot is not None and self.running.get(req.slot) is req:
            del self.running[req.slot]
            self.engine.evict(req.slot)        # blocks back to the pool
        self.completed.append(req)
        if self.telemetry is not None:
            self.telemetry.emit_event(req.record())

    def _expire(self) -> None:
        """Deadline sweep, run BETWEEN ticks (the same boundary where
        admissions/evictions already happen — the compiled tick shape
        never changes). A running slot past its deadline is evicted and
        its block reservation freed; a queued request past its deadline
        is dropped before ever taking a slot."""
        now = self._clock()

        def expired(req):
            return (req.deadline_s is not None
                    and now - req.submit_ts > req.deadline_s)

        for slot, req in list(self.running.items()):
            if expired(req):
                self._finish(req, "timeout")
        for req in [r for r in self.queue if expired(r)]:
            self.queue.remove(req)
            self._finish(req, "timeout")

    def _admit(self) -> None:
        if self.policy == "static" and self.running:
            return                       # gang: wait for the whole batch
        free = self.engine.free_slots()
        while self.queue and free:
            req = self.queue[0]
            # a decode tick appends the pending token BEFORE sampling, so
            # the cache must hold prompt + all generated tokens except
            # the last sampled one: reserve prompt + max_new - 1
            target = len(req.prompt) + req.max_new_tokens - 1
            if not self.engine.can_admit(max(target, len(req.prompt))):
                break                    # pool backpressure: try next tick
            self.queue.pop(0)
            slot = free.pop(0)
            tok = self.engine.admit(slot, req.prompt, reserve_len=target,
                                    staged=getattr(req, "_staged", None))
            req.slot = slot
            req.tokens.append(tok)
            req.first_token_ts = self._clock()
            self.running[slot] = req
            self._maybe_finish(slot, tok)

    def _maybe_finish(self, slot: int, tok: int) -> None:
        req = self.running[slot]
        if req.eos_id is not None and tok == req.eos_id:
            self._finish(req, "eos")
        elif len(req.tokens) >= req.max_new_tokens:
            self._finish(req, "length")

    def step(self) -> bool:
        """Expire deadlines, admit, run one decode tick, collect
        finished requests. Returns True while work remains."""
        self._expire()
        self._admit()
        if self.running:
            front = self.engine.decode_tick()
            for slot, req in list(self.running.items()):
                tok = int(front[slot])
                req.tokens.append(tok)
                self._maybe_finish(slot, tok)
        return bool(self.queue or self.running)

    def run(self, max_ticks: int = 100000) -> List[Request]:
        """Drive ticks until the queue drains; returns completed
        requests in completion order."""
        for _ in range(max_ticks):
            if not self.step():
                break
        else:
            raise RuntimeError(f"scheduler did not drain in "
                               f"{max_ticks} ticks")
        return self.completed
