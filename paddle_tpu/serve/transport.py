"""Submit/complete IPC transport for process-isolated serving replicas
(ISSUE 13).

PR 11 drew the replica seam — the router balances on heartbeat-carried
load, delivery is rid-keyed and idempotent, death is observed from file
staleness — but every replica was still an in-process object, so a real
segfault or OOM kill in one engine took the whole fleet down. This
module is the wire that lets a replica live in its own process: a
length-prefixed JSON frame protocol over the child's stdin/stdout pipes,
with the failure modes a real RPC layer has, each CLASSIFIED instead of
crashing the router:

- **Framing**: ``u32 big-endian length || UTF-8 JSON payload``. A frame
  whose length field is absurd or whose body fails to parse raises
  :class:`TransportCorrupt` — a garbled reply is an error VERDICT the
  caller can act on (retransmit), never a router exception.
- **Per-message timeout**: every :meth:`ReplicaTransport.request` waits
  a bounded time for the matching reply (``select`` on the pipe fd). A
  hung or dead child surfaces as :class:`TransportTimeout` /
  :class:`TransportClosed`; the DEATH verdict still belongs to the
  heartbeat file going stale (the PR-11 doctrine — observed, never
  announced), the transport just stops waiting.
- **At-least-once delivery, seq-numbered**: requests carry a
  monotonically increasing ``seq``; on timeout or corruption the sender
  retransmits the SAME seq (injection flags stripped — the fault was
  the delivery, not the work). The child dedupes by seq and replays its
  cached reply, so a lost/garbled REPLY never re-executes the work, and
  a lost REQUEST simply runs on the retransmit. Above this sits the
  fleet's rid-keyed idempotency (PR 11), so even a duplicate that slips
  a cold cache is dropped at the replica boundary.

Fault injection rides the messages themselves: the fleet (which owns the
:class:`~paddle_tpu.train.faults.FaultSchedule`) sets ``inject_drop_reply``
/ ``inject_corrupt_reply`` flags on a tick request, and the CHILD enacts
them — processing the work, then losing or garbling the reply — so the
drill exercises the real timeout/corrupt/retransmit/dedupe path end to
end, not a parent-side simulation of it.

**Sockets (ISSUE 18).** The protocol never assumed a pipe — framing,
timeouts, seq retransmission and the reply cache are all byte-stream
semantics — so the cross-host promotion is three SEAMS, not a second
protocol: :func:`listen` / :func:`connect` (plus
:class:`SocketFrameReader` and the partial-write-safe
:class:`SocketWriter`) put the SAME frame bytes on a TCP connection,
``spawn_replica_process(connect=...)`` tells the child to dial the
parent's listener instead of inheriting pipes, and everything above —
:class:`ReplicaTransport`, the child's serve loop, heartbeat-file
health, SIGKILL drills, the fleet's reconcile path — runs unchanged.
``ServingFleet(replica_mode="socket")`` exercises it over loopback in
CI; a remote host runs the same child against a reachable address.

**Binary frames (ISSUE 18).** KV-page handoffs must not round-trip
through base64/JSON, so a second frame kind rides the same stream: the
length prefix's HIGH BIT marks a raw-bytes payload guarded by a CRC32
(JSON frames get corruption detection from the parse; raw bytes need
the checksum to classify a garbled payload as
:class:`TransportCorrupt` instead of silently adopting garbage KV). A
JSON message (request OR reply) carrying ``nblobs: k`` is immediately
followed by ``k`` binary frames — one logical exchange, so the seq
cache replays reply+blobs together and a retransmitted request resends
its payload frames with it.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import random
import select
import socket as socket_lib
import struct
import subprocess
import sys
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple, Union

__all__ = ["TransportError", "TransportTimeout", "TransportCorrupt",
           "TransportClosed", "encode_frame", "write_frame",
           "encode_binary_frame", "write_binary_frame", "FrameReader",
           "SocketFrameReader", "SocketWriter", "listen", "connect",
           "accept_connection", "ReplicaTransport",
           "spawn_replica_process", "MAX_FRAME_BYTES", "BINARY_FLAG"]

_log = logging.getLogger("paddle_tpu.serve.transport")

# a frame longer than this is garbage, not a message (the biggest real
# frame is a KV-page handoff blob — a few MB of pages for the mini
# models; a length beyond this means the stream is desynchronized)
MAX_FRAME_BYTES = 1 << 24

# the length prefix's high bit marks a BINARY frame: u32 crc32 + raw
# payload bytes instead of UTF-8 JSON. MAX_FRAME_BYTES (2^24) leaves
# the bit unambiguous — a JSON frame can never legally set it.
BINARY_FLAG = 1 << 31

_HEADER = struct.Struct(">I")


class TransportError(RuntimeError):
    """Base class for classified transport failures. ``kind`` is the
    machine-readable verdict carried into telemetry."""
    kind = "error"


class TransportTimeout(TransportError):
    """No (matching) reply within the per-message timeout — a hung child
    or a lost reply. Retransmit or let the heartbeat verdict decide."""
    kind = "timeout"


class TransportCorrupt(TransportError):
    """A frame arrived but is not a message: absurd length prefix or a
    body that fails to parse. Classified, never raised through the
    fleet tick as a crash."""
    kind = "corrupt"


class TransportClosed(TransportError):
    """The pipe is gone (EOF / EPIPE) — the peer process exited or was
    killed. Terminal for this transport; the heartbeat file goes stale
    on its own schedule."""
    kind = "closed"


def _json_default(o):
    """Prompts and lengths often arrive as numpy scalars; their
    ``item()`` is the plain-python value JSON wants."""
    if hasattr(o, "item"):
        return o.item()
    raise TypeError(f"not JSON-serializable: {type(o).__name__}")


def encode_frame(obj: Dict[str, Any]) -> bytes:
    """One message as wire bytes: length prefix + compact JSON."""
    body = json.dumps(obj, separators=(",", ":"),
                      default=_json_default).encode("utf-8")
    if len(body) > MAX_FRAME_BYTES:
        raise ValueError(f"frame too large: {len(body)} bytes")
    return _HEADER.pack(len(body)) + body


def write_frame(fobj, obj: Dict[str, Any]) -> int:
    """Write one frame and flush; returns the wire byte count (the
    transport's tx-bytes metric wants the exact framed size, not the
    payload estimate). Pipe failures raise :class:`TransportClosed`."""
    try:
        frame = encode_frame(obj)
        fobj.write(frame)
        fobj.flush()
        return len(frame)
    except (BrokenPipeError, OSError, ValueError) as e:
        raise TransportClosed(f"write failed: {e}") from e


def encode_binary_frame(payload: bytes) -> bytes:
    """One raw-bytes message as wire bytes: ``u32 (len+4)|BINARY_FLAG``
    then ``u32 crc32(payload)`` then the payload verbatim. The checksum
    is what lets the reader CLASSIFY a garbled payload — JSON frames get
    that for free from the parse; raw KV pages would otherwise be
    silently adopted corrupt."""
    payload = bytes(payload)
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(f"binary frame too large: {len(payload)} bytes")
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return (_HEADER.pack((len(payload) + 4) | BINARY_FLAG)
            + _HEADER.pack(crc) + payload)


def write_binary_frame(fobj, payload: bytes) -> int:
    """Write one binary frame and flush; returns the wire byte count.
    Pipe failures raise :class:`TransportClosed`."""
    try:
        frame = encode_binary_frame(payload)
        fobj.write(frame)
        fobj.flush()
        return len(frame)
    except (BrokenPipeError, OSError, ValueError) as e:
        raise TransportClosed(f"write failed: {e}") from e


class FrameReader:
    """Incremental frame reader over a pipe/socket file object, with an
    optional per-read timeout (``select`` on the fd — a blocking
    ``read(n)`` would hang exactly when the peer does)."""

    def __init__(self, fobj):
        self._f = fobj
        self._fd = fobj.fileno()
        self._buf = bytearray()
        # cumulative wire bytes consumed as COMPLETE frames (header
        # included) — the transport's rx-bytes metric reads deltas of
        # this; partial/buffered bytes don't count until the frame does
        self.bytes_read = 0
        self.frames_read = 0

    def read_frame(self, timeout_s: Optional[float] = None,
                   allow_binary: bool = False
                   ) -> Union[Dict[str, Any], bytes]:
        """Read one frame. Raises :class:`TransportTimeout` when no
        complete frame arrives in ``timeout_s`` (partial bytes stay
        buffered for the next call), :class:`TransportCorrupt` on a
        garbage length, unparseable JSON body, or a binary payload
        failing its CRC, :class:`TransportClosed` on EOF.

        With ``allow_binary`` a frame whose length prefix carries
        :data:`BINARY_FLAG` is returned as raw ``bytes`` (checksum
        verified); without it a binary frame is a protocol violation
        (the caller expected a message) and classifies as corrupt."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        # peek-then-consume: nothing leaves the buffer until the WHOLE
        # frame is present, so a timeout mid-body is resumable — the
        # next call re-parses the same header instead of mistaking
        # body bytes for a length prefix
        self._fill(_HEADER.size, deadline)
        (n,) = _HEADER.unpack(bytes(self._buf[:_HEADER.size]))
        if n & BINARY_FLAG:
            n &= ~BINARY_FLAG & 0xFFFFFFFF
            if n < 4 or n - 4 > MAX_FRAME_BYTES:
                del self._buf[:_HEADER.size]
                raise TransportCorrupt(f"binary frame length {n} exceeds "
                                       f"{MAX_FRAME_BYTES}")
            self._fill(_HEADER.size + n, deadline)
            body = bytes(self._buf[_HEADER.size:_HEADER.size + n])
            del self._buf[:_HEADER.size + n]
            self.bytes_read += _HEADER.size + n
            self.frames_read += 1
            (want,) = _HEADER.unpack(body[:4])
            payload = body[4:]
            got = zlib.crc32(payload) & 0xFFFFFFFF
            if got != want:
                # a garbled raw payload parses as nothing — the CRC is
                # the only line between "classified corrupt" and
                # adopting garbage KV pages into a live pool
                raise TransportCorrupt(
                    f"binary frame checksum mismatch "
                    f"(crc32 {got:#010x} != {want:#010x})")
            if not allow_binary:
                raise TransportCorrupt(
                    "unexpected binary frame (message expected)")
            return payload
        if n > MAX_FRAME_BYTES:
            # the stream is desynchronized beyond repair once the length
            # field is garbage; classify rather than read 4GB
            del self._buf[:_HEADER.size]
            raise TransportCorrupt(f"frame length {n} exceeds "
                                   f"{MAX_FRAME_BYTES}")
        self._fill(_HEADER.size + n, deadline)
        body = bytes(self._buf[_HEADER.size:_HEADER.size + n])
        del self._buf[:_HEADER.size + n]
        self.bytes_read += _HEADER.size + n
        self.frames_read += 1
        try:
            return json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, ValueError) as e:
            raise TransportCorrupt(f"unparseable frame body: {e}") from e

    def read_binary_frame(self, timeout_s: Optional[float] = None
                          ) -> bytes:
        """Read one frame that MUST be binary (a declared blob). A JSON
        frame arriving in a blob slot means the peer and reader disagree
        about ``nblobs`` — the stream is desynchronized, so classify as
        corrupt rather than mis-deliver a message as payload."""
        out = self.read_frame(timeout_s=timeout_s, allow_binary=True)
        if not isinstance(out, (bytes, bytearray)):
            raise TransportCorrupt("expected binary frame, got message")
        return bytes(out)

    def _fill(self, n: int, deadline: Optional[float]) -> None:
        """Grow the buffer to at least ``n`` bytes WITHOUT consuming —
        a timeout leaves every byte in place for the next attempt."""
        while len(self._buf) < n:
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TransportTimeout(
                        f"no frame within timeout ({len(self._buf)}/{n} "
                        f"bytes buffered)")
                ready, _, _ = select.select([self._fd], [], [], remaining)
                if not ready:
                    raise TransportTimeout(
                        f"no frame within timeout ({len(self._buf)}/{n} "
                        f"bytes buffered)")
            try:
                chunk = os.read(self._fd, 65536)
            except OSError as e:
                raise TransportClosed(f"read failed: {e}") from e
            if not chunk:
                raise TransportClosed("EOF")
            self._buf += chunk


class SocketFrameReader(FrameReader):
    """:class:`FrameReader` over a connected TCP socket. The base class
    only needs a ``fileno()`` (``select`` + ``os.read`` work on socket
    fds on POSIX), so the protocol — framing, timeouts, corruption
    classification — is inherited byte-for-byte; this subclass exists to
    name the seam and keep a typed handle on the socket."""

    def __init__(self, sock: socket_lib.socket):
        self.sock = sock
        super().__init__(sock)


class SocketWriter:
    """Write half of a socket transport: ``sendall`` under the hood
    (file-object wrappers over sockets may short-write; a torn frame
    header desynchronizes the stream permanently). Duck-types the
    ``write``/``flush``/``close`` surface :func:`write_frame` expects,
    so the frame writers are shared with the pipe path."""

    def __init__(self, sock: socket_lib.socket):
        self.sock = sock

    def write(self, data: bytes) -> None:
        self.sock.sendall(data)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def listen(host: str = "127.0.0.1", port: int = 0,
           backlog: int = 16) -> socket_lib.socket:
    """Open a listening TCP socket for replica connections. Port 0 picks
    an ephemeral port — read it back via ``getsockname()`` and hand it
    to the child on argv (``--connect host:port``). Loopback by default
    (the CI shape); bind a routable address to accept remote hosts."""
    srv = socket_lib.socket(socket_lib.AF_INET, socket_lib.SOCK_STREAM)
    srv.setsockopt(socket_lib.SOL_SOCKET, socket_lib.SO_REUSEADDR, 1)
    srv.bind((host, int(port)))
    srv.listen(backlog)
    return srv


def accept_connection(srv: socket_lib.socket,
                      timeout_s: Optional[float] = None
                      ) -> Tuple[socket_lib.socket, Any]:
    """Accept one replica connection, bounded by ``timeout_s`` (a child
    that dies before dialing must not hang the fleet's spawn path —
    classify as :class:`TransportTimeout` and let the caller reap it).
    Disables Nagle: frames are latency-sensitive request/reply, and the
    big KV-page blobs saturate writes on their own."""
    if timeout_s is not None:
        ready, _, _ = select.select([srv.fileno()], [], [], timeout_s)
        if not ready:
            raise TransportTimeout(
                f"no replica connection within {timeout_s:.1f}s")
    sock, addr = srv.accept()
    sock.setsockopt(socket_lib.IPPROTO_TCP, socket_lib.TCP_NODELAY, 1)
    return sock, addr


def connect(host: str, port: int, timeout_s: float = 30.0,
            retry_interval_s: float = 0.05, *,
            backoff_cap_s: float = 0.5,
            rng: Optional[random.Random] = None,
            sleep=time.sleep) -> socket_lib.socket:
    """Dial the fleet's listener (child side), retrying connection
    refusals until ``timeout_s`` — the parent always listens before
    spawning, but a remote-host child may race a slow accept loop.

    Retries back off with full jitter (ISSUE 20): attempt ``k`` sleeps
    ``uniform(0, min(backoff_cap_s, retry_interval_s * 2**k))``, so a
    healed partition does not get every waiting dialer knocking in the
    same millisecond. ``rng``/``sleep`` are injectable for deterministic
    drills (the default rng is process-seeded — dial desynchronization
    WANTS per-process randomness)."""
    deadline = time.monotonic() + float(timeout_s)
    rng = rng if rng is not None else random.Random()
    last: Optional[Exception] = None
    attempt = 0
    while time.monotonic() < deadline:
        try:
            sock = socket_lib.create_connection(
                (host, int(port)),
                timeout=max(0.1, deadline - time.monotonic()))
            sock.settimeout(None)
            sock.setsockopt(socket_lib.IPPROTO_TCP,
                            socket_lib.TCP_NODELAY, 1)
            return sock
        except OSError as e:
            last = e
            cap = min(float(backoff_cap_s),
                      float(retry_interval_s) * (2.0 ** min(attempt, 32)))
            sleep(rng.uniform(0.0, cap))
            attempt += 1
    raise TransportClosed(f"could not connect to {host}:{port}: {last}")


class ReplicaTransport:
    """The parent half of the submit/complete channel to one replica
    process: seq-numbered requests, per-message timeout, bounded
    retransmission, and counters for every classified failure (they ride
    into ``ServingFleet.stats()``)."""

    def __init__(self, read_file, write_file, *, proc=None,
                 timeout_s: float = 2.0, max_attempts: int = 3,
                 on_event=None, metrics=None,
                 backoff_base_s: float = 0.02,
                 backoff_cap_s: float = 0.25,
                 backoff_seed: Optional[int] = None,
                 sleep=time.sleep):
        # a pre-built FrameReader (e.g. SocketFrameReader) passes
        # through; anything else is assumed to be a readable file/fd
        self._reader = (read_file if isinstance(read_file, FrameReader)
                        else FrameReader(read_file))
        self._w = write_file
        self.proc = proc
        self.timeout_s = float(timeout_s)
        self.max_attempts = int(max_attempts)
        self._seq = itertools.count(1)
        self.retransmits = 0
        self.timeouts = 0
        self.corrupt_replies = 0
        self.closed = False
        # retransmit backoff (ISSUE 20): attempt k waits
        # uniform(0, min(cap, base * 2**(k-1))) before resending, so a
        # fleet's links healing together don't retransmit in lockstep.
        # Seeded per link by the fleet (backoff_seed=replica_id) — the
        # delays a drill observes are reproducible.
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self._backoff_rng = random.Random(
            0x5EED if backoff_seed is None else int(backoff_seed))
        self._sleep = sleep
        self.backoffs = 0
        self.backoff_s = 0.0
        # optional observer hook (ISSUE 17): called as
        # on_event(event, op) for "retransmit"/"timeout"/"corrupt" —
        # the fleet pins these onto the merged trace as instants.
        # Best-effort by contract: an observer bug must never turn
        # into a transport failure.
        self.on_event = on_event
        # optional metrics registry handle (ISSUE 19): a MetricsHub or
        # a link-scoped facade (the fleet passes scoped(link=<id>)).
        # When set, every classified failure increments a registry
        # counter AT THE SAME SITE as the attribute counter (so the
        # stats()/registry totals are equal by construction), wire
        # bytes/frames are counted exactly, and each successful
        # request/reply round trip lands in a per-link RTT histogram.
        # None = the attribute counters alone, unchanged.
        self.metrics = metrics

    def _notify(self, event: str, op: str) -> None:
        if self.on_event is not None:
            try:
                self.on_event(event, op)
            except Exception:
                _log.exception("transport on_event observer failed")

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def request(self, op: str, *, timeout_s: Optional[float] = None,
                max_attempts: Optional[int] = None,
                blobs: Optional[List[bytes]] = None,
                **payload) -> Dict[str, Any]:
        """Send ``{op, seq, **payload}`` and return the matching reply.
        Timeouts and corrupt replies retransmit the same seq up to
        ``max_attempts`` total tries (the child's seq cache makes the
        retry safe); the last classified error raises if every attempt
        fails. A closed pipe raises immediately — retransmitting into a
        dead process is noise.

        ``blobs`` ride as binary frames immediately after the message
        (which declares them via ``nblobs``); a retransmit resends the
        message AND its blobs — the exchange is atomic by seq, and the
        child consumes declared blobs even on a dedupe-replay hit. A
        reply declaring ``nblobs`` gets its payloads attached as
        ``reply["blobs"]`` (a list of ``bytes``)."""
        if self.closed:
            raise TransportClosed("transport already closed")
        seq = next(self._seq)
        msg = {"op": op, "seq": seq, **payload}
        if blobs:
            msg["nblobs"] = len(blobs)
        attempts = self.max_attempts if max_attempts is None \
            else int(max_attempts)
        wait = self.timeout_s if timeout_s is None else float(timeout_s)
        last_err: Optional[TransportError] = None
        m = self.metrics
        for attempt in range(max(1, attempts)):
            if attempt:
                # full-jitter capped exponential backoff BEFORE the
                # retransmit: a transient outage healing under load
                # must not see every link's retry at once
                delay = self._backoff_rng.uniform(
                    0.0, min(self.backoff_cap_s,
                             self.backoff_base_s
                             * (2.0 ** (attempt - 1))))
                if delay > 0.0:
                    self.backoffs += 1
                    self.backoff_s += delay
                    if m is not None:
                        m.counter("transport_backoff_seconds",
                                  "seconds slept in retransmit "
                                  "backoff").inc(delay)
                    self._sleep(delay)
                self.retransmits += 1
                if m is not None:
                    m.counter("transport_retransmits",
                              "same-seq retries after a classified "
                              "delivery failure").inc()
                self._notify("retransmit", op)
                # the injected fault was the DELIVERY, not the work:
                # the retransmit asks for the cached reply, clean
                msg = {k: v for k, v in msg.items()
                       if not k.startswith("inject_")}
                _log.warning("retransmitting %s seq=%d (attempt %d: %s)",
                             op, seq, attempt + 1, last_err)
            try:
                t0 = time.monotonic()
                rx0 = self._reader.bytes_read
                fx0 = self._reader.frames_read
                sent = write_frame(self._w, msg)
                nframes = 1
                for b in blobs or ():
                    sent += write_binary_frame(self._w, b)
                    nframes += 1
                reply = self._recv_matching(seq, wait)
                if m is not None:
                    m.counter("transport_bytes_out",
                              "framed request bytes written").inc(sent)
                    m.counter("transport_frames_out",
                              "request frames written").inc(nframes)
                    m.counter("transport_bytes_in",
                              "framed reply bytes consumed").inc(
                        self._reader.bytes_read - rx0)
                    m.counter("transport_frames_in",
                              "reply frames consumed").inc(
                        self._reader.frames_read - fx0)
                    # per-connection wire health: the write→matching-
                    # reply round trip, host wall time (real seconds —
                    # RTT is a wire property, not a SimClock one)
                    m.histogram("transport_rtt_ms",
                                "request round-trip time (ms)").observe(
                        (time.monotonic() - t0) * 1e3)
                return reply
            except TransportTimeout as e:
                self.timeouts += 1
                if m is not None:
                    m.counter("transport_timeouts",
                              "requests with no matching reply in "
                              "time").inc()
                self._notify("timeout", op)
                last_err = e
            except TransportCorrupt as e:
                self.corrupt_replies += 1
                if m is not None:
                    m.counter("transport_corrupt_replies",
                              "replies classified corrupt").inc()
                self._notify("corrupt", op)
                last_err = e
            except TransportClosed:
                self.closed = True
                raise
        assert last_err is not None
        raise last_err

    def _recv_matching(self, seq: int, timeout_s: float) -> Dict[str, Any]:
        """Read frames until one carries ``seq`` (stale replies from an
        earlier timed-out exchange are drained and dropped — including
        any blobs they declared, which would otherwise desynchronize the
        stream), bounded by one shared deadline."""
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TransportTimeout(f"no reply for seq={seq}")
            reply = self._reader.read_frame(timeout_s=remaining)
            declared = int(reply.get("nblobs") or 0)
            if declared:
                reply_blobs = []
                for _ in range(declared):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TransportTimeout(
                            f"reply blobs incomplete for seq={seq}")
                    reply_blobs.append(
                        self._reader.read_binary_frame(
                            timeout_s=remaining))
                reply["blobs"] = reply_blobs
            if reply.get("seq") == seq:
                return reply
            _log.warning("dropping stale reply seq=%s (awaiting %d)",
                         reply.get("seq"), seq)

    def close(self) -> None:
        """Close BOTH pipe ends — workers are append-only tombstones in
        the fleet, so a leaked read fd per dead/released replica would
        accumulate for the process lifetime of an elastic fleet."""
        self.closed = True
        for f in (self._w, self._reader._f):
            try:
                f.close()
            except OSError:
                pass


def spawn_replica_process(spec: Dict[str, Any], *, stderr=None,
                          env: Optional[Dict[str, str]] = None,
                          connect: Optional[str] = None
                          ) -> subprocess.Popen:
    """Launch ``python -m paddle_tpu.serve.replica_proc`` with ``spec``
    on argv. In pipe mode (default) stdin/stdout are the transport (the
    child re-points its fd 1 at stderr before any library can print to
    it); wrap the Popen's pipes in a :class:`ReplicaTransport`. With
    ``connect="host:port"`` the child dials the fleet's :func:`listen`
    socket instead — stdin is devnull and stdout is left for logs, and
    the caller pairs the Popen with its :func:`accept_connection`
    arrival. The same child binary serves both; a REMOTE host simply
    runs it by hand against a routable address."""
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    child_env = dict(os.environ if env is None else env)
    child_env["PYTHONPATH"] = repo_root + os.pathsep + \
        child_env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "paddle_tpu.serve.replica_proc",
           "--spec", json.dumps(spec)]
    if connect is not None:
        cmd += ["--connect", connect]
        return subprocess.Popen(cmd, stdin=subprocess.DEVNULL,
                                stdout=stderr, stderr=stderr,
                                env=child_env)
    return subprocess.Popen(cmd, stdin=subprocess.PIPE,
                            stdout=subprocess.PIPE, stderr=stderr,
                            env=child_env)
