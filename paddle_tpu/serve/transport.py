"""Submit/complete IPC transport for process-isolated serving replicas
(ISSUE 13).

PR 11 drew the replica seam — the router balances on heartbeat-carried
load, delivery is rid-keyed and idempotent, death is observed from file
staleness — but every replica was still an in-process object, so a real
segfault or OOM kill in one engine took the whole fleet down. This
module is the wire that lets a replica live in its own process: a
length-prefixed JSON frame protocol over the child's stdin/stdout pipes,
with the failure modes a real RPC layer has, each CLASSIFIED instead of
crashing the router:

- **Framing**: ``u32 big-endian length || UTF-8 JSON payload``. A frame
  whose length field is absurd or whose body fails to parse raises
  :class:`TransportCorrupt` — a garbled reply is an error VERDICT the
  caller can act on (retransmit), never a router exception.
- **Per-message timeout**: every :meth:`ReplicaTransport.request` waits
  a bounded time for the matching reply (``select`` on the pipe fd). A
  hung or dead child surfaces as :class:`TransportTimeout` /
  :class:`TransportClosed`; the DEATH verdict still belongs to the
  heartbeat file going stale (the PR-11 doctrine — observed, never
  announced), the transport just stops waiting.
- **At-least-once delivery, seq-numbered**: requests carry a
  monotonically increasing ``seq``; on timeout or corruption the sender
  retransmits the SAME seq (injection flags stripped — the fault was
  the delivery, not the work). The child dedupes by seq and replays its
  cached reply, so a lost/garbled REPLY never re-executes the work, and
  a lost REQUEST simply runs on the retransmit. Above this sits the
  fleet's rid-keyed idempotency (PR 11), so even a duplicate that slips
  a cold cache is dropped at the replica boundary.

Fault injection rides the messages themselves: the fleet (which owns the
:class:`~paddle_tpu.train.faults.FaultSchedule`) sets ``inject_drop_reply``
/ ``inject_corrupt_reply`` flags on a tick request, and the CHILD enacts
them — processing the work, then losing or garbling the reply — so the
drill exercises the real timeout/corrupt/retransmit/dedupe path end to
end, not a parent-side simulation of it.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import select
import struct
import subprocess
import sys
import time
from typing import Any, Dict, Optional

__all__ = ["TransportError", "TransportTimeout", "TransportCorrupt",
           "TransportClosed", "encode_frame", "write_frame", "FrameReader",
           "ReplicaTransport", "spawn_replica_process", "MAX_FRAME_BYTES"]

_log = logging.getLogger("paddle_tpu.serve.transport")

# a frame longer than this is garbage, not a message (the biggest real
# frame is a tick reply carrying a few hundred request records)
MAX_FRAME_BYTES = 1 << 24

_HEADER = struct.Struct(">I")


class TransportError(RuntimeError):
    """Base class for classified transport failures. ``kind`` is the
    machine-readable verdict carried into telemetry."""
    kind = "error"


class TransportTimeout(TransportError):
    """No (matching) reply within the per-message timeout — a hung child
    or a lost reply. Retransmit or let the heartbeat verdict decide."""
    kind = "timeout"


class TransportCorrupt(TransportError):
    """A frame arrived but is not a message: absurd length prefix or a
    body that fails to parse. Classified, never raised through the
    fleet tick as a crash."""
    kind = "corrupt"


class TransportClosed(TransportError):
    """The pipe is gone (EOF / EPIPE) — the peer process exited or was
    killed. Terminal for this transport; the heartbeat file goes stale
    on its own schedule."""
    kind = "closed"


def _json_default(o):
    """Prompts and lengths often arrive as numpy scalars; their
    ``item()`` is the plain-python value JSON wants."""
    if hasattr(o, "item"):
        return o.item()
    raise TypeError(f"not JSON-serializable: {type(o).__name__}")


def encode_frame(obj: Dict[str, Any]) -> bytes:
    """One message as wire bytes: length prefix + compact JSON."""
    body = json.dumps(obj, separators=(",", ":"),
                      default=_json_default).encode("utf-8")
    if len(body) > MAX_FRAME_BYTES:
        raise ValueError(f"frame too large: {len(body)} bytes")
    return _HEADER.pack(len(body)) + body


def write_frame(fobj, obj: Dict[str, Any]) -> None:
    """Write one frame and flush. Pipe failures raise
    :class:`TransportClosed`."""
    try:
        fobj.write(encode_frame(obj))
        fobj.flush()
    except (BrokenPipeError, OSError, ValueError) as e:
        raise TransportClosed(f"write failed: {e}") from e


class FrameReader:
    """Incremental frame reader over a pipe/socket file object, with an
    optional per-read timeout (``select`` on the fd — a blocking
    ``read(n)`` would hang exactly when the peer does)."""

    def __init__(self, fobj):
        self._f = fobj
        self._fd = fobj.fileno()
        self._buf = bytearray()

    def read_frame(self, timeout_s: Optional[float] = None
                   ) -> Dict[str, Any]:
        """Read one frame. Raises :class:`TransportTimeout` when no
        complete frame arrives in ``timeout_s`` (partial bytes stay
        buffered for the next call), :class:`TransportCorrupt` on a
        garbage length or unparseable body, :class:`TransportClosed` on
        EOF."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        header = self._read_exact(_HEADER.size, deadline)
        (n,) = _HEADER.unpack(header)
        if n > MAX_FRAME_BYTES:
            # the stream is desynchronized beyond repair once the length
            # field is garbage; classify rather than read 4GB
            raise TransportCorrupt(f"frame length {n} exceeds "
                                   f"{MAX_FRAME_BYTES}")
        body = self._read_exact(n, deadline)
        try:
            return json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, ValueError) as e:
            raise TransportCorrupt(f"unparseable frame body: {e}") from e

    def _read_exact(self, n: int, deadline: Optional[float]) -> bytes:
        while len(self._buf) < n:
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TransportTimeout(
                        f"no frame within timeout ({len(self._buf)}/{n} "
                        f"bytes buffered)")
                ready, _, _ = select.select([self._fd], [], [], remaining)
                if not ready:
                    raise TransportTimeout(
                        f"no frame within timeout ({len(self._buf)}/{n} "
                        f"bytes buffered)")
            try:
                chunk = os.read(self._fd, 65536)
            except OSError as e:
                raise TransportClosed(f"read failed: {e}") from e
            if not chunk:
                raise TransportClosed("EOF")
            self._buf += chunk
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out


class ReplicaTransport:
    """The parent half of the submit/complete channel to one replica
    process: seq-numbered requests, per-message timeout, bounded
    retransmission, and counters for every classified failure (they ride
    into ``ServingFleet.stats()``)."""

    def __init__(self, read_file, write_file, *, proc=None,
                 timeout_s: float = 2.0, max_attempts: int = 3,
                 on_event=None):
        self._reader = FrameReader(read_file)
        self._w = write_file
        self.proc = proc
        self.timeout_s = float(timeout_s)
        self.max_attempts = int(max_attempts)
        self._seq = itertools.count(1)
        self.retransmits = 0
        self.timeouts = 0
        self.corrupt_replies = 0
        self.closed = False
        # optional observer hook (ISSUE 17): called as
        # on_event(event, op) for "retransmit"/"timeout"/"corrupt" —
        # the fleet pins these onto the merged trace as instants.
        # Best-effort by contract: an observer bug must never turn
        # into a transport failure.
        self.on_event = on_event

    def _notify(self, event: str, op: str) -> None:
        if self.on_event is not None:
            try:
                self.on_event(event, op)
            except Exception:
                _log.exception("transport on_event observer failed")

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def request(self, op: str, *, timeout_s: Optional[float] = None,
                max_attempts: Optional[int] = None,
                **payload) -> Dict[str, Any]:
        """Send ``{op, seq, **payload}`` and return the matching reply.
        Timeouts and corrupt replies retransmit the same seq up to
        ``max_attempts`` total tries (the child's seq cache makes the
        retry safe); the last classified error raises if every attempt
        fails. A closed pipe raises immediately — retransmitting into a
        dead process is noise."""
        if self.closed:
            raise TransportClosed("transport already closed")
        seq = next(self._seq)
        msg = {"op": op, "seq": seq, **payload}
        attempts = self.max_attempts if max_attempts is None \
            else int(max_attempts)
        wait = self.timeout_s if timeout_s is None else float(timeout_s)
        last_err: Optional[TransportError] = None
        for attempt in range(max(1, attempts)):
            if attempt:
                self.retransmits += 1
                self._notify("retransmit", op)
                # the injected fault was the DELIVERY, not the work:
                # the retransmit asks for the cached reply, clean
                msg = {k: v for k, v in msg.items()
                       if not k.startswith("inject_")}
                _log.warning("retransmitting %s seq=%d (attempt %d: %s)",
                             op, seq, attempt + 1, last_err)
            try:
                write_frame(self._w, msg)
                return self._recv_matching(seq, wait)
            except TransportTimeout as e:
                self.timeouts += 1
                self._notify("timeout", op)
                last_err = e
            except TransportCorrupt as e:
                self.corrupt_replies += 1
                self._notify("corrupt", op)
                last_err = e
            except TransportClosed:
                self.closed = True
                raise
        assert last_err is not None
        raise last_err

    def _recv_matching(self, seq: int, timeout_s: float) -> Dict[str, Any]:
        """Read frames until one carries ``seq`` (stale replies from an
        earlier timed-out exchange are drained and dropped), bounded by
        one shared deadline."""
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TransportTimeout(f"no reply for seq={seq}")
            reply = self._reader.read_frame(timeout_s=remaining)
            if reply.get("seq") == seq:
                return reply
            _log.warning("dropping stale reply seq=%s (awaiting %d)",
                         reply.get("seq"), seq)

    def close(self) -> None:
        """Close BOTH pipe ends — workers are append-only tombstones in
        the fleet, so a leaked read fd per dead/released replica would
        accumulate for the process lifetime of an elastic fleet."""
        self.closed = True
        for f in (self._w, self._reader._f):
            try:
                f.close()
            except OSError:
                pass


def spawn_replica_process(spec: Dict[str, Any], *, stderr=None,
                          env: Optional[Dict[str, str]] = None
                          ) -> subprocess.Popen:
    """Launch ``python -m paddle_tpu.serve.replica_proc`` with ``spec``
    on argv, wired for framing: stdin/stdout are the transport (the
    child re-points its fd 1 at stderr before any library can print to
    it). Returns the Popen; wrap its pipes in a
    :class:`ReplicaTransport`."""
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    child_env = dict(os.environ if env is None else env)
    child_env["PYTHONPATH"] = repo_root + os.pathsep + \
        child_env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "paddle_tpu.serve.replica_proc",
           "--spec", json.dumps(spec)]
    return subprocess.Popen(cmd, stdin=subprocess.PIPE,
                            stdout=subprocess.PIPE, stderr=stderr,
                            env=child_env)
