"""Network chaos plane for the serving fleet (ISSUE 20).

The fleet's fault drills so far injected one-shot failures *inside* the
protocol (``inject_drop_reply``, ``inject_corrupt_reply``) — the child
does the work and loses the reply. Real networks misbehave *between*
the endpoints instead: frames are delayed, rate-limited, dropped, or
cut off entirely for a window, often in ONE direction only. This module
is a fault shim at the exact frame seam the transport already defines —
:class:`~paddle_tpu.serve.transport.SocketWriter` on the way out,
:class:`~paddle_tpu.serve.transport.FrameReader` on the way in — so
every pathology is enacted on real wire bytes and surfaces through the
real evidence chain (reply timeout → retransmit → exhausted retries →
``transport_down`` → heartbeat staleness → death verdict), never as a
parent-side simulation.

Composable per-link impairments (:class:`LinkChaos`):

- **one-way delay**: a constant or uniform-sampled hold per message,
  plus jitter — enacted as a bounded real sleep at the seam (the
  transport is synchronous request/reply, so holding the frame and
  holding the caller are the same latency);
- **bandwidth throttle**: serialization time ``bytes*8/bps`` added to
  the hold — big KV-page blobs pay proportionally more than ticks;
- **drop probability**: the frame is consumed and never delivered;
- **partition windows**: ``(start_s, end_s[, direction])`` intervals of
  100% loss, per direction — the asymmetric ("I can hear you but you
  cannot hear me") case that manufactures false deaths;
- **link flap**: a ``(period_s, down_s[, start_s])`` square wave of
  short outages — the heartbeat-damping drill's signal.

Determinism: window/flap verdicts are evaluated against the FLEET
clock (``SimClock`` in drills — :meth:`NetworkChaos.bind`), and random
verdicts (drop draws, delay samples) come from per-``(link,
direction)`` :class:`random.Random` streams derived from one seed.
Because the fleet is single-threaded and synchronous, two runs with the
same seed, schedule and workload draw identical verdict sequences —
:meth:`NetworkChaos.stats` is assertable across runs. Frames are
protocol-coherent units: a dropped JSON message takes its declared
binary payload frames down with it (on both seams), so chaos never
desynchronizes the stream — it only loses exchanges, exactly like a
lossy network under a framing protocol.

Like :class:`~paddle_tpu.train.faults.FaultSchedule`, a
:class:`NetworkChaos` is ``describe()``-able: the full per-link
configuration plus the verdict counters, for drill provenance records.

``ServingFleet(replica_mode="socket", chaos=NetworkChaos(...))`` wraps
each replica link at spawn; ``chaos=None`` (the default) constructs the
stock reader/writer classes — the chaos-off fleet is byte-identical to
the pre-chaos transport.
"""

from __future__ import annotations

import random
import struct
import time
from typing import Any, Dict, List, Optional, Tuple, Union

from . import transport as transport_lib

__all__ = ["LinkChaos", "NetworkChaos", "ChaosWriter", "ChaosFrameReader"]

_HEADER = struct.Struct(">I")

# direction names are from the PARENT's point of view: "send" impairs
# parent→child frames (requests), "recv" impairs child→parent frames
# (replies and their payload blobs)
_DIRECTIONS = ("send", "recv", "both")


def _norm_direction(d: str, what: str) -> str:
    if d not in _DIRECTIONS:
        raise ValueError(f"{what} direction must be one of "
                         f"{_DIRECTIONS}, got {d!r}")
    return d


class LinkChaos:
    """The impairment profile of ONE parent↔replica link. All fields
    compose; everything defaults off, so ``LinkChaos()`` is a transparent
    link.

    Args:
      delay_s: one-way delay per message — a float, or a ``(lo, hi)``
        pair sampled uniformly per message.
      jitter_s: extra uniform ``[0, jitter_s)`` delay per message.
      drop_p: per-message drop probability.
      bandwidth_bps: link rate; each frame adds ``len*8/bps``
        serialization time to its hold (None = infinite).
      partitions: iterable of ``(start_s, end_s)`` or ``(start_s,
        end_s, direction)`` windows of total loss, in fleet-clock
        seconds. Direction defaults to ``"both"``.
      flap: ``(period_s, down_s)`` or ``(period_s, down_s, start_s)``
        — from ``start_s`` on, the link is down for the first
        ``down_s`` of every ``period_s``.
      direction: which direction the delay/drop/bandwidth impairments
        apply to (partitions carry their own; the flap follows this).
    """

    def __init__(self, *, delay_s: Union[float, Tuple[float, float]]
                 = 0.0, jitter_s: float = 0.0, drop_p: float = 0.0,
                 bandwidth_bps: Optional[float] = None,
                 partitions=(), flap: Optional[Tuple] = None,
                 direction: str = "both"):
        self.delay_s = delay_s
        self.jitter_s = float(jitter_s)
        self.drop_p = float(drop_p)
        if not 0.0 <= self.drop_p <= 1.0:
            raise ValueError(f"drop_p must be in [0, 1], got {drop_p}")
        self.bandwidth_bps = (None if bandwidth_bps is None
                              else float(bandwidth_bps))
        self.direction = _norm_direction(direction, "link")
        self.partitions: List[Tuple[float, float, str]] = []
        for win in partitions:
            if len(win) == 2:
                s, e = win
                d = "both"
            else:
                s, e, d = win
            self.partitions.append(
                (float(s), float(e), _norm_direction(d, "partition")))
        self.flap: Optional[Tuple[float, float, float]] = None
        if flap is not None:
            period = float(flap[0])
            down = float(flap[1])
            start = float(flap[2]) if len(flap) > 2 else 0.0
            if period <= 0 or not 0 <= down <= period:
                raise ValueError(
                    f"flap needs period_s > 0 and 0 <= down_s <= "
                    f"period_s, got {flap!r}")
            self.flap = (period, down, start)

    def applies(self, direction: str) -> bool:
        return self.direction in ("both", direction)

    def down_reason(self, direction: str,
                    now: float) -> Optional[str]:
        """Why the link is cut for ``direction`` at ``now`` (None =
        up). Partitions win over flaps in the counters — a window is
        the deliberate drill, the flap is background weather."""
        for s, e, d in self.partitions:
            if s <= now < e and d in ("both", direction):
                return "partition"
        if self.flap is not None and self.applies(direction):
            period, down, start = self.flap
            if now >= start and (now - start) % period < down:
                return "flap"
        return None

    def sample_delay(self, rng: random.Random) -> float:
        if isinstance(self.delay_s, (tuple, list)):
            lo, hi = self.delay_s
            d = rng.uniform(float(lo), float(hi))
        else:
            d = float(self.delay_s)
        if self.jitter_s > 0.0:
            d += rng.uniform(0.0, self.jitter_s)
        return d

    def describe(self) -> Dict[str, Any]:
        return {"delay_s": self.delay_s, "jitter_s": self.jitter_s,
                "drop_p": self.drop_p,
                "bandwidth_bps": self.bandwidth_bps,
                "partitions": [list(w) for w in self.partitions],
                "flap": list(self.flap) if self.flap else None,
                "direction": self.direction}


class NetworkChaos:
    """The fleet-wide chaos plane: per-link :class:`LinkChaos` profiles,
    one seed, one verdict ledger.

    Args:
      seed: master seed; each ``(link, direction)`` random stream is
        derived from it deterministically.
      links: ``{replica_id: LinkChaos}`` — links without an entry fall
        back to ``default`` (or pass through untouched).
      default: profile for unlisted links (None = transparent).
      max_sleep_s: cap on any single REAL sleep enacted for a delay
        hold (the full sampled hold is still accounted in the stats —
        the cap protects CI wall time, not the model).
    """

    def __init__(self, seed: int = 0, *,
                 links: Optional[Dict[int, LinkChaos]] = None,
                 default: Optional[LinkChaos] = None,
                 max_sleep_s: float = 0.05):
        self.seed = int(seed)
        self.links = dict(links or {})
        self.default = default
        self.max_sleep_s = float(max_sleep_s)
        self.clock = None
        self._rngs: Dict[Tuple[int, str], random.Random] = {}
        # the verdict ledger: everything the plane did, per link and
        # per direction — the determinism drill asserts equality of
        # this whole dict across two same-seed runs
        self.frames_dropped = 0
        self.bytes_dropped = 0
        self.frames_delayed = 0
        self.delay_injected_s = 0.0
        self.drop_reasons: Dict[str, int] = {}
        self.per_link: Dict[int, Dict[str, Any]] = {}

    # -- wiring ------------------------------------------------------------

    def bind(self, clock) -> None:
        """Adopt the fleet's clock (``SimClock`` in drills) — window
        and flap verdicts are evaluated against it."""
        self.clock = clock

    def _now(self) -> float:
        return float(self.clock()) if self.clock is not None else 0.0

    def link(self, link_id: int) -> Optional[LinkChaos]:
        return self.links.get(int(link_id), self.default)

    def _rng(self, link_id: int, direction: str) -> random.Random:
        key = (int(link_id), direction)
        rng = self._rngs.get(key)
        if rng is None:
            # distinct deterministic streams per link and direction, so
            # the draw sequence on one link never depends on traffic
            # interleaving with another
            rng = random.Random(
                (self.seed * 1000003 + key[0] * 8191
                 + (17 if direction == "send" else 31)) & 0x7FFFFFFF)
            self._rngs[key] = rng
        return rng

    def wrap_writer(self, link_id: int, writer):
        """The outbound seam: a :class:`ChaosWriter` when this link has
        a profile, the writer untouched otherwise (chaos-off links are
        byte-identical)."""
        if self.link(link_id) is None:
            return writer
        return ChaosWriter(writer, self, int(link_id))

    # -- verdicts ----------------------------------------------------------

    def _bucket(self, link_id: int) -> Dict[str, Any]:
        b = self.per_link.get(int(link_id))
        if b is None:
            b = {"dropped_send": 0, "dropped_recv": 0,
                 "delayed": 0, "delay_s": 0.0, "bytes_dropped": 0}
            self.per_link[int(link_id)] = b
        return b

    def verdict(self, link_id: int, direction: str,
                nbytes: int) -> Tuple[str, float]:
        """One message verdict: ``("drop", 0.0)`` or ``("deliver",
        hold_s)``. Counters are recorded here so both seams share one
        ledger. Binary payload frames do not get their own verdict —
        they inherit their message's (see the seam classes) — but their
        bytes do pay the bandwidth serialization time via
        :meth:`serialization_s`."""
        lc = self.link(link_id)
        if lc is None:
            return ("deliver", 0.0)
        now = self._now()
        down = lc.down_reason(direction, now)
        reason = down
        if reason is None and lc.applies(direction) and lc.drop_p > 0.0:
            if self._rng(link_id, direction).random() < lc.drop_p:
                reason = "drop"
        if reason is not None:
            self.frames_dropped += 1
            self.bytes_dropped += int(nbytes)
            self.drop_reasons[reason] = \
                self.drop_reasons.get(reason, 0) + 1
            b = self._bucket(link_id)
            b[f"dropped_{direction}"] += 1
            b["bytes_dropped"] += int(nbytes)
            return ("drop", 0.0)
        hold = 0.0
        if lc.applies(direction):
            hold = lc.sample_delay(self._rng(link_id, direction))
            hold += self.serialization_s(link_id, direction, nbytes)
        if hold > 0.0:
            self.frames_delayed += 1
            self.delay_injected_s += hold
            b = self._bucket(link_id)
            b["delayed"] += 1
            b["delay_s"] += hold
        return ("deliver", hold)

    def serialization_s(self, link_id: int, direction: str,
                        nbytes: int) -> float:
        lc = self.link(link_id)
        if (lc is None or lc.bandwidth_bps is None
                or not lc.applies(direction)):
            return 0.0
        return (int(nbytes) * 8.0) / lc.bandwidth_bps

    def hold(self, hold_s: float) -> None:
        """Enact a delay hold as a bounded real sleep (the synchronous
        transport makes holding the frame and holding the caller the
        same observable latency)."""
        if hold_s > 0.0:
            time.sleep(min(hold_s, self.max_sleep_s))

    # -- provenance --------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        return {"frames_dropped": self.frames_dropped,
                "bytes_dropped": self.bytes_dropped,
                "frames_delayed": self.frames_delayed,
                "delay_injected_s": round(self.delay_injected_s, 9),
                "drop_reasons": dict(sorted(self.drop_reasons.items())),
                "per_link": {k: {**v, "delay_s": round(v["delay_s"], 9)}
                             for k, v in sorted(self.per_link.items())}}

    def describe(self) -> Dict[str, Any]:
        """Full provenance, FaultSchedule-style: the configuration that
        was armed plus the verdicts it produced."""
        return {"seed": self.seed,
                "max_sleep_s": self.max_sleep_s,
                "default": (self.default.describe()
                            if self.default else None),
                "links": {k: v.describe()
                          for k, v in sorted(self.links.items())},
                "stats": self.stats()}


class ChaosWriter:
    """Outbound ("send") seam: wraps the duck-typed writer the frame
    writers use. Each ``write()`` call is exactly one wire frame
    (:func:`~paddle_tpu.serve.transport.write_frame` /
    ``write_binary_frame`` write whole frames); a JSON frame draws a
    fresh verdict, a binary frame (the high bit of its length prefix)
    inherits the verdict of the message it rides behind — the protocol
    invariant that blobs immediately follow their declaring message
    makes the exchange one coherent unit, delivered or lost whole."""

    def __init__(self, inner, chaos: NetworkChaos, link_id: int):
        self.inner = inner
        self.chaos = chaos
        self.link_id = int(link_id)
        self._blob_verdict = "deliver"   # verdict blobs inherit

    def write(self, data: bytes) -> None:
        binary = False
        if len(data) >= _HEADER.size:
            (n,) = _HEADER.unpack(bytes(data[:_HEADER.size]))
            binary = bool(n & transport_lib.BINARY_FLAG)
        if binary:
            if self._blob_verdict == "drop":
                self.chaos.bytes_dropped += len(data)
                self.chaos._bucket(self.link_id)["bytes_dropped"] += \
                    len(data)
                return
            # payload frames pay their own serialization time (the
            # throttle is what makes big KV blobs slower than ticks)
            ser = self.chaos.serialization_s(self.link_id, "send",
                                             len(data))
            if ser > 0.0:
                self.chaos.delay_injected_s += ser
                self.chaos._bucket(self.link_id)["delay_s"] += ser
                self.chaos.hold(ser)
            self.inner.write(data)
            return
        action, hold = self.chaos.verdict(self.link_id, "send",
                                          len(data))
        self._blob_verdict = action
        if action == "drop":
            return
        self.chaos.hold(hold)
        self.inner.write(data)

    def flush(self) -> None:
        self.inner.flush()

    def close(self) -> None:
        self.inner.close()


class ChaosFrameReader(transport_lib.SocketFrameReader):
    """Inbound ("recv") seam: a :class:`~paddle_tpu.serve.transport.
    SocketFrameReader` whose message reads pass through the chaos
    verdict. A dropped message consumes its declared payload blobs too
    (stream sync survives — only the exchange is lost); a delayed one
    holds before delivery. Blob reads (``allow_binary``) pass through
    untouched: they belong to an already-delivered message."""

    def __init__(self, sock, chaos: NetworkChaos, link_id: int):
        super().__init__(sock)
        self.chaos = chaos
        self.link_id = int(link_id)

    def read_frame(self, timeout_s: Optional[float] = None,
                   allow_binary: bool = False):
        if allow_binary:
            # a declared payload of a message that was already
            # delivered: chaos judged the exchange at the message
            return super().read_frame(timeout_s=timeout_s,
                                      allow_binary=True)
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        while True:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise transport_lib.TransportTimeout(
                        "no frame within timeout (chaos)")
            rx0 = self.bytes_read
            msg = super().read_frame(timeout_s=remaining)
            nbytes = self.bytes_read - rx0
            action, hold = self.chaos.verdict(self.link_id, "recv",
                                              nbytes)
            if action == "deliver":
                self.chaos.hold(hold)
                return msg
            # dropped: consume the declared blobs so the next read
            # starts on a frame boundary, then keep waiting
            for _ in range(int(msg.get("nblobs") or 0)):
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise transport_lib.TransportTimeout(
                            "no frame within timeout (chaos)")
                blob = super().read_frame(timeout_s=remaining,
                                          allow_binary=True)
                self.chaos.bytes_dropped += (len(blob) + _HEADER.size
                                             if isinstance(
                                                 blob, (bytes,
                                                        bytearray))
                                             else 0)
