"""Paged KV cache — the serving runtime's device-memory manager.

Long-context serving is memory-bound on the KV cache, and naive per-request
contiguous allocation at ``max_len`` wastes most of it: concurrent sequences
have ragged lengths, so reserving the worst case per slot strands HBM
(PagedAttention's motivating measurement — PAPERS.md [S1]). The fix is the
OS page-table design: the cache is a single pool of fixed-size **blocks**
(``[num_blocks, block_size, heads, head_dim]`` per layer) and each sequence
holds an ordered **block table** of pool indices; allocation is
block-granular, so waste is bounded by one partial block per sequence and
freed blocks are immediately reusable by any other request.

Split of responsibilities (the framework's static-shapes contract):

- **Host side, dynamic**: :class:`BlockAllocator` (free-list alloc/free)
  and :class:`PagedKVCache` (device pools + the authoritative host mirror
  of block tables and lengths). Admission/eviction mutate ONLY these small
  host arrays between decode ticks — nothing here is traced.
- **Device side, pure**: :func:`gather_pages`, :func:`scatter_prefill`,
  :func:`scatter_token` — ``jnp``-pure gather/scatter the compiled
  prefill/decode programs call with fixed shapes. Block tables enter the
  compiled step as ordinary int32 operands, so the program never retraces
  as sequences come and go.

Block id 0 is reserved as the **null block**: unallocated table entries and
masked-off scatter rows all target it, so every scatter is total (no
dynamic shapes, no OOB) and its contents are unspecified-but-finite —
reads through it are always masked by the length before use.

**Copy-on-write prefix sharing** (ISSUE 12, PAPERS.md [S1][S4]): blocks
are REFCOUNTED, and a :class:`PrefixCache` maps content hashes of
block-aligned prompt prefixes to the physical blocks already holding
their KV. Admission walks the new prompt's full-block chain through the
cache; every hit is adopted by reference (incref — zero new HBM, zero
prefill scatter for those rows), and only the divergent tail allocates
fresh blocks. A sharer never writes a multiply-owned block: the engine
FORKS it first (allocate + device-copy the one block + decref the
original) — the classic COW page-table move, confined to the partial
boundary block at the divergence point. Eviction decrefs; a block
returns to the free list exactly once, when its LAST owner lets go, and
its cache entries are invalidated at that same moment — sharing is
between concurrently-resident sequences, so churn can never serve stale
pool bytes.

**Int8 KV quantization** (ISSUE 14, the capacity lever): with
``kv_dtype="int8"`` each pool stores symmetric int8 values plus a
per-block scale page ``[L, num_blocks, block_size, H]`` (one f32 scale
per token row per head — scales live at block granularity beside the
pools, per-row within the block so incremental scatters NEVER requantize
resident tokens). Quantization happens on scatter
(:func:`quantize_rows` inside the ``*_pages`` wrappers) and
dequantization inside the consumer — the Pallas kernels rescale blocks
in VMEM, the XLA path in :func:`gather_pages` — so HBM holds ~1 byte
per KV element instead of 4 and resident capacity roughly triples at
equal pool bytes (``kv_bytes_per_token`` is the exact accounting).

**Radix retention** (ISSUE 14, RadixAttention [S4]): with sharing on, a
prefix-cache-registered block whose LAST owner decrefs moves to a
**retained LRU** instead of the free list — its cache entries stay
valid, so a follow-up request with the same prompt prefix hits even
when no live sequence shares it. Retained blocks are RECLAIMABLE
capacity: ``alloc`` recycles them lazily (oldest first) only when the
free list runs dry, invalidating their entries at that moment, and
``free_blocks``/``admit_probe`` count ``free + retained`` so
backpressure and the autoscaler's load signal never shed against
capacity that one reclaim away exists. Adopting a retained block
increfs it straight out of the LRU (a *retained hit*).
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

__all__ = ["BlockAllocator", "PagedKVCache", "PrefixCache", "PrefixMatch",
           "gather_pages", "scatter_prefill", "scatter_token",
           "scatter_span", "scatter_prefill_pages", "scatter_token_pages",
           "scatter_span_pages", "quantize_rows", "dequantize_rows",
           "pages_to_blobs", "blobs_to_pages", "NULL_BLOCK"]

# block 0 never holds live data: it is the scatter target for padding rows
# and the gather source for unallocated table entries (always masked)
NULL_BLOCK = 0


# ---------------------------------------------------------------------------
# device side: jnp-pure gather/scatter (called from compiled programs)
# ---------------------------------------------------------------------------

def quantize_rows(kv):
    """Symmetric per-row-per-head int8 quantization of KV projections:
    ``kv [..., hd]`` f32 -> ``(int8 [..., hd], scale [...])`` with
    ``scale = amax/127`` over the head_dim (the finest granularity that
    needs no requantization when later tokens land in the same block —
    the scale-granularity decision, DESIGN_DECISIONS PR-14)."""
    amax = jnp.max(jnp.abs(kv.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(kv / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def dequantize_rows(q, scale):
    """Inverse of :func:`quantize_rows`: ``int8 [..., hd] * scale [...]``
    -> f32 values."""
    return q.astype(jnp.float32) * scale[..., None]


def gather_pages(pages, table):
    """Gather one layer's paged K (or V) into position order.

    ``pages`` ``[N, bs, H, hd]``, ``table`` ``[S, MB]`` int32 ->
    ``[S, MB*bs, H, hd]``: row ``s``'s tokens ``0..len-1`` in order, with
    unspecified (null-block / stale) content beyond the sequence length —
    the attention mask owns that boundary. A quantized pool — the tuple
    ``(int8 values, scales [N, bs, H])`` — gathers DEQUANTIZED f32
    values, so every consumer downstream of the gather is
    dtype-oblivious."""
    if isinstance(pages, tuple):
        vals, scales = pages
        S, MB = table.shape
        _, bs, H, hd = vals.shape
        deq = dequantize_rows(vals[table], scales[table])
        return deq.reshape(S, MB * bs, H, hd)
    S, MB = table.shape
    _, bs, H, hd = pages.shape
    return pages[table].reshape(S, MB * bs, H, hd)


def scatter_prefill(pages, kv, table, length, start=0):
    """Write a prefill's per-layer K (or V) rows into the paged pool.

    ``kv`` ``[B, W, H, hd]`` holds projections for positions ``0..W-1``
    (``W`` = the fixed padded prefill width); only rows in
    ``[start, length)`` are live — the rest are routed to the null
    block. ``start`` (scalar or ``[B]``) masks off a prefix-cache hit:
    shared rows already live in the donor's blocks and a sharer must
    never write a multiply-owned page (the COW discipline). Returns the
    updated pool. ``table`` ``[B, MB]``, ``length`` ``[B]``."""
    B, W = kv.shape[:2]
    bs = pages.shape[1]
    start = jnp.broadcast_to(jnp.asarray(start, jnp.int32), (B,))
    pos = jnp.arange(W, dtype=jnp.int32)
    live = ((pos[None, :] < length[:, None])
            & (pos[None, :] >= start[:, None]))
    blk = jnp.where(live,
                    jnp.take_along_axis(table, pos[None, :] // bs, axis=1),
                    NULL_BLOCK)                                   # [B, W]
    off = jnp.broadcast_to(pos % bs, (B, W))
    return pages.at[blk, off].set(kv)


def scatter_token(pages, kv, table, position, active):
    """Write one decode step's per-layer K (or V) for every slot.

    ``kv`` ``[S, H, hd]`` is the new token's projection per slot;
    ``position`` ``[S]`` the 0-based index it occupies (the sequence
    length BEFORE this token); inactive slots scatter to the null block.
    Returns the updated pool."""
    S = kv.shape[0]
    bs = pages.shape[1]
    blk = jnp.where(active,
                    table[jnp.arange(S), position // bs],
                    NULL_BLOCK)                                   # [S]
    off = position % bs
    return pages.at[blk, off].set(kv)


def scatter_span(pages, kv, table, start, n, write_from=None):
    """Write a span of consecutive tokens' per-layer K (or V) for every
    slot — the multi-token generalization of :func:`scatter_token` used
    by the speculative verify tick and chunked prefill.

    ``kv`` ``[S, Q, H, hd]``: token ``j`` of slot ``s`` lands at
    position ``start[s] + j``; only tokens ``j < n[s]`` are live (the
    rest — draft padding, chunk tail — route to the null block).
    ``write_from`` ``[S]`` (optional) additionally masks positions below
    it — a chunk re-reading a fully shared prefix for its logits must
    not write the co-owned pages. Returns the updated pool."""
    S, Q = kv.shape[:2]
    bs = pages.shape[1]
    MB = table.shape[1]
    pos = start[:, None] + jnp.arange(Q, dtype=jnp.int32)[None, :]  # [S, Q]
    live = jnp.arange(Q, dtype=jnp.int32)[None, :] < n[:, None]
    if write_from is not None:
        live = live & (pos >= write_from[:, None])
    # clip the table index: masked-off rows may point past the table
    # width; they route to the null block anyway
    idx = jnp.clip(pos // bs, 0, MB - 1)
    blk = jnp.where(live, jnp.take_along_axis(table, idx, axis=1),
                    NULL_BLOCK)                                   # [S, Q]
    off = pos % bs
    return pages.at[blk, off].set(kv)


# quant-aware scatter wrappers: a plain pool scatters values as-is; a
# quantized pool (the (values, scales) tuple) quantizes on scatter —
# int8 rows into the value pages, per-row-per-head scales into the
# scale pages with the SAME block/offset routing (the scatter functions
# above are shape-agnostic past the [blocks, block_size] prefix).

def scatter_prefill_pages(pages, kv, table, length, start=0):
    if isinstance(pages, tuple):
        vals, scales = pages
        q, s = quantize_rows(kv)
        return (scatter_prefill(vals, q, table, length, start),
                scatter_prefill(scales, s, table, length, start))
    return scatter_prefill(pages, kv.astype(pages.dtype), table, length,
                           start)


def scatter_token_pages(pages, kv, table, position, active):
    if isinstance(pages, tuple):
        vals, scales = pages
        q, s = quantize_rows(kv)
        return (scatter_token(vals, q, table, position, active),
                scatter_token(scales, s, table, position, active))
    return scatter_token(pages, kv.astype(pages.dtype), table, position,
                         active)


def scatter_span_pages(pages, kv, table, start, n, write_from=None):
    if isinstance(pages, tuple):
        vals, scales = pages
        q, s = quantize_rows(kv)
        return (scatter_span(vals, q, table, start, n, write_from),
                scatter_span(scales, s, table, start, n, write_from))
    return scatter_span(pages, kv.astype(pages.dtype), table, start, n,
                        write_from)


# ---------------------------------------------------------------------------
# cross-replica handoff serialization (ISSUE 18)
# ---------------------------------------------------------------------------
#
# Prefill/decode disaggregation ships a finished prompt's KV pages to a
# decode replica BLOCK BY BLOCK: the paged layout already made the block
# the unit of allocation, sharing and eviction, so it is the natural wire
# unit too — one binary transport frame per block, raw pool bytes (int8
# values + f32 scales when quantized — the receiver adopts them verbatim,
# so dequantization is bit-identical and the quantized wire cost is
# exactly the ~2.7x-smaller `bytes_per_block`). No base64, no JSON.

def pages_to_blobs(kpages, vpages) -> List[bytes]:
    """Serialize exported pages (``[L, nb, bs, H, hd]`` arrays, or
    ``(values, scales)`` tuples when quantized) into one ``bytes`` blob
    per block: K leaves then V leaves, each C-contiguous. The inverse is
    :func:`blobs_to_pages`; each blob is exactly ``bytes_per_block``
    long, which is what the wire-byte accounting audits against."""
    kleaves = list(kpages) if isinstance(kpages, tuple) else [kpages]
    vleaves = list(vpages) if isinstance(vpages, tuple) else [vpages]
    nb = int(np.asarray(kleaves[0]).shape[1])
    out = []
    for j in range(nb):
        parts = [np.ascontiguousarray(np.asarray(a)[:, j]).tobytes()
                 for a in kleaves + vleaves]
        out.append(b"".join(parts))
    return out


def blobs_to_pages(blobs: List[bytes], *, num_layers: int,
                   block_size: int, num_heads: int, head_dim: int,
                   quantized: bool, dtype="float32"):
    """Rebuild ``(kpages, vpages)`` pool page arrays from per-block wire
    blobs. The receiver supplies ITS OWN pool geometry — a blob whose
    length disagrees means the fleet is not homogeneous, and adopting it
    would scatter garbage, so that is a hard :class:`ValueError` (the
    adopt path refuses the handoff; the request resubmits)."""
    if not blobs:
        raise ValueError("handoff carries zero page blobs")
    L, bs, H, hd = num_layers, block_size, num_heads, head_dim
    if quantized:
        specs = [((L, bs, H, hd), np.dtype(np.int8)),
                 ((L, bs, H), np.dtype(np.float32))]
    else:
        specs = [((L, bs, H, hd), np.dtype(dtype))]
    leaf_bytes = [int(np.prod(s)) * d.itemsize for s, d in specs]
    per_blob = 2 * sum(leaf_bytes)
    nleaves = len(specs)
    cols: List[List[np.ndarray]] = [[] for _ in range(2 * nleaves)]
    for blob in blobs:
        if len(blob) != per_blob:
            raise ValueError(
                f"handoff blob is {len(blob)} bytes but this pool's "
                f"geometry expects {per_blob} — replica pool shapes "
                f"disagree")
        off = 0
        for i in range(2 * nleaves):
            shape, d = specs[i % nleaves]
            cols[i].append(np.frombuffer(
                blob, dtype=d, count=int(np.prod(shape)),
                offset=off).reshape(shape))
            off += leaf_bytes[i % nleaves]
    kleaves = [np.stack(cols[i], axis=1) for i in range(nleaves)]
    vleaves = [np.stack(cols[nleaves + i], axis=1)
               for i in range(nleaves)]
    k = tuple(kleaves) if quantized else kleaves[0]
    v = tuple(vleaves) if quantized else vleaves[0]
    return k, v


# ---------------------------------------------------------------------------
# host side: allocation / free (between-tick bookkeeping, never traced)
# ---------------------------------------------------------------------------

class BlockAllocator:
    """Refcounted free-list allocator over pool block ids
    ``1..num_blocks-1`` (block 0 is the reserved null block). FIFO reuse
    keeps churn deterministic — tests pin that re-admitted sequences
    land on recycled blocks.

    Refcounts are what make physical prefix sharing safe: ``alloc``
    hands out blocks at refcount 1, a prefix-cache hit ``incref``\\ s the
    donor's block instead of allocating, and ``decref`` returns a block
    to the free list exactly once — when its LAST owner drops it. The
    legacy ``free`` is a decref loop, so single-owner code paths keep
    their exact historical behavior.

    **Retention** (ISSUE 14): ``decref(block, retain=True)`` parks a
    last-owner block in the retained LRU instead of freeing it — its KV
    pages stay addressable through the prefix cache for future
    admissions. Retained blocks are reclaimable capacity, not leaks:
    ``alloc`` recycles them lazily (oldest retained first, after the
    genuinely-free list) through ``reclaim_hook`` so the owning cache
    invalidates their entries at exactly the recycle moment, and every
    retained block still lands on the free list exactly once per
    retention episode. ``incref`` of a retained block REVIVES it out of
    the LRU at refcount 1 (the retained-hit path)."""

    def __init__(self, num_blocks: int):
        assert num_blocks >= 2, "need at least one non-null block"
        self.num_blocks = num_blocks
        self._free = collections.deque(range(1, num_blocks))
        self._rc: Dict[int, int] = {}
        # retained LRU: block -> True, insertion-ordered (oldest first);
        # rc == 0 for every member, but the block is NOT on the free
        # list — the prefix cache still maps its content
        self._retained: "collections.OrderedDict[int, bool]" = \
            collections.OrderedDict()
        # invoked with a block id the moment a retained block is
        # recycled onto the free list (the cache invalidation weld)
        self.reclaim_hook = None
        self.retained_reclaims = 0
        # cumulative alloc counter: the "fresh blocks" denominator the
        # sharing tests/bench diff against (adoptions don't bump it)
        self.total_allocs = 0

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_retained(self) -> int:
        return len(self._retained)

    @property
    def reclaimable(self) -> int:
        """The pool's REAL spare capacity: free plus lazily-reclaimable
        retained blocks — what admission/backpressure must count."""
        return len(self._free) + len(self._retained)

    def is_retained(self, block: int) -> bool:
        return block in self._retained

    def ref_count(self, block: int) -> int:
        """Current owner count (0 for free and retained blocks)."""
        return self._rc.get(block, 0)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` block ids at refcount 1, or None (and no change)
        if unavailable. The free list serves first (FIFO — churn stays
        deterministic); under pressure, retained blocks are reclaimed
        oldest-first, each invalidated via ``reclaim_hook`` as it is
        recycled."""
        if n > self.reclaimable:
            return None
        while len(self._free) < n:
            blk, _ = self._retained.popitem(last=False)   # LRU victim
            if self.reclaim_hook is not None:
                self.reclaim_hook(blk)
            self._free.append(blk)
            self.retained_reclaims += 1
        got = [self._free.popleft() for _ in range(n)]
        for b in got:
            self._rc[b] = 1
        self.total_allocs += len(got)
        return got

    def incref(self, block: int) -> bool:
        """Adopt an allocated block (a prefix-cache hit: one more owner
        of the same physical pages). A RETAINED block revives out of the
        LRU at refcount 1; returns True exactly for that case (the
        retained-hit signal the telemetry counts)."""
        assert block != NULL_BLOCK, "cannot adopt the null block"
        if block in self._retained:
            del self._retained[block]
            self._rc[block] = 1
            return True
        assert self._rc.get(block, 0) > 0, \
            f"incref of unallocated block {block}"
        self._rc[block] += 1
        return False

    def decref(self, block: int, retain: bool = False) -> bool:
        """Drop one ownership; returns True when this was the LAST owner
        and the block left the refcounted set — onto the free list, or
        into the retained LRU with ``retain=True`` (prefix-registered
        blocks whose KV should outlive the sequence)."""
        assert block != NULL_BLOCK, "cannot free the null block"
        rc = self._rc.get(block, 0)
        assert rc > 0, f"decref of free block {block} (double free)"
        if rc > 1:
            self._rc[block] = rc - 1
            return False
        del self._rc[block]
        if retain:
            self._retained[block] = True
        else:
            self._free.append(block)
        return True

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            self.decref(b)


@dataclasses.dataclass
class PrefixMatch:
    """One admission's prefix-cache verdict: the physical blocks to
    adopt by reference (in table order) and the token count they cover.
    ``partial`` flags that the LAST adopted block is the donor's partial
    boundary block (shared mid-block — the engine must fork it before
    any write lands there: the copy-on-write point)."""
    blocks: List[int]
    length: int
    partial: bool = False

    @property
    def hit_blocks(self) -> int:
        return len(self.blocks)


class PrefixCache:
    """Content-addressed index of resident prompt-prefix KV blocks
    (the RadixAttention idea [S4] at block granularity).

    Keys are CUMULATIVE hashes: ``h_i = H(h_{i-1}, tokens[i*bs:(i+1)*bs])``
    — a chain hit guarantees the whole prefix matches, not just one
    block's tokens, so two prompts can never alias through a colliding
    interior block. Full blocks map ``h_i -> block``; the boundary
    partial block of a registered prompt maps ``(h_parent, tail_tokens)
    -> block`` and is only shared on an EXACT tail match (a duplicate
    prompt — retry storms, identical few-shot calls), because a sharer
    reads every row below its own length and will write the rest: any
    non-exact partial share would fork immediately for zero saved work.

    The cache holds NO references of its own: entries are invalidated
    the moment their block's last owner decrefs it (``PagedKVCache``
    wires the hook), so a recycled block can never serve stale bytes
    and every block still returns to the free list exactly once."""

    def __init__(self, block_size: int):
        self.block_size = block_size
        self._full: Dict[Tuple, int] = {}
        self._partial: Dict[Tuple, int] = {}
        self._by_block: Dict[int, List[Tuple[str, Tuple]]] = {}

    def __len__(self) -> int:
        return len(self._full) + len(self._partial)

    @staticmethod
    def _chain(parent, chunk) -> Tuple:
        return (parent, tuple(int(t) for t in chunk))

    def match(self, tokens: List[int]) -> PrefixMatch:
        """Longest resident prefix of ``tokens``: full-block chain hits,
        then an exact-tail partial boundary hit."""
        bs = self.block_size
        blocks: List[int] = []
        parent: Tuple = ()
        nf = len(tokens) // bs
        for i in range(nf):
            key = self._chain(parent, tokens[i * bs:(i + 1) * bs])
            b = self._full.get(key)
            if b is None:
                break
            blocks.append(b)
            parent = key
        matched = len(blocks) * bs
        rem = tokens[matched:]
        if len(blocks) == nf and rem:
            pb = self._partial.get(self._chain(parent, rem))
            if pb is not None:
                return PrefixMatch(blocks + [pb], len(tokens),
                                   partial=True)
        return PrefixMatch(blocks, matched)

    def register(self, tokens: List[int], blocks: List[int]) -> int:
        """Publish a freshly-prefilled prompt's blocks (table order).
        First writer wins — duplicate content keeps the existing entry
        so concurrent owners converge on ONE physical block chain.
        Returns how many new entries were added."""
        bs = self.block_size
        added = 0
        parent: Tuple = ()
        nf = len(tokens) // bs
        for i in range(nf):
            key = self._chain(parent, tokens[i * bs:(i + 1) * bs])
            if key not in self._full:
                self._full[key] = blocks[i]
                self._by_block.setdefault(blocks[i], []).append(
                    ("full", key))
                added += 1
            parent = key
        rem = tokens[nf * bs:]
        if rem and nf < len(blocks):
            key = self._chain(parent, rem)
            if key not in self._partial:
                self._partial[key] = blocks[nf]
                self._by_block.setdefault(blocks[nf], []).append(
                    ("partial", key))
                added += 1
        return added

    def covers(self, block: int) -> bool:
        """Whether any entry resolves to ``block`` — the retention
        eligibility test (only registered blocks are worth retaining:
        an unregistered block is unreachable through the cache)."""
        return block in self._by_block

    def invalidate_block(self, block: int) -> None:
        """Drop every entry resolving to ``block`` (its last owner just
        freed it, or the allocator reclaimed it from the retained LRU —
        the pool may recycle the pages any time now)."""
        for kind, key in self._by_block.pop(block, ()):
            table = self._full if kind == "full" else self._partial
            if table.get(key) == block:
                del table[key]


class PagedKVCache:
    """Device pools + the authoritative host mirror of block tables and
    sequence lengths for up to ``max_slots`` concurrent sequences.

    ``k``/``v`` are ``[L, num_blocks, block_size, H, hd]`` device arrays
    (the leading layer axis matches the model's scan-over-layers stack, so
    the decode scan consumes one layer's pool per iteration). The compiled
    tick DONATES and returns them; the engine reassigns ``cache.k/.v``
    each call. Tables/lengths live here as small host numpy arrays —
    admission and eviction are plain host mutations between ticks."""

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 num_blocks: int, block_size: int, max_slots: int,
                 max_blocks_per_seq: int, dtype=jnp.float32,
                 share_prefix: bool = False, kv_dtype: Optional[str] = None,
                 retain_prefix: bool = True, tp_degree: int = 1):
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        # tensor-parallel degree (ISSUE 15): the pools are LOGICALLY
        # [L, N, bs, H, hd] but physically head-sharded over a tp mesh
        # (`shard_pools`), so every per-byte accounting number here is
        # PER SHARD — each device holds H/tp heads of every block, and
        # capacity at equal per-device HBM scales with the mesh. Tables,
        # lengths and the allocator stay shard-oblivious: a block id
        # names the same logical block on every shard.
        if num_heads % tp_degree:
            raise ValueError(f"num_heads {num_heads} must divide by "
                             f"tp_degree {tp_degree}")
        self.tp_degree = int(tp_degree)
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.max_slots = max_slots
        self.max_blocks_per_seq = max_blocks_per_seq
        self.dtype = dtype
        if kv_dtype not in (None, "f32", "float32", "int8"):
            raise ValueError(f"kv_dtype must be None|'f32'|'int8', "
                             f"got {kv_dtype!r}")
        self.quantized = kv_dtype == "int8"
        shape = (num_layers, num_blocks, block_size, num_heads, head_dim)
        if self.quantized:
            # int8 value pages + per-block scale pages (one f32 per
            # token row per head) — quantize-on-scatter writes both
            # through the same block/offset routing
            sshape = shape[:-1]
            self.k = (jnp.zeros(shape, jnp.int8),
                      jnp.zeros(sshape, jnp.float32))
            self.v = (jnp.zeros(shape, jnp.int8),
                      jnp.zeros(sshape, jnp.float32))
        else:
            self.k = jnp.zeros(shape, dtype)
            self.v = jnp.zeros(shape, dtype)
        self.allocator = BlockAllocator(num_blocks)
        self.tables = np.zeros((max_slots, max_blocks_per_seq), np.int32)
        self.lengths = np.zeros((max_slots,), np.int32)
        self._owned: List[List[int]] = [[] for _ in range(max_slots)]
        # COW bookkeeping: which table indices this slot ADOPTED (vs
        # allocated), and the admission-reserved fork target
        self._adopted: List[set] = [set() for _ in range(max_slots)]
        self._fork_reserve: List[Optional[int]] = [None] * max_slots
        self.share_prefix = share_prefix
        self.prefix_cache = PrefixCache(block_size) if share_prefix \
            else None
        # radix retention (ISSUE 14): registered blocks outlive their
        # last owner in the allocator's retained LRU; the reclaim hook
        # welds cache invalidation to the lazy recycle moment
        self.retain_prefix = bool(retain_prefix and share_prefix)
        if self.prefix_cache is not None:
            self.allocator.reclaim_hook = \
                self.prefix_cache.invalidate_block
        # cumulative sharing counters (telemetry feeds off these)
        self.prefix_hit_blocks = 0
        self.cow_forks = 0
        self.retained_hits = 0

    # -- derived -----------------------------------------------------------

    @property
    def context_width(self) -> int:
        """The fixed gather width ``max_blocks_per_seq * block_size`` —
        the maximum context length a slot can hold, and the padded width
        every prefill/decode attention runs at."""
        return self.max_blocks_per_seq * self.block_size

    @property
    def free_blocks(self) -> int:
        """Spare pool capacity INCLUDING lazily-reclaimable retained
        blocks — the number admission, backpressure, and the fleet's
        load signal must use (a retained block is one reclaim away from
        free; counting only the raw free list would shed spuriously)."""
        return self.allocator.reclaimable

    @property
    def retained_blocks(self) -> int:
        """Blocks currently parked in the retained LRU (rc 0, prefix
        cache still maps their content)."""
        return self.allocator.num_retained

    @property
    def quant_dtype(self) -> str:
        return "int8" if self.quantized else jnp.dtype(self.dtype).name

    @property
    def kv_bytes_per_token(self) -> int:
        """HBM bytes one resident token costs across all layers, K and
        V, PER SHARD: the capacity accounting behind the int8 ~3-4x win
        (values at 1 byte + one f32 scale per head vs 4 bytes per
        element). Under tensor parallelism each device holds
        ``num_heads / tp_degree`` heads of every row (ISSUE 15), so this
        is the number a device's HBM budget divides by — capacity scales
        with the mesh."""
        if self.quantized:
            per_head = self.head_dim * 1 + 4          # int8 + f32 scale
        else:
            per_head = self.head_dim * jnp.dtype(self.dtype).itemsize
        heads_local = self.num_heads // self.tp_degree
        return 2 * self.num_layers * heads_local * per_head

    @property
    def bytes_per_block(self) -> int:
        """HBM bytes one pool block costs PER SHARD (both pools, scales
        included) — the equal-pool-bytes denominator the quantization
        and tensor-parallel bench legs size with."""
        return self.kv_bytes_per_token * self.block_size

    def blocks_needed(self, length: int) -> int:
        return -(-length // self.block_size)          # ceil

    def owned_count(self, slot: int) -> int:
        """How many pool blocks ``slot`` currently holds — the size of
        the reservation an eviction returns to the free list (leak
        accounting for the eviction telemetry and tests)."""
        return len(self._owned[slot])

    # -- slot lifecycle ----------------------------------------------------

    def ensure_capacity(self, slot: int, new_len: int) -> bool:
        """Grow ``slot``'s block table to cover ``new_len`` tokens.
        Returns False (and changes nothing) if the pool cannot supply the
        extra blocks — the scheduler's backpressure signal."""
        assert new_len <= self.context_width, \
            f"length {new_len} exceeds slot capacity {self.context_width}"
        need = self.blocks_needed(new_len) - len(self._owned[slot])
        if need <= 0:
            return True
        got = self.allocator.alloc(need)
        if got is None:
            return False
        start = len(self._owned[slot])
        self._owned[slot].extend(got)
        self.tables[slot, start:start + len(got)] = got
        return True

    def free_slot(self, slot: int) -> None:
        """Decref ``slot``'s blocks (a shared block survives while other
        sequences still reference it; the LAST owner's decref frees it —
        or RETAINS it when its content is prefix-registered — ) and
        clear the table row. Decrefs run in REVERSE table order so a
        retained prefix chain's tail blocks are older in the LRU than
        its roots: reclaim-under-pressure eats tails first and a
        surviving partial chain still matches from the root. The pool
        data itself is NOT zeroed — stale block contents are finite and
        always masked by length, so reuse is a table update, not a
        memory wipe (the paged design's whole point)."""
        for b in reversed(self._owned[slot]):
            self._decref(b)
        if self._fork_reserve[slot] is not None:
            self._decref(self._fork_reserve[slot])
            self._fork_reserve[slot] = None
        self._owned[slot] = []
        self._adopted[slot] = set()
        self.tables[slot] = NULL_BLOCK
        self.lengths[slot] = 0

    def _decref(self, block: int) -> bool:
        retain = (self.retain_prefix and self.prefix_cache is not None
                  and self.prefix_cache.covers(block))
        dropped = self.allocator.decref(block, retain=retain)
        if dropped and not retain and self.prefix_cache is not None:
            self.prefix_cache.invalidate_block(block)
        return dropped

    # -- copy-on-write prefix sharing --------------------------------------
    #
    # Ownership discipline: the slot that ALLOCATED a block is its
    # writer and appends in place; a slot that ADOPTED a block via the
    # prefix cache reads rows below the registered coverage and must
    # FORK before its first write into it (``cow_targets`` names the
    # blocks, ``fork_block`` swaps them). Only the partial boundary
    # block of an exact-duplicate prompt is ever in that position —
    # adopted FULL blocks cover prompt positions strictly below the
    # sharer's write range — so admission reserves exactly one fork
    # block when the match includes a partial boundary.

    def match_prefix(self, tokens: List[int]) -> Optional[PrefixMatch]:
        """The resident shared prefix of ``tokens`` (None with sharing
        off, an empty match when nothing resident matches)."""
        if self.prefix_cache is None:
            return None
        return self.prefix_cache.match(tokens)

    def adopt_prefix(self, slot: int, match: PrefixMatch) -> None:
        """Map ``match``'s physical blocks into ``slot``'s table by
        reference (incref each) — the admission-side half of sharing.
        Must run on an empty slot, before ``ensure_capacity`` sizes the
        fresh-tail allocation. A partial boundary match also reserves
        the copy-on-write fork target so the first divergent write can
        never strand on an exhausted pool."""
        assert not self._owned[slot], "adopt_prefix on a non-empty slot"
        for i, b in enumerate(match.blocks):
            if self.allocator.incref(b):      # revived out of the LRU
                self.retained_hits += 1
            self.tables[slot, i] = b
        self._owned[slot] = list(match.blocks)
        self._adopted[slot] = set(range(len(match.blocks)))
        self.prefix_hit_blocks += len(match.blocks)
        if match.partial:
            got = self.allocator.alloc(1)
            if got is None:
                self.free_slot(slot)       # roll back the adoption
                raise RuntimeError(
                    f"KV pool exhausted reserving the COW fork block for "
                    f"slot {slot} — gate admissions on can_admit()")
            self._fork_reserve[slot] = got[0]

    def register_prefix(self, slot: int, tokens: List[int]) -> int:
        """Publish ``slot``'s freshly-written prompt blocks to the
        prefix cache (no-op with sharing off)."""
        if self.prefix_cache is None:
            return 0
        return self.prefix_cache.register(tokens, self._owned[slot])

    # -- cross-replica handoff (ISSUE 18) ----------------------------------

    def export_pages(self, slot: int):
        """Read out the pool pages covering ``slot``'s current length
        for a prefill→decode handoff: returns ``(block_ids, kpages,
        vpages)`` where the page arrays are host numpy ``[L, nb, bs, H,
        hd]`` (plus ``[L, nb, bs, H]`` scale leaves as ``(values,
        scales)`` tuples when quantized) in TABLE ORDER — physical block
        ids don't travel; the receiver re-homes the pages at its own
        allocations. Shared/adopted blocks export fine (it's a read);
        only blocks covering the length ship, not the reservation."""
        nb = self.blocks_needed(int(self.lengths[slot]))
        ids = [int(b) for b in self.tables[slot, :nb]]
        sel = jnp.asarray(ids, jnp.int32)

        def take(pool):
            if isinstance(pool, tuple):
                return (np.asarray(pool[0][:, sel]),
                        np.asarray(pool[1][:, sel]))
            return np.asarray(pool[:, sel])

        return ids, take(self.k), take(self.v)

    def import_pages(self, slot: int, kpages, vpages, length: int,
                     reserve_len: Optional[int] = None) -> bool:
        """Adopt handed-off pages into an EMPTY slot: allocate blocks to
        cover ``max(length, reserve_len)`` (the full decode reservation,
        so adoption can never strand mid-sequence on a dry pool), write
        the page bytes at this pool's own block ids, and set the length.
        Returns False (nothing changed) when the pool can't supply the
        blocks — the decode side's backpressure; the fleet retries or
        re-routes the handoff."""
        assert not self._owned[slot], "import_pages on a non-empty slot"
        target = max(int(length), int(reserve_len or 0))
        if not self.ensure_capacity(slot, target):
            return False
        nb = self.blocks_needed(int(length))
        sel = jnp.asarray(self._owned[slot][:nb], jnp.int32)

        def put(pool, pages):
            if isinstance(pool, tuple):
                return (pool[0].at[:, sel].set(
                            jnp.asarray(pages[0], pool[0].dtype)),
                        pool[1].at[:, sel].set(
                            jnp.asarray(pages[1], pool[1].dtype)))
            return pool.at[:, sel].set(jnp.asarray(pages, pool.dtype))

        self.k = put(self.k, kpages)
        self.v = put(self.v, vpages)
        self.lengths[slot] = int(length)
        return True

    def cow_targets(self, slot: int, lo: int, hi: int) -> List[int]:
        """Table indices of ``slot``'s ADOPTED, still multiply-owned
        blocks covering write positions ``[lo, hi]`` — the engine forks
        exactly these before a tick scatters there. An adopted block
        whose co-owners all evicted promotes to write-in-place (its
        cache coverage is below every write this slot will ever do)."""
        bs = self.block_size
        out = []
        for i in sorted(self._adopted[slot]):
            if not lo // bs <= i <= hi // bs:
                continue
            if self.allocator.ref_count(self._owned[slot][i]) > 1:
                out.append(i)
            else:
                self._adopted[slot].discard(i)
        return out

    def fork_block(self, slot: int, index: int) -> Tuple[int, int]:
        """Copy-on-write fork of ``slot``'s table entry ``index``:
        point the slot at the admission-reserved fork target (or a
        fresh allocation) and decref the shared original. Returns
        ``(src, dst)`` — the CALLER owns the device copy of the pool
        pages (host tables know nothing about HBM)."""
        src = self._owned[slot][index]
        dst = self._fork_reserve[slot]
        if dst is None:
            got = self.allocator.alloc(1)
            if got is None:
                raise RuntimeError(
                    f"KV pool exhausted forking shared block {src} for "
                    f"slot {slot} — admission must reserve fork headroom")
            dst = got[0]
        else:
            self._fork_reserve[slot] = None
        self._owned[slot][index] = dst
        self.tables[slot, index] = dst
        self._adopted[slot].discard(index)
        self._decref(src)
        self.cow_forks += 1
        return src, dst

    def shard_pools(self, mesh, axis: str = "model") -> None:
        """Commit the device pools head-sharded over ``mesh``'s ``axis``
        (ISSUE 15): values ``[L, N, bs, H, hd]`` split on the H axis,
        int8 scale pages ``[L, N, bs, H]`` split identically, so every
        shard owns its head group of EVERY block. The host side — tables,
        lengths, allocator, prefix cache — is untouched and stays
        shard-oblivious: admission/eviction/CoW/retention reason about
        one logical block table while the bytes live distributed."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        # TRIMMED spec (no trailing None): matches the normalized form
        # `tp_constrain` pins on the compiled programs' pool outputs, so
        # the carry's sharding hashes identical call to call (padded vs
        # trimmed specs retrace on some jax versions). Covers both the
        # [L, N, bs, H, hd] value pages and [L, N, bs, H] scale pages.
        sh = NamedSharding(mesh, P(None, None, None, axis))

        def put(pool):
            if isinstance(pool, tuple):
                return (jax.device_put(pool[0], sh),
                        jax.device_put(pool[1], sh))
            return jax.device_put(pool, sh)

        self.k = put(self.k)
        self.v = put(self.v)

    def device_tables(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """The current (tables, lengths) as device operands for a tick."""
        return jnp.asarray(self.tables), jnp.asarray(self.lengths)
