"""Paged KV cache — the serving runtime's device-memory manager.

Long-context serving is memory-bound on the KV cache, and naive per-request
contiguous allocation at ``max_len`` wastes most of it: concurrent sequences
have ragged lengths, so reserving the worst case per slot strands HBM
(PagedAttention's motivating measurement — PAPERS.md [S1]). The fix is the
OS page-table design: the cache is a single pool of fixed-size **blocks**
(``[num_blocks, block_size, heads, head_dim]`` per layer) and each sequence
holds an ordered **block table** of pool indices; allocation is
block-granular, so waste is bounded by one partial block per sequence and
freed blocks are immediately reusable by any other request.

Split of responsibilities (the framework's static-shapes contract):

- **Host side, dynamic**: :class:`BlockAllocator` (free-list alloc/free)
  and :class:`PagedKVCache` (device pools + the authoritative host mirror
  of block tables and lengths). Admission/eviction mutate ONLY these small
  host arrays between decode ticks — nothing here is traced.
- **Device side, pure**: :func:`gather_pages`, :func:`scatter_prefill`,
  :func:`scatter_token` — ``jnp``-pure gather/scatter the compiled
  prefill/decode programs call with fixed shapes. Block tables enter the
  compiled step as ordinary int32 operands, so the program never retraces
  as sequences come and go.

Block id 0 is reserved as the **null block**: unallocated table entries and
masked-off scatter rows all target it, so every scatter is total (no
dynamic shapes, no OOB) and its contents are unspecified-but-finite —
reads through it are always masked by the length before use.
"""

from __future__ import annotations

import collections
from typing import List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

__all__ = ["BlockAllocator", "PagedKVCache", "gather_pages",
           "scatter_prefill", "scatter_token", "NULL_BLOCK"]

# block 0 never holds live data: it is the scatter target for padding rows
# and the gather source for unallocated table entries (always masked)
NULL_BLOCK = 0


# ---------------------------------------------------------------------------
# device side: jnp-pure gather/scatter (called from compiled programs)
# ---------------------------------------------------------------------------

def gather_pages(pages, table):
    """Gather one layer's paged K (or V) into position order.

    ``pages`` ``[N, bs, H, hd]``, ``table`` ``[S, MB]`` int32 ->
    ``[S, MB*bs, H, hd]``: row ``s``'s tokens ``0..len-1`` in order, with
    unspecified (null-block / stale) content beyond the sequence length —
    the attention mask owns that boundary."""
    S, MB = table.shape
    _, bs, H, hd = pages.shape
    return pages[table].reshape(S, MB * bs, H, hd)


def scatter_prefill(pages, kv, table, length):
    """Write a prefill's per-layer K (or V) rows into the paged pool.

    ``kv`` ``[B, W, H, hd]`` holds projections for positions ``0..W-1``
    (``W`` = the fixed padded prefill width); only rows ``< length`` are
    live — the rest are routed to the null block. Returns the updated
    pool. ``table`` ``[B, MB]``, ``length`` ``[B]``."""
    B, W = kv.shape[:2]
    bs = pages.shape[1]
    pos = jnp.arange(W, dtype=jnp.int32)
    blk = jnp.where(pos[None, :] < length[:, None],
                    jnp.take_along_axis(table, pos[None, :] // bs, axis=1),
                    NULL_BLOCK)                                   # [B, W]
    off = jnp.broadcast_to(pos % bs, (B, W))
    return pages.at[blk, off].set(kv)


def scatter_token(pages, kv, table, position, active):
    """Write one decode step's per-layer K (or V) for every slot.

    ``kv`` ``[S, H, hd]`` is the new token's projection per slot;
    ``position`` ``[S]`` the 0-based index it occupies (the sequence
    length BEFORE this token); inactive slots scatter to the null block.
    Returns the updated pool."""
    S = kv.shape[0]
    bs = pages.shape[1]
    blk = jnp.where(active,
                    table[jnp.arange(S), position // bs],
                    NULL_BLOCK)                                   # [S]
    off = position % bs
    return pages.at[blk, off].set(kv)


# ---------------------------------------------------------------------------
# host side: allocation / free (between-tick bookkeeping, never traced)
# ---------------------------------------------------------------------------

class BlockAllocator:
    """Free-list allocator over pool block ids ``1..num_blocks-1`` (block 0
    is the reserved null block). FIFO reuse keeps churn deterministic —
    tests pin that re-admitted sequences land on recycled blocks."""

    def __init__(self, num_blocks: int):
        assert num_blocks >= 2, "need at least one non-null block"
        self.num_blocks = num_blocks
        self._free = collections.deque(range(1, num_blocks))

    @property
    def num_free(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` block ids, or None (and no change) if unavailable."""
        if n > len(self._free):
            return None
        return [self._free.popleft() for _ in range(n)]

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            assert b != NULL_BLOCK, "cannot free the null block"
            self._free.append(b)


class PagedKVCache:
    """Device pools + the authoritative host mirror of block tables and
    sequence lengths for up to ``max_slots`` concurrent sequences.

    ``k``/``v`` are ``[L, num_blocks, block_size, H, hd]`` device arrays
    (the leading layer axis matches the model's scan-over-layers stack, so
    the decode scan consumes one layer's pool per iteration). The compiled
    tick DONATES and returns them; the engine reassigns ``cache.k/.v``
    each call. Tables/lengths live here as small host numpy arrays —
    admission and eviction are plain host mutations between ticks."""

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 num_blocks: int, block_size: int, max_slots: int,
                 max_blocks_per_seq: int, dtype=jnp.float32):
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.max_slots = max_slots
        self.max_blocks_per_seq = max_blocks_per_seq
        self.dtype = dtype
        shape = (num_layers, num_blocks, block_size, num_heads, head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        self.allocator = BlockAllocator(num_blocks)
        self.tables = np.zeros((max_slots, max_blocks_per_seq), np.int32)
        self.lengths = np.zeros((max_slots,), np.int32)
        self._owned: List[List[int]] = [[] for _ in range(max_slots)]

    # -- derived -----------------------------------------------------------

    @property
    def context_width(self) -> int:
        """The fixed gather width ``max_blocks_per_seq * block_size`` —
        the maximum context length a slot can hold, and the padded width
        every prefill/decode attention runs at."""
        return self.max_blocks_per_seq * self.block_size

    @property
    def free_blocks(self) -> int:
        return self.allocator.num_free

    def blocks_needed(self, length: int) -> int:
        return -(-length // self.block_size)          # ceil

    def owned_count(self, slot: int) -> int:
        """How many pool blocks ``slot`` currently holds — the size of
        the reservation an eviction returns to the free list (leak
        accounting for the eviction telemetry and tests)."""
        return len(self._owned[slot])

    # -- slot lifecycle ----------------------------------------------------

    def ensure_capacity(self, slot: int, new_len: int) -> bool:
        """Grow ``slot``'s block table to cover ``new_len`` tokens.
        Returns False (and changes nothing) if the pool cannot supply the
        extra blocks — the scheduler's backpressure signal."""
        assert new_len <= self.context_width, \
            f"length {new_len} exceeds slot capacity {self.context_width}"
        need = self.blocks_needed(new_len) - len(self._owned[slot])
        if need <= 0:
            return True
        got = self.allocator.alloc(need)
        if got is None:
            return False
        start = len(self._owned[slot])
        self._owned[slot].extend(got)
        self.tables[slot, start:start + len(got)] = got
        return True

    def free_slot(self, slot: int) -> None:
        """Return ``slot``'s blocks to the pool and clear its table row.
        The pool data itself is NOT zeroed — stale block contents are
        finite and always masked by length, so reuse is a table update,
        not a memory wipe (the paged design's whole point)."""
        if self._owned[slot]:
            self.allocator.free(self._owned[slot])
        self._owned[slot] = []
        self.tables[slot] = NULL_BLOCK
        self.lengths[slot] = 0

    def device_tables(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """The current (tables, lengths) as device operands for a tick."""
        return jnp.asarray(self.tables), jnp.asarray(self.lengths)
