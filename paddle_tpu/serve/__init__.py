"""Serving runtime — continuous batching over a paged KV cache, scaled
out to a fault-drilled multi-replica fleet.

The "millions of users" pillar (ROADMAP #1): training produced a
checkpoint; this package turns it into an incremental-decode server.
The layers mirror the serving literature the design follows (PAPERS.md
[S1] PagedAttention, [S2] Orca, [R2] Bamboo for the death-is-routine
doctrine):

- :mod:`.kv_cache` — the paged KV pool: fixed-size REFCOUNTED blocks
  shared by all concurrent sequences, host-side block
  tables/alloc/free, the content-addressed :class:`PrefixCache` behind
  copy-on-write prefix sharing (ISSUE 12), and the ``jnp``-pure
  gather/scatter used by the compiled programs.
- :mod:`.engine` — :class:`DecodeEngine`: the two compiled fixed-shape
  programs (padded-width or chunked prefill, max-slot decode tick with
  an active mask — optionally the ``[S, 1+k]`` speculative verify
  tick), donated KV carry, greedy or seeded-stochastic sampling
  (:class:`SamplingConfig`), COW fork-on-write, retrace accounting,
  the structured :class:`AdmitProbe` backpressure verdict, and — with
  ``mesh=`` (ISSUE 15) — the whole tick tensor-parallel over a tp mesh
  (megatron-placed params, head-axis-sharded KV pools, token-identical
  to single-device with the host side shard-oblivious).
- :mod:`.scheduler` — :class:`ContinuousBatchingScheduler`: iteration-
  level request admission/eviction between decode ticks with
  FCFS/SJF/priority queue policies, chunked-prefill interleaving,
  submit-time load shedding, deadline eviction, and per-request
  TTFT/TPOT + sharing/speculation telemetry.
- :mod:`.router` / :mod:`.fleet` — :class:`FleetRouter` +
  :class:`ServingFleet` (ISSUE 11): N replica workers behind
  session-affine least-loaded routing, heartbeat health gating (the
  PR-10 machinery), idempotent rid-keyed resubmission of a dead
  replica's requests, graceful drain for elastic scale-down.
- :mod:`.loadgen` — seeded traffic shapes (Poisson/bursty arrivals,
  ragged lengths, shareable-prefix sessions, deadlines/priorities) and
  the :class:`SimClock` that makes fleet fault drills deterministic.
- :mod:`.transport` / :mod:`.replica_proc` (ISSUE 13, sockets +
  binary frames in ISSUE 18) — the length-prefixed submit/complete
  frame protocol (per-message timeout, seq-numbered at-least-once
  delivery, classified corruption), now carried over pipes OR TCP
  sockets (``listen``/``connect``/``SocketFrameReader`` — the SAME
  retry/dedupe brain either way), with CRC-checked binary frames for
  raw payloads (KV pages cross the wire as bytes, not JSON), and the
  child-process replica entrypoint behind
  ``ServingFleet(replica_mode="process"|"socket")``: a SIGKILL, hang,
  or corrupt reply is contained in one process, observed via heartbeat
  staleness, and healed by the same reconcile path.
- **Prefill/decode disaggregation** (ISSUE 18): give
  ``ServingFleet(roles=[...])`` per-replica roles and prefill-role
  replicas run the prompt pass against their LOCAL paged pool, then
  stream the finished KV pages block-by-block to a decode-role replica
  (``export_pages``/``import_pages`` → framed binary payloads → an
  ``adopt`` op), which continues from the first generated token —
  bit-identical to colocated serving, with the handoff rid-keyed
  through the reconcile ledger so mid-transfer death resubmits cleanly.
- :mod:`.chaos` + epoch-fenced membership (ISSUE 20) —
  :class:`NetworkChaos`/:class:`LinkChaos`, the SimClock-deterministic
  network fault plane at the frame seam (per-link delay distributions,
  bandwidth throttle, drop probability, asymmetric partition windows,
  link flap schedules — seeded and ``describe()``-able), driving the
  fleet's partition-tolerant membership: every replica holds a
  monotonically-increasing epoch lease stamped on every frame, a
  declared-dead replica is fenced BY EPOCH (not by kill — no signal
  needs to reach it), a fenced child self-fences on its first stale
  rejection, and a healed partition re-admits the zombie under a fresh
  lease; a disagg fleet that lost every prefill replica degrades to
  colocated prefill on its decoders instead of to stuck.
- :mod:`.autoscaler` — the supervised elastic-capacity policy loop on
  top of ``drain()`` and ``spawn_replica()``, an M/M/c queueing-model
  controller per role (ISSUE 18): Erlang-C predicted delay from an
  arrival-rate EMA + tick-time EMA + role capacity, scale up on
  predicted-delay breach gated on the delay derivative, down on
  sustained idle, hysteresis against flapping, cold-spawn replacement
  of dead replicas under a loud restart budget.
"""

from .kv_cache import (BlockAllocator, PagedKVCache, PrefixCache,
                       PrefixMatch, gather_pages, scatter_prefill,
                       scatter_token, scatter_span,
                       scatter_prefill_pages, scatter_token_pages,
                       scatter_span_pages, quantize_rows,
                       dequantize_rows, pages_to_blobs, blobs_to_pages)
from .engine import AdmitProbe, DecodeEngine, SamplingConfig
from .scheduler import ContinuousBatchingScheduler, Request
from .router import FleetRouter, RouteDecision
from .fleet import (FleetRequest, ProcReplicaWorker, ReplicaWorker,
                    ServingFleet, build_proc_spec)
from .loadgen import (GenRequest, SimClock, hostile_workload,
                      make_workload, workload_stats)
from .autoscaler import Autoscaler, AutoscalerGaveUp, erlang_c_wait
from .chaos import LinkChaos, NetworkChaos
from .transport import (BINARY_FLAG, ReplicaTransport,
                        SocketFrameReader, SocketWriter,
                        TransportClosed, TransportCorrupt,
                        TransportError, TransportTimeout,
                        accept_connection, connect,
                        encode_binary_frame, listen,
                        write_binary_frame)

__all__ = ["BlockAllocator", "PagedKVCache", "PrefixCache", "PrefixMatch",
           "DecodeEngine", "AdmitProbe", "SamplingConfig",
           "ContinuousBatchingScheduler", "Request", "gather_pages",
           "scatter_prefill", "scatter_token", "scatter_span",
           "scatter_prefill_pages", "scatter_token_pages",
           "scatter_span_pages", "quantize_rows", "dequantize_rows",
           "FleetRouter", "RouteDecision", "ServingFleet",
           "ReplicaWorker", "ProcReplicaWorker", "FleetRequest",
           "build_proc_spec",
           "pages_to_blobs", "blobs_to_pages",
           "Autoscaler", "AutoscalerGaveUp", "erlang_c_wait",
           "LinkChaos", "NetworkChaos",
           "ReplicaTransport", "TransportError", "TransportTimeout",
           "TransportCorrupt", "TransportClosed",
           "SocketFrameReader", "SocketWriter", "listen", "connect",
           "accept_connection", "encode_binary_frame",
           "write_binary_frame", "BINARY_FLAG",
           "GenRequest", "SimClock", "make_workload",
           "hostile_workload", "workload_stats"]
