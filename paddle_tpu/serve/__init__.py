"""Serving runtime — continuous batching over a paged KV cache, scaled
out to a fault-drilled multi-replica fleet.

The "millions of users" pillar (ROADMAP #1): training produced a
checkpoint; this package turns it into an incremental-decode server.
The layers mirror the serving literature the design follows (PAPERS.md
[S1] PagedAttention, [S2] Orca, [R2] Bamboo for the death-is-routine
doctrine):

- :mod:`.kv_cache` — the paged KV pool: fixed-size REFCOUNTED blocks
  shared by all concurrent sequences, host-side block
  tables/alloc/free, the content-addressed :class:`PrefixCache` behind
  copy-on-write prefix sharing (ISSUE 12), and the ``jnp``-pure
  gather/scatter used by the compiled programs.
- :mod:`.engine` — :class:`DecodeEngine`: the two compiled fixed-shape
  programs (padded-width or chunked prefill, max-slot decode tick with
  an active mask — optionally the ``[S, 1+k]`` speculative verify
  tick), donated KV carry, greedy or seeded-stochastic sampling
  (:class:`SamplingConfig`), COW fork-on-write, retrace accounting, and
  the structured :class:`AdmitProbe` backpressure verdict.
- :mod:`.scheduler` — :class:`ContinuousBatchingScheduler`: iteration-
  level request admission/eviction between decode ticks with
  FCFS/SJF/priority queue policies, chunked-prefill interleaving,
  submit-time load shedding, deadline eviction, and per-request
  TTFT/TPOT + sharing/speculation telemetry.
- :mod:`.router` / :mod:`.fleet` — :class:`FleetRouter` +
  :class:`ServingFleet` (ISSUE 11): N replica workers behind
  session-affine least-loaded routing, heartbeat health gating (the
  PR-10 machinery), idempotent rid-keyed resubmission of a dead
  replica's requests, graceful drain for elastic scale-down.
- :mod:`.loadgen` — seeded traffic shapes (Poisson/bursty arrivals,
  ragged lengths, shareable-prefix sessions, deadlines/priorities) and
  the :class:`SimClock` that makes fleet fault drills deterministic.
"""

from .kv_cache import (BlockAllocator, PagedKVCache, PrefixCache,
                       PrefixMatch, gather_pages, scatter_prefill,
                       scatter_token, scatter_span)
from .engine import AdmitProbe, DecodeEngine, SamplingConfig
from .scheduler import ContinuousBatchingScheduler, Request
from .router import FleetRouter, RouteDecision
from .fleet import FleetRequest, ReplicaWorker, ServingFleet
from .loadgen import GenRequest, SimClock, make_workload, workload_stats

__all__ = ["BlockAllocator", "PagedKVCache", "PrefixCache", "PrefixMatch",
           "DecodeEngine", "AdmitProbe", "SamplingConfig",
           "ContinuousBatchingScheduler", "Request", "gather_pages",
           "scatter_prefill", "scatter_token", "scatter_span",
           "FleetRouter", "RouteDecision", "ServingFleet",
           "ReplicaWorker", "FleetRequest",
           "GenRequest", "SimClock", "make_workload", "workload_stats"]
