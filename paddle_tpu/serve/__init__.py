"""Serving runtime — continuous batching over a paged KV cache.

The "millions of users" pillar (ROADMAP #1): training produced a
checkpoint; this package turns it into an incremental-decode server. Three
layers, mirroring the serving literature the design follows (PAPERS.md
[S1] PagedAttention, [S2] Orca):

- :mod:`.kv_cache` — the paged KV pool: fixed-size blocks shared by all
  concurrent sequences, host-side block tables/alloc/free, ``jnp``-pure
  gather/scatter used by the compiled programs.
- :mod:`.engine` — :class:`DecodeEngine`: the two compiled fixed-shape
  programs (padded-width prefill, max-slot decode tick with an active
  mask), donated KV carry, greedy sampling, retrace accounting.
- :mod:`.scheduler` — :class:`ContinuousBatchingScheduler`: iteration-
  level request admission/eviction between decode ticks with per-request
  TTFT/TPOT telemetry.
"""

from .kv_cache import (BlockAllocator, PagedKVCache, gather_pages,
                       scatter_prefill, scatter_token)
from .engine import DecodeEngine
from .scheduler import ContinuousBatchingScheduler, Request

__all__ = ["BlockAllocator", "PagedKVCache", "DecodeEngine",
           "ContinuousBatchingScheduler", "Request", "gather_pages",
           "scatter_prefill", "scatter_token"]
