"""Serving-replica child process entrypoint (ISSUE 13).

``python -m paddle_tpu.serve.replica_proc --spec '<json>'`` builds its
OWN :class:`~paddle_tpu.serve.engine.DecodeEngine` +
:class:`~paddle_tpu.serve.scheduler.ContinuousBatchingScheduler` pair
from the spec (model config + a variables ``.npz`` the parent saved —
training checkpoints serve unmodified, just like the in-process path),
then serves the :mod:`~paddle_tpu.serve.transport` frame protocol over
stdin/stdout until EOF or a ``stop`` op.

The contract that makes a SIGKILL here a non-event for the router:

- The child writes its OWN PR-10 heartbeat file each handled tick (the
  ``now`` carried on the tick message — the fleet's clock is the one
  time base, so SimClock drills stay deterministic). Kill the process
  and the beats simply stop; the router observes staleness and the
  fleet re-homes the requests. Nothing is announced.
- Every request is handled at-least-once-safely: a ``seq`` already
  processed replays the cached reply bytes (a retransmit after a lost
  or corrupted reply never re-executes a tick), and a ``submit`` whose
  rid is already known acks as a duplicate (the PR-11 idempotency
  boundary, now enforced on BOTH sides of the pipe).
- Telemetry emitted child-side (request records, deadline evictions) is
  buffered and shipped on the next tick reply; the PARENT re-emits it
  into the fleet's single stream — one telemetry stream, one terminal
  record per rid, exactly as in-process.
- Injected faults arrive as flags ON the message (``inject_drop_reply``
  / ``inject_corrupt_reply``): the child does the work, then loses or
  garbles the reply — so the drill exercises the real
  timeout→retransmit→cached-reply path, not a mock of it.

The first thing ``main`` does — before importing jax or building the
model — is dup the real stdout away and point fd 1 at stderr, so no
library print can ever tear a frame.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import sys
from typing import Any, Dict, List, Optional

__all__ = ["save_variables_npz", "load_variables_npz", "serve_loop",
           "EventBuffer", "SettableClock", "main"]

# separator for flattened variable-tree paths in the .npz; module names
# are identifier-like (no "::" can appear in a key)
_SEP = "::"


def save_variables_npz(path: str, variables: Dict[str, Any]) -> str:
    """Flatten a nested variables dict into one ``.npz`` (atomic
    tmp+rename — a crashed writer never leaves a torn file a spawning
    child could half-load)."""
    import numpy as np

    flat: Dict[str, Any] = {}

    def walk(d, prefix):
        for k, v in d.items():
            key = f"{prefix}{_SEP}{k}" if prefix else str(k)
            if isinstance(v, dict):
                walk(v, key)
            else:
                flat[key] = np.asarray(v)

    walk(variables, "")
    # np.savez appends ".npz" to names without it: keep the suffix so
    # the tmp name we rename is the name savez actually wrote
    tmp = f"{path}.tmp.{os.getpid()}.npz"
    np.savez(tmp, **flat)
    os.replace(tmp, path)
    return path


def load_variables_npz(path: str) -> Dict[str, Any]:
    """Rebuild the nested variables dict :func:`save_variables_npz`
    flattened."""
    import numpy as np

    out: Dict[str, Any] = {}
    with np.load(path) as z:
        for key in z.files:
            parts = key.split(_SEP)
            d = out
            for p in parts[:-1]:
                d = d.setdefault(p, {})
            d[parts[-1]] = z[key]
    return out


class EventBuffer:
    """Telemetry shim for the child's scheduler: captures emitted
    records so the tick handler can ship them to the parent (which owns
    the fleet's single telemetry stream).

    ``jsonl_path`` (ISSUE 17 satellite) additionally appends every
    record to a local JSONL, flushed per record — the child's
    decode_tick/request evidence survives a SIGKILL even though the
    buffered copy dies with the process. Shipping is unchanged
    (:meth:`drain` still hands the parent everything); the file is the
    forensic sibling, not a second stream of record."""

    def __init__(self, jsonl_path: Optional[str] = None):
        self.records: List[Dict[str, Any]] = []
        self._f = None
        if jsonl_path:
            d = os.path.dirname(jsonl_path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._f = open(jsonl_path, "a")

    def emit_event(self, rec: Dict[str, Any]) -> None:
        self.records.append(rec)
        if self._f is not None:
            try:
                self._f.write(json.dumps(rec, default=str) + "\n")
                self._f.flush()
            except OSError:
                pass                     # persistence is best-effort

    def drain(self) -> List[Dict[str, Any]]:
        out, self.records = self.records, []
        return out


class SettableClock:
    """The child's clock is SET from each message's ``now`` — the fleet
    clock is the single time base for deadlines, TTFT and heartbeats,
    which is what keeps SimClock drills deterministic across the
    process boundary."""

    def __init__(self, t0: float = 0.0):
        self.t = float(t0)

    def __call__(self) -> float:
        return self.t

    def set(self, now: Optional[float]) -> None:
        if now is not None:
            self.t = float(now)


def _build(spec: Dict[str, Any]):
    """Heavy construction (jax import lives here): model from spec,
    variables from the parent's npz (or a seeded init — bit-identical
    to a parent that used the same seed), engine + scheduler. An
    optional ``spec["mesh"]`` (``{axis_name: size}``, ISSUE 15) builds
    the engine tensor-parallel over this process's local devices — the
    Mesh itself is constructed HERE because device handles cannot cross
    the JSON wire; a spec without the key is the single-device engine,
    bit-identical to the pre-tp build.

    Cold-start elimination (ISSUE 16): ``spec["compile_cache_dir"]``
    points jax's persistent compilation cache at a directory shared
    across spawns, ``spec["autotune_cache_dir"]`` enables the kernel
    autotuner against its JSON cache, and ``spec["warmup"]`` executes
    both engine programs before the hello reply — so an autoscaler
    cold-spawn or supervisor restart answers its first request with
    zero compiles on the serving path. All three keys are ABSENT from
    a default spec (build unchanged, byte-identical schema). Returns
    ``(engine, sched, buf, clock, startup_ms)`` where ``startup_ms``
    is the build/compile/warmup wall breakdown the hello and heartbeat
    payloads carry."""
    import time

    t_start = time.perf_counter()
    import jax
    import jax.numpy as jnp

    from ..models import TransformerLM
    from .engine import DecodeEngine
    from .scheduler import ContinuousBatchingScheduler

    if spec.get("compile_cache_dir"):
        from ..obs import xla_cache
        xla_cache.setup_compilation_cache(spec["compile_cache_dir"])
    if spec.get("autotune_cache_dir"):
        from ..nn import autotune
        autotune.enable(spec["autotune_cache_dir"])

    model = TransformerLM(**spec["model"])
    if spec.get("variables_npz"):
        loaded = load_variables_npz(spec["variables_npz"])
        vs = jax.tree_util.tree_map(jnp.asarray, loaded)
    else:
        vs = model.init(jax.random.PRNGKey(int(spec.get("seed", 0))),
                        jnp.zeros((1, model.max_len), jnp.int32))
    ek = dict(spec.get("engine") or {})
    mesh_axes = spec.get("mesh")
    if mesh_axes:
        import numpy as np
        from jax.sharding import Mesh
        names = tuple(mesh_axes)
        sizes = tuple(int(mesh_axes[n]) for n in names)
        need = int(np.prod(sizes))
        devs = jax.devices()
        if len(devs) < need:
            raise RuntimeError(
                f"spec mesh {dict(mesh_axes)} needs {need} devices, "
                f"replica has {len(devs)} — spawn with "
                f"--xla_force_host_platform_device_count or drop the "
                f"mesh from the spec")
        ek["mesh"] = Mesh(np.asarray(devs[:need]).reshape(sizes), names)
    engine = DecodeEngine(model, vs, **ek)
    t_built = time.perf_counter()
    startup: Dict[str, Any] = {
        "build": round((t_built - t_start) * 1e3, 3)}
    if spec.get("warmup"):
        rep = engine.warmup()
        t_warm = time.perf_counter()
        # "compile" = the programs' first executions (where XLA compile
        # or persistent-cache deserialize happens); "warmup" = the
        # remainder (extra trial iterations, cache bookkeeping)
        compile_ms = (rep["prefill_s"] + rep["tick_s"]) * 1e3
        startup.update({
            "compile": round(compile_ms, 3),
            "warmup": round((t_warm - t_built) * 1e3 - compile_ms, 3),
            "total": round((t_warm - t_start) * 1e3, 3),
            "autotune_trials": rep["autotune_trials"],
            "autotune_cache_hit": rep["autotune_cache_hit"],
            "xla_cache_hit": rep["xla_cache_hit"],
            "xla_cache_entries_added": rep["xla_cache_entries_added"],
        })
    else:
        startup.update({"compile": 0.0, "warmup": 0.0,
                        "total": startup["build"]})
    buf = EventBuffer(jsonl_path=(
        os.path.join(spec["telemetry_dir"],
                     f"replica_{int(spec.get('replica_id', 0))}.jsonl")
        if spec.get("telemetry_dir") else None))
    clock = SettableClock()
    tracer = None
    if spec.get("trace"):
        # distributed tracing (ISSUE 17): the child's spans are stamped
        # with the SettableClock — i.e. the message-carried fleet clock
        # — so the parent's merge puts every process on one time base
        from ..obs.trace import Tracer
        tracer = Tracer(clock=clock)
        engine.tracer = tracer
    metrics = None
    if spec.get("metrics"):
        # fleet metrics (ISSUE 19): the child grows its OWN registry,
        # stamped by the same message-carried fleet clock; deltas ship
        # on tick replies (the span-batch move) and the parent merges
        # them under a replica=<id> label
        from ..obs.metrics import MetricsHub
        metrics = MetricsHub(clock=clock)
        engine.metrics = metrics
    sched = ContinuousBatchingScheduler(
        engine, telemetry=buf, order=spec.get("order", "fcfs"),
        shed=False, est_tick_s=spec.get("est_tick_s"), clock=clock,
        tracer=tracer, role=spec.get("role", "both"), metrics=metrics)
    return engine, sched, buf, clock, startup, metrics


def serve_loop(read_file, write_file, *, engine, sched, buf, clock,
               root: str, replica_id: int,
               reply_cache_size: int = 16,
               startup: Optional[Dict[str, Any]] = None,
               metrics=None,
               lease_timeout_s: Optional[float] = None) -> int:
    """The child's message loop (transport-layer concerns only — the
    handler logic is inline because it IS the replica). Returns the exit
    code; EOF on stdin is a clean shutdown (the parent died or closed
    us).

    **Epoch leases (ISSUE 20).** The hello grants this replica a
    monotonically-increasing epoch; every stamped op must carry it.
    A ``fence`` op (or an op stamped with a NEWER epoch, or — with
    ``lease_timeout_s`` set — a contact gap longer than the lease) makes
    the child **self-fence**: evict every slot, free the blocks, drop
    all bookkeeping, stop heartbeating, and from then on REJECT every
    op carrying the revoked epoch (``error="stale_epoch"``) — so a
    replica the router falsely declared dead behind a partition can
    never double-execute a rid that was resubmitted elsewhere, even
    though no kill signal can reach its host. A ``readmit`` op grants a
    fresh epoch and a clean slate. Unstamped ops (legacy/fake drivers)
    pass unchecked on an unfenced child; a fenced child rejects them
    too — the fence is the stronger invariant. Every reply is stamped
    with the child's lease epoch so the parent can discard replies from
    an epoch it already revoked."""
    from ..parallel import multihost
    from . import transport as tp

    # a pre-built reader (SocketFrameReader in --connect mode) passes
    # through; a file/fd gets the stock FrameReader
    reader = (read_file if isinstance(read_file, tp.FrameReader)
              else tp.FrameReader(read_file))
    tracer = getattr(sched, "tracer", None)
    reply_cache: "collections.OrderedDict[int, bytes]" = \
        collections.OrderedDict()
    known = set()                      # delivered rids (idempotency)
    collected = 0                      # sched.completed cursor
    hb_seq = 0
    draining = False
    # the lease (ISSUE 20): epoch 0 = never granted (unstamped legacy
    # drivers); last_contact tracks the message-carried fleet clock so
    # lease expiry is SimClock-deterministic like everything else
    lease = {"epoch": 0, "timeout_s": lease_timeout_s,
             "last_contact": None}
    fstate: Dict[str, Any] = {"fenced": False, "info": None,
                              "stale_rejects": 0}

    def _self_fence(reason: str) -> Dict[str, Any]:
        """Evict everything, free the blocks, stop beating — the child
        half of the membership protocol. Idempotent; returns the fence
        record. Mirrors the in-process zombie fence
        (``ReplicaWorker.reset``)."""
        if fstate["fenced"]:
            return fstate["info"]
        free_before = engine.cache.free_blocks
        slots = 0
        for slot in list(sched.running):
            engine.evict(slot)
            slots += 1
        for slot in list(sched.prefilling):
            engine.evict(slot)
            slots += 1
        sched.running.clear()
        sched.prefilling.clear()
        sched.queue.clear()
        if getattr(sched, "handoffs", None) is not None:
            sched.handoffs.clear()
        known.clear()
        info = {"kind": "fence", "replica": replica_id,
                "t": clock(), "reason": reason,
                "epoch": lease["epoch"], "slots_evicted": slots,
                "blocks_freed": engine.cache.free_blocks - free_before,
                # the split-brain oracle: tokens generated AFTER this
                # point are zombie work — the drill asserts zero
                "tokens_at_fence": engine.tokens_generated,
                "source": "replica"}
        fstate["fenced"] = True
        fstate["info"] = info
        # forensics: lands in the child's local JSONL immediately and
        # ships to the parent stream on the first post-readmit tick
        buf.emit_event(info)
        if metrics is not None:
            metrics.counter("fleet_fence_total",
                            "self-fence events on this replica",
                            reason=reason).inc()
        return info

    def load_report() -> Dict[str, Any]:
        rep = sched.load_report()
        rep.update({
            "free_blocks": engine.cache.free_blocks,
            "free_slots": len(engine.free_slots()),
            # getattr: the engine surface here is duck-typed (tests and
            # remote views fake it); tp arrived in ISSUE 15
            "tp_degree": getattr(engine, "tp_degree", 1),
            "engine_ticks": engine.ticks,
            "prefix_hit_blocks": engine.cache.prefix_hit_blocks,
            "cow_forks": engine.cache.cow_forks,
            "est_tick_s": sched.est_tick_s,
            "compile_counts": engine.compile_counts(),
            "running_rids": [r.rid for r in sched.running.values()],
            "queued_rids": [r.rid for r in sched.queue],
            "prefilling_rids": [r.rid for r in sched.prefilling.values()],
        })
        return rep

    def beat(now: Optional[float]) -> None:
        nonlocal hb_seq
        if fstate["fenced"]:
            # a fenced replica is out of the membership: beating would
            # advertise capacity the router must not route to
            return
        hb_seq += 1
        multihost.write_heartbeat(
            root, host_id=replica_id, seq=hb_seq, now=now,
            extra={"role": "serving-replica", "pid": os.getpid(),
                   **({"startup_ms": startup} if startup else {}),
                   **{k: v for k, v in load_report().items()
                      if not k.endswith("_rids")
                      and k != "compile_counts"}})

    def _geometry() -> Dict[str, Any]:
        return {"pid": os.getpid(),
                "context_width": engine.context_width,
                "max_slots": engine.max_slots,
                "block_size": engine.cache.block_size,
                "num_blocks": engine.cache.num_blocks}

    def handle(msg: Dict[str, Any]) -> Dict[str, Any]:
        nonlocal collected, draining
        op = msg.get("op")
        clock.set(msg.get("now"))
        ep = msg.get("epoch")
        if op == "hello":
            # the initial lease grant — handled BEFORE the epoch guard
            # (the granted epoch is necessarily newer than the zero
            # lease, which must not read as a supersession)
            if ep is not None and int(ep) > lease["epoch"]:
                lease["epoch"] = int(ep)
            lease["last_contact"] = clock()
            beat(msg.get("now"))
            return {"ok": True, "startup_ms": startup,
                    "load": load_report(), **_geometry()}
        if op == "fence":
            # revocation notice: the router declared us dead and bumped
            # the epoch. Fence NOW (recording the revoked epoch), then
            # adopt the new one so zombie-driven ops carrying the old
            # epoch classify as stale, not merely "fenced".
            info = _self_fence("revoked")
            if ep is not None and int(ep) > lease["epoch"]:
                lease["epoch"] = int(ep)
            return {"ok": True, "fenced": True, "fence": info}
        if op == "readmit":
            # a fresh lease + a clean slate: the partition healed and
            # the router is re-admitting this (empty, fenced) replica
            new_ep = int(msg["epoch"])
            if new_ep <= lease["epoch"] and lease["epoch"]:
                return {"ok": False, "error": "stale_epoch",
                        "epoch": lease["epoch"]}
            info = fstate["info"]
            report = {
                "ok": True, "epoch": new_ep, "fence": info,
                "stale_epoch_rejects": fstate["stale_rejects"],
                # zombie-work oracle: tokens generated between the
                # fence and this readmit (the drill asserts 0)
                "tokens_while_fenced": (
                    engine.tokens_generated - info["tokens_at_fence"]
                    if info else 0),
                **_geometry()}
            lease["epoch"] = new_ep
            lease["last_contact"] = clock()
            fstate["fenced"] = False
            known.clear()
            draining = False
            beat(msg.get("now"))
            report["load"] = load_report()
            return report
        if op == "stop":
            # the shutdown path must work regardless of lease state —
            # a fenced child still exits cleanly
            return {"ok": True, "stopping": True}
        if fstate["fenced"]:
            # THE fence: no op carrying the revoked epoch (or none at
            # all) executes on a fenced replica — a falsely-declared-
            # dead zombie cannot double-run a resubmitted rid
            if ep is not None and int(ep) < lease["epoch"]:
                fstate["stale_rejects"] += 1
                return {"ok": False, "error": "stale_epoch",
                        "epoch": lease["epoch"]}
            return {"ok": False, "error": "fenced",
                    "epoch": lease["epoch"]}
        if ep is not None:
            ep = int(ep)
            if ep > lease["epoch"]:
                if lease["epoch"] == 0:
                    lease["epoch"] = ep     # implicit grant (no hello)
                else:
                    # someone holds a NEWER lease for this replica id:
                    # this process was superseded — fence, don't race
                    _self_fence("superseded")
                    lease["epoch"] = ep
                    return {"ok": False, "error": "fenced",
                            "epoch": lease["epoch"]}
            elif ep < lease["epoch"]:
                fstate["stale_rejects"] += 1
                return {"ok": False, "error": "stale_epoch",
                        "epoch": lease["epoch"]}
            now_t = clock()
            lt = lease["timeout_s"]
            if (lt is not None and lease["last_contact"] is not None
                    and now_t - lease["last_contact"] > float(lt)):
                # the router has been silent longer than the lease: our
                # epoch may be revoked on the other side of a partition
                # — fence unilaterally rather than keep decoding rids
                # that are being resubmitted elsewhere
                _self_fence("lease-expired")
                return {"ok": False, "error": "fenced",
                        "epoch": lease["epoch"]}
            lease["last_contact"] = now_t
        if op == "submit":
            rid = int(msg["rid"])
            if rid in known:
                if tracer is not None:
                    tracer.instant("dup_submit", rid=rid)
                return {"ok": True, "rid": rid, "duplicate": True}
            if draining:
                # the drain contract: admit nothing new; the fleet's
                # reconcile re-homes the request
                return {"ok": False, "rid": rid, "reason": "draining"}
            sched.submit(msg["prompt"], int(msg["max_new_tokens"]),
                         eos_id=msg.get("eos_id"),
                         deadline_s=msg.get("deadline_s"),
                         priority=int(msg.get("priority") or 0),
                         rid=rid, submit_ts=msg.get("submit_ts"),
                         retries=int(msg.get("retries") or 0))
            known.add(rid)
            return {"ok": True, "rid": rid, "duplicate": False}
        if op == "adopt":
            # prefill→decode handoff (ISSUE 18): the KV pages arrived
            # as framed binary payloads riding this message
            rid = int(msg["rid"])
            if rid in known:
                return {"ok": True, "rid": rid, "duplicate": True}
            if draining:
                return {"ok": False, "rid": rid, "reason": "draining"}
            blobs = msg.get("blobs") or []
            if msg.get("_corrupt_blobs") or any(b is None for b in blobs):
                # a payload failed its CRC: frame sync survived (the
                # whole frame was consumed), so refuse cleanly — the
                # fleet retries or re-homes
                return {"ok": False, "rid": rid,
                        "reason": "corrupt-payload"}
            from .kv_cache import blobs_to_pages
            cache = engine.cache
            try:
                kpages, vpages = blobs_to_pages(
                    blobs, num_layers=cache.num_layers,
                    block_size=cache.block_size,
                    num_heads=cache.num_heads, head_dim=cache.head_dim,
                    quantized=cache.quantized, dtype=cache.dtype)
            except ValueError as e:
                return {"ok": False, "rid": rid,
                        "reason": f"corrupt-payload: {e}"}
            req = sched.adopt(msg["meta"], kpages, vpages)
            if req is None:
                return {"ok": False, "rid": rid,
                        "reason": sched.last_backpressure or "capacity"}
            known.add(rid)
            return {"ok": True, "rid": rid, "duplicate": False}
        if op == "tick":
            sched.step()
            beat(msg.get("now"))
            completed = []
            comp = sched.completed
            while collected < len(comp):
                req = comp[collected]
                collected += 1
                known.discard(req.rid)
                completed.append({"record": req.record(),
                                  "tokens": list(req.tokens)})
            reply = {"ok": True, "tick": msg.get("tick"),
                     "completed": completed, "events": buf.drain(),
                     "load": load_report()}
            # finished-prefill KV packages ship on the tick reply as
            # framed binary payloads (a prefill-role replica only;
            # getattr: transport tests drive serve_loop with fakes)
            pop = getattr(sched, "pop_handoffs", None)
            if pop is not None:
                handoffs, out_blobs = [], []
                from .kv_cache import pages_to_blobs
                for req, hmeta, kpages, vpages in pop():
                    hb = pages_to_blobs(kpages, vpages)
                    known.discard(req.rid)   # fleet-owned now: a later
                    # re-delivery (decode death) must not dedupe here
                    handoffs.append({"rid": req.rid, "meta": hmeta,
                                     "nblobs": len(hb)})
                    out_blobs.extend(hb)
                if handoffs:
                    reply["handoffs"] = handoffs
                    reply["_blobs"] = out_blobs
            if tracer is not None:
                # span-batch shipping: spans ride the tick reply the
                # work already uses (no side-channel files; a SIGKILL
                # loses at most one tick's worth)
                reply["spans"] = tracer.drain_events()
            if metrics is not None:
                # registry deltas piggyback the same way: whatever
                # changed since the last drain rides this reply, and a
                # batch undelivered at SIGKILL honestly dies with the
                # process (the drained watermark died too)
                deltas = metrics.drain_delta()
                if deltas:
                    reply["metrics"] = deltas
            return reply
        if op == "drain":
            draining = True
            rids = []
            for req in list(sched.queue):
                sched.queue.remove(req)
                known.discard(req.rid)
                rids.append(req.rid)
            return {"ok": True, "queued_rids": rids,
                    "load": load_report()}
        if op == "resume":
            # drain cancelled (the raced-capacity yield, PR 11): this
            # replica is live again and must admit
            draining = False
            return {"ok": True, "load": load_report()}
        if op == "metrics":
            # remote scrape (ISSUE 19): the full local registry as
            # Prometheus text. A read, not a drain — the tick-reply
            # delta watermarks are untouched, so scraping never steals
            # increments from the parent's merge.
            if metrics is None:
                return {"ok": False, "error": "metrics not enabled"}
            return {"ok": True, "exposition": metrics.render()}
        if op == "stats":
            return {"ok": True, "load": load_report(),
                    "compile_counts": engine.compile_counts(),
                    "free_blocks": engine.cache.free_blocks,
                    "num_blocks": engine.cache.num_blocks,
                    "ticks": engine.ticks,
                    "tokens_generated": engine.tokens_generated,
                    "fenced": fstate["fenced"],
                    "fence": fstate["info"],
                    "stale_epoch_rejects": fstate["stale_rejects"]}
        return {"ok": False, "error": f"unknown op {op!r}"}

    while True:
        try:
            msg = reader.read_frame()
        except tp.TransportClosed:
            return 0                    # parent went away: clean exit
        # the blob channel: a message declaring nblobs is followed by
        # that many binary frames — consumed UNCONDITIONALLY (a cached-
        # seq retransmit resends its blobs too; skipping them would
        # desync the stream). A CRC-failed payload keeps frame sync
        # (the whole frame was consumed) and is classified, not fatal.
        nblobs = int(msg.get("nblobs") or 0)
        if nblobs:
            blobs: List[Optional[bytes]] = []
            corrupt = False
            try:
                for _ in range(nblobs):
                    try:
                        blobs.append(reader.read_binary_frame())
                    except tp.TransportCorrupt:
                        blobs.append(None)
                        corrupt = True
            except tp.TransportClosed:
                return 0
            msg["blobs"] = blobs
            if corrupt:
                msg["_corrupt_blobs"] = True
        seq = msg.get("seq", 0)
        if seq in reply_cache:
            # at-least-once retransmit: replay the cached bytes, never
            # re-execute the work
            try:
                write_file.write(reply_cache[seq])
                write_file.flush()
            except (BrokenPipeError, OSError):
                return 0
            continue
        try:
            reply = handle(msg)
        except Exception as e:          # a handler bug must not kill the
            # replica — classify it; the parent re-homes on not-ok
            reply = {"ok": False,
                     "error": f"{type(e).__name__}: {e}"}
        reply["seq"] = seq
        # every reply carries the child's lease epoch: the parent
        # discards whole replies from an epoch it already revoked
        reply.setdefault("epoch", lease["epoch"])
        out_blobs = reply.pop("_blobs", None) or []
        if out_blobs:
            reply["nblobs"] = len(out_blobs)
        data = tp.encode_frame(reply)
        for b in out_blobs:
            # cached as ONE byte string with the reply: a retransmit
            # replays message + payloads exactly as first sent
            data += tp.encode_binary_frame(b)
        reply_cache[seq] = data
        while len(reply_cache) > reply_cache_size:
            reply_cache.popitem(last=False)
        try:
            if msg.get("inject_drop_reply"):
                pass                    # work done; the reply is "lost"
            elif msg.get("inject_corrupt_reply"):
                # a framed garble: valid length prefix, unparseable body
                garbage = b"\xff\xfe<corrupt-reply>"
                write_file.write(
                    tp._HEADER.pack(len(garbage)) + garbage)
                write_file.flush()
            else:
                write_file.write(data)
                write_file.flush()
        except (BrokenPipeError, OSError):
            return 0
        if reply.get("stopping"):
            return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serve.replica_proc",
        description="One process-isolated serving replica speaking the "
                    "length-prefixed frame protocol on stdin/stdout.")
    p.add_argument("--spec", required=True,
                   help="JSON spec (or @path to a JSON file): model "
                        "config, engine kwargs, variables npz, root, "
                        "replica_id")
    p.add_argument("--connect", default=None, metavar="HOST:PORT",
                   help="dial the fleet's TCP listener and speak the "
                        "frame protocol over the socket instead of "
                        "stdin/stdout (cross-host serving; loopback "
                        "in CI)")
    args = p.parse_args(argv)

    from . import transport as tp

    if args.connect:
        # socket transport: stdout was already pointed at stderr by the
        # spawner; still shield fd 1 so stray prints go to the log
        os.dup2(2, 1)
        sys.stdout = sys.stderr
        host, _, port = args.connect.rpartition(":")
        sock = tp.connect(host or "127.0.0.1", int(port))
        read_file: Any = tp.SocketFrameReader(sock)
        out: Any = tp.SocketWriter(sock)
    else:
        # claim the transport BEFORE anything can print: dup the real
        # stdout for frames, then point fd 1 at stderr so stray prints
        # (library warnings, user code) can never tear a frame
        out = os.fdopen(os.dup(1), "wb")
        os.dup2(2, 1)
        sys.stdout = sys.stderr
        read_file = sys.stdin.buffer

    raw = args.spec
    if raw.startswith("@"):
        with open(raw[1:]) as f:
            raw = f.read()
    spec = json.loads(raw)
    engine, sched, buf, clock, startup, metrics = _build(spec)
    return serve_loop(
        read_file, out, engine=engine, sched=sched, buf=buf,
        clock=clock, root=spec["root"],
        replica_id=int(spec["replica_id"]), startup=startup,
        metrics=metrics, lease_timeout_s=spec.get("lease_timeout_s"))


if __name__ == "__main__":
    sys.exit(main())
