"""Traffic-realistic workload generation (ISSUE 11): "millions of users"
is a traffic *shape*, not a tokens/sec number — this module generates the
shape so the fleet can be scheduled under it, measured under it, and
fault-drilled under it, deterministically.

Three properties of production LLM traffic the serving literature keeps
measuring (PagedAttention's motivating traces [S1], Orca's arrival model
[S2]), each seeded and reproducible here:

- **Arrival process**: Poisson (independent users) or bursty — a
  two-state modulated Poisson (thundering herds, retry storms): gaps are
  exponential at ``rate_rps`` in the quiet state and
  ``rate_rps * burst_factor`` inside a burst, with the state flipping
  with probability ``1/burst_len`` per arrival (geometric burst and
  quiet lengths).
- **Ragged lengths**: prompt and decode budgets are log-normal around
  the geometric mean of their ``(lo, hi)`` range, clipped — short
  requests dominate, long stragglers exist, which is exactly the shape
  continuous batching and SJF exist to absorb.
- **Sessions with shareable prefixes**: a fraction ``p_session`` of
  requests belong to one of ``n_sessions`` conversations, sharing that
  session's fixed prompt prefix (system prompt / chat history) plus a
  fresh tail — the trace shape prefix-cache block sharing and router
  session affinity are measured against.

Per-request SLO fields ride along: a deadline (uniform in a range, on a
``p_deadline`` fraction of requests) and a priority tier drawn from
``priorities``. The output is a plain list of :class:`GenRequest`
sorted by arrival time; ``ServingFleet.play`` (or any custom loop)
replays it against an injectable clock — :class:`SimClock` for
deterministic CI, the wall clock for real measurement.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["GenRequest", "SimClock", "make_workload",
           "hostile_workload", "workload_stats"]


class SimClock:
    """An injectable virtual clock: the drive loop advances it a fixed
    ``dt`` per fleet tick, so arrivals, deadlines, heartbeat staleness
    and predicted delays are all deterministic functions of tick counts —
    the whole fleet fault drill replays bit-identically in CI."""

    def __init__(self, t0: float = 0.0):
        self.t = float(t0)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


@dataclasses.dataclass
class GenRequest:
    """One generated arrival: submit ``prompt`` at ``at_s`` with the
    decode budget and SLO fields attached."""
    at_s: float
    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int] = None
    deadline_s: Optional[float] = None
    priority: int = 0
    session_id: Optional[int] = None


def _ragged(rng: np.random.RandomState, lo: int, hi: int,
            sigma: float) -> int:
    """Log-normal length around the geometric mean of [lo, hi], clipped.
    sigma=0 degenerates to the geometric mean (deterministic lengths).
    The lower bound is clamped to 1: lengths are counts (a 0 bound would
    put 0 inside the log)."""
    lo, hi = max(1, int(lo)), int(hi)
    if hi <= lo:
        return lo
    mu = math.log(math.sqrt(lo * hi))
    x = rng.lognormal(mu, sigma) if sigma > 0 else math.exp(mu)
    return int(np.clip(int(round(x)), lo, hi))


def make_workload(n_requests: int, vocab: int, *, seed: int = 0,
                  rate_rps: float = 8.0, arrival: str = "poisson",
                  burst_factor: float = 6.0, burst_len: float = 4.0,
                  prompt_len: Tuple[int, int] = (2, 12),
                  max_new: Tuple[int, int] = (2, 12),
                  sigma: float = 0.6,
                  n_sessions: int = 0, session_prefix_len: int = 6,
                  p_session: float = 0.6,
                  deadline_s=None, p_deadline: float = 1.0,
                  priorities: Sequence[int] = (0,),
                  priority_weights: Optional[Sequence[float]] = None,
                  eos_id: Optional[int] = None,
                  max_total: Optional[int] = None) -> List[GenRequest]:
    """Generate ``n_requests`` seeded arrivals (see module docstring for
    the model). ``deadline_s`` is a float or ``(lo, hi)`` range applied
    to a ``p_deadline`` fraction of requests; ``max_total`` clamps
    ``prompt + max_new`` to a slot capacity (the generator trims the
    decode budget first, then the prompt tail)."""
    if arrival not in ("poisson", "bursty"):
        raise ValueError(f"arrival must be 'poisson'|'bursty', "
                         f"got {arrival!r}")
    rng = np.random.RandomState(seed)
    prefixes = [list(rng.randint(1, vocab, session_prefix_len))
                for _ in range(n_sessions)]
    out: List[GenRequest] = []
    t, in_burst = 0.0, False
    for _ in range(n_requests):
        rate = rate_rps * (burst_factor if in_burst else 1.0)
        t += float(rng.exponential(1.0 / max(rate, 1e-9)))
        if arrival == "bursty" and rng.rand() < 1.0 / max(burst_len, 1.0):
            in_burst = not in_burst

        plen = _ragged(rng, *prompt_len, sigma=sigma)
        mnew = _ragged(rng, *max_new, sigma=sigma)
        sid: Optional[int] = None
        if n_sessions > 0 and rng.rand() < p_session:
            sid = int(rng.randint(n_sessions))
            tail = max(1, plen - session_prefix_len)
            prompt = prefixes[sid] + list(rng.randint(1, vocab, tail))
        else:
            prompt = list(rng.randint(1, vocab, max(1, plen)))
        if max_total is not None:
            prompt = prompt[:max(1, max_total - 1)]
            mnew = max(1, min(mnew, max_total - len(prompt)))

        dl: Optional[float] = None
        if deadline_s is not None and rng.rand() < p_deadline:
            if isinstance(deadline_s, (tuple, list)):
                dl = float(rng.uniform(deadline_s[0], deadline_s[1]))
            else:
                dl = float(deadline_s)
        prio = int(rng.choice(list(priorities), p=priority_weights))
        out.append(GenRequest(at_s=round(t, 6), prompt=prompt,
                              max_new_tokens=mnew, eos_id=eos_id,
                              deadline_s=dl, priority=prio,
                              session_id=sid))
    return out


def hostile_workload(n_requests: int, vocab: int, *, seed: int = 0,
                     rate_rps: float = 12000.0,
                     burst_factor: float = 10.0,
                     **kw) -> List[GenRequest]:
    """The hostile-scale preset (ISSUE 18): a 10k+ rps bursty trace —
    thundering-herd bursts an order of magnitude over the base rate —
    meant to be replayed on :class:`SimClock` against a fleet whose
    router/reconcile HOST cost per tick is the measurement
    (``fleet.stats()["router_ms"]``). Every :func:`make_workload` knob
    passes through; only the arrival process is pinned hostile."""
    return make_workload(n_requests, vocab, seed=seed,
                         rate_rps=rate_rps, arrival="bursty",
                         burst_factor=burst_factor, **kw)


def _shareable_prefix_tokens(workload: List[GenRequest]) -> int:
    """Tokens a perfect prefix cache could avoid re-storing: for each
    request, the longest common prefix with the BEST earlier request in
    the trace (first occurrences share nothing — someone must pay for
    the prefix once). Session traces make this essentially
    ``session_prefix_len`` per repeat visit; the fleet gate sizes its
    expected prefix-cache hits from exactly this number (ISSUE 12).

    The best-LCP-with-any-earlier-prompt is computed against a set of
    every prefix of every earlier prompt (a request's best LCP is k iff
    its own length-k prefix IS some earlier prompt's prefix), scanned
    longest-first — O(total tokens), where the pairwise scan the
    hostile-scale traces (ISSUE 18) replaced was O(n²·L)."""
    seen: set = set()
    total = 0
    for g in workload:
        p = tuple(g.prompt)
        best = 0
        for k in range(len(p), 0, -1):
            if p[:k] in seen:
                best = k
                break
        total += best
        for k in range(1, len(p) + 1):
            seen.add(p[:k])
    return total


def workload_stats(workload: List[GenRequest]) -> dict:
    """Shape summary of a generated workload (for bench records and
    fleet-gate sizing)."""
    if not workload:
        return {"n": 0}
    gaps = np.diff([g.at_s for g in workload]) if len(workload) > 1 else [0]
    prompt_tokens = sum(len(g.prompt) for g in workload)
    shareable = _shareable_prefix_tokens(workload)
    return {
        "n": len(workload),
        "span_s": round(workload[-1].at_s - workload[0].at_s, 4),
        "mean_gap_s": round(float(np.mean(gaps)), 4),
        "max_gap_s": round(float(np.max(gaps)), 4),
        "mean_prompt_len": round(float(np.mean(
            [len(g.prompt) for g in workload])), 2),
        "mean_max_new": round(float(np.mean(
            [g.max_new_tokens for g in workload])), 2),
        "with_deadline": sum(g.deadline_s is not None for g in workload),
        "with_session": sum(g.session_id is not None for g in workload),
        "sessions": len({g.session_id for g in workload
                         if g.session_id is not None}),
        "prompt_tokens_total": prompt_tokens,
        "shareable_prefix_tokens": shareable,
        "shareable_prefix_ratio": round(shareable / prompt_tokens, 4)
        if prompt_tokens else 0.0,
    }
