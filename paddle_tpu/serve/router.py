"""Fleet routing policy (ISSUE 11): which replica gets a request, and
whether it gets one at all.

The router is deliberately a POLICY object, separate from the fleet's
mechanics: it reads replica health and load, and returns a
:class:`RouteDecision` — place on this worker, park (no healthy
capacity), or shed. The fleet owns delivery, lineage and resubmission.

Three policy layers, in decision order:

- **Health gating** reuses the PR-10 heartbeat machinery
  (``parallel/multihost``): every replica worker writes an atomic
  heartbeat file under the fleet root each tick, and
  :meth:`FleetRouter.refresh_health` declares a replica dead when its
  beat goes stale past ``heartbeat_timeout_s`` — the SAME evidence a
  cross-process or cross-host deployment would use (the beats carry a
  load payload for that future, though in-process placement reads the
  scheduler directly). Death is declared from FILE staleness, never
  from in-process knowledge: a killed replica is only treated as dead
  once the watchdog could have known (Bamboo's lesson [R2] — death is
  routine, and it is OBSERVED, not announced).
- **Placement**: session affinity first — a session's requests re-land
  on the replica already holding its prefix KV (the prefix-cache
  locality the ROADMAP block-sharing item will exploit; mappings
  self-heal when the pinned replica dies or drains) — then least-loaded
  by ``pending_new_tokens`` (the tick-denominated backlog), replica id
  breaking ties deterministically.
- **Shedding**: a deadline-carrying request whose predicted completion
  on the chosen replica already blows its remaining budget is rejected
  NOW (``finish_reason="shed"``) instead of queued to die later; the
  engine's structured :class:`~paddle_tpu.serve.engine.AdmitProbe`
  reason rides the decision so the record says WHY ("blocks" pool
  saturation vs plain queue delay). Resubmissions after a replica death
  are never shed — the user already waited; the deadline eviction path
  owns that verdict.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

from ..parallel import multihost

__all__ = ["FleetRouter", "RouteDecision"]


@dataclasses.dataclass
class RouteDecision:
    """The router's verdict for one request: ``worker`` (None = no
    healthy capacity — park and retry), ``shed`` with the reason, the
    predicted completion estimate that drove it, and the chosen
    replica's structured admission backpressure."""
    worker: Optional[Any] = None
    shed: bool = False
    shed_reason: Optional[str] = None
    predicted_completion_s: Optional[float] = None
    backpressure: Optional[str] = None
    affinity_hit: bool = False


class FleetRouter:
    """Load-balances requests over ``workers`` (ReplicaWorker list) with
    session affinity, heartbeat health gating, and SLO shedding."""

    def __init__(self, workers: List[Any], root: str, *,
                 heartbeat_timeout_s: float = 3.0,
                 clock=time.perf_counter, affinity: bool = True,
                 shed: bool = True, max_sessions: int = 4096,
                 tracer=None, death_confirmations: int = 2,
                 metrics=None):
        # held BY REFERENCE, not copied: the autoscaler (ISSUE 13)
        # appends newly spawned replicas to the fleet's worker list and
        # the router must see them become placeable immediately
        self.workers = workers
        self.root = root
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.clock = clock
        self.affinity = affinity
        self.shed = shed
        # session_id -> replica_id, LRU-bounded at max_sessions: pins
        # are refreshed on every route, so evicting the coldest pin
        # costs at worst one prefix-locality miss for a dormant session
        # — never unbounded growth on a long-lived fleet
        self.max_sessions = int(max_sessions)
        self.sessions: Dict[int, int] = {}
        # optional fleet Tracer (ISSUE 17): death verdicts become
        # timeline instants on the router lane
        self.tracer = tracer
        # flap damping (ISSUE 20): a replica must look stale K times IN
        # A ROW before the death verdict lands — one beat arriving a
        # hair late (GC pause, loaded host, brief link flap) costs one
        # grace observation instead of a full fence/resubmit cycle.
        # K=1 restores the old single-observation behavior.
        self.death_confirmations = max(1, int(death_confirmations))
        self._stale_streak: Dict[int, int] = {}
        self.false_deaths_averted = 0
        self.metrics = metrics

    # -- health ------------------------------------------------------------

    def refresh_health(self, now: Optional[float] = None) -> List[Any]:
        """Probe the heartbeat files and flip stale replicas to
        ``"dead"``; returns the NEWLY dead workers (the fleet resubmits
        their requests). Only live/draining replicas are expected to
        beat — released ones left quietly."""
        now = self.clock() if now is None else now
        expected = [w.replica_id for w in self.workers
                    if w.state in ("live", "draining")]
        if not expected:
            return []
        stale = set(multihost.detect_dead_hosts(
            self.root, self.heartbeat_timeout_s,
            expected_hosts=expected, now=now))
        newly = []
        for w in self.workers:
            if w.state not in ("live", "draining"):
                continue
            rid = w.replica_id
            if rid not in stale:
                if self._stale_streak.pop(rid, 0):
                    # it came back before the verdict: a flap absorbed,
                    # not a death — the damping satellite's payoff
                    self.false_deaths_averted += 1
                    if self.metrics is not None:
                        self.metrics.counter(
                            "fleet_false_death_averted",
                            "stale-looking replicas that beat again "
                            "before K confirmations").inc()
                    if self.tracer is not None:
                        self.tracer.instant("false_death_averted",
                                            replica=rid)
                continue
            streak = self._stale_streak.get(rid, 0) + 1
            self._stale_streak[rid] = streak
            if streak < self.death_confirmations:
                continue
            del self._stale_streak[rid]
            w.state = "dead"
            newly.append(w)
            if self.tracer is not None:
                self.tracer.instant("replica_dead", replica=rid)
            # unpin this replica's sessions: they re-pin wherever
            # their next request lands
            for sid in [s for s, r in self.sessions.items()
                        if r == rid]:
                del self.sessions[sid]
        return newly

    def candidates(self, role: Optional[str] = None) -> List[Any]:
        """Placeable replicas: live state (draining replicas finish what
        they have but admit nothing new — the drain contract). With
        ``role``, only replicas serving that role — a "both" replica
        serves either (the colocated fallback in a mixed fleet)."""
        out = [w for w in self.workers if w.state == "live"]
        if role is not None:
            out = [w for w in out
                   if getattr(w, "role", "both") in (role, "both")]
        return out

    @staticmethod
    def load_key(worker, role: Optional[str] = None):
        """The role-aware placement key (ISSUE 18): prefill placement
        balances on ``prefill_backlog`` (prompt tokens not yet
        prefilled — the work a prefill replica actually does; decode
        debt would be noise there), everything else on
        ``pending_new_tokens`` (the tick-denominated decode backlog).
        Replica id breaks ties deterministically."""
        if role == "prefill":
            return (worker.scheduler.prefill_backlog(),
                    worker.replica_id)
        return (worker.scheduler.pending_new_tokens(),
                worker.replica_id)

    # -- placement ---------------------------------------------------------

    def route(self, *, prompt_len: int, max_new_tokens: int,
              deadline_s: Optional[float] = None,
              session_id: Optional[int] = None,
              submit_ts: Optional[float] = None,
              now: Optional[float] = None,
              allow_shed: bool = True,
              role: Optional[str] = None) -> RouteDecision:
        cands = self.candidates(role)
        if not cands:
            return RouteDecision(worker=None)
        least = min(cands, key=lambda w: self.load_key(w, role))
        chosen, hit = None, False
        if self.affinity and session_id is not None:
            pinned = self.sessions.get(session_id)
            chosen = next((w for w in cands if w.replica_id == pinned),
                          None)
            hit = chosen is not None
        if chosen is None:
            chosen = least

        def would_shed(worker):
            if not (allow_shed and self.shed and deadline_s is not None):
                return False
            est = worker.scheduler.predicted_completion_s(max_new_tokens)
            if est is None:
                return False
            t = self.clock() if now is None else now
            waited = (0.0 if submit_ts is None
                      else max(0.0, t - submit_ts))
            return waited + est > deadline_s

        if would_shed(chosen) and chosen is not least:
            # affinity must not cost the deadline: before a terminal
            # shed verdict, fall back to the least-loaded replica —
            # losing prefix locality beats losing the request
            chosen, hit = least, False
        est = chosen.scheduler.predicted_completion_s(max_new_tokens)
        probe = chosen.engine.admit_probe(
            max(prompt_len + max_new_tokens - 1, prompt_len))
        if would_shed(chosen):
            return RouteDecision(
                worker=None, shed=True,
                shed_reason=probe.reason or "delay",
                predicted_completion_s=est,
                backpressure=probe.reason)
        if self.affinity and session_id is not None:
            # refresh the LRU pin (re-insert moves it to newest)
            self.sessions.pop(session_id, None)
            self.sessions[session_id] = chosen.replica_id
            while len(self.sessions) > self.max_sessions:
                self.sessions.pop(next(iter(self.sessions)))
        return RouteDecision(worker=chosen, predicted_completion_s=est,
                             backpressure=probe.reason, affinity_hit=hit)
