"""Multi-replica serving fleet (ISSUE 11): replica death is routine.

PR 10 made *training* recovery a supervised, continuously-fault-injected
subsystem; this module applies the same doctrine to serving. A
:class:`ServingFleet` runs N :class:`ReplicaWorker`\\ s — each one a
:class:`~paddle_tpu.serve.engine.DecodeEngine` +
:class:`~paddle_tpu.serve.scheduler.ContinuousBatchingScheduler` pair —
behind a :class:`~paddle_tpu.serve.router.FleetRouter`, and guarantees
that EVERY submitted request reaches a terminal ``finish_reason``
(``"length"|"eos"|"timeout"|"shed"``) no matter which replica dies,
stalls, or drains mid-flight.

The recovery contract, and how each piece is honest about what a
distributed deployment could actually know:

- **Death is observed, not announced.** A killed replica simply stops
  ticking and heartbeating; the router declares it dead only when its
  heartbeat FILE (the PR-10 ``parallel/multihost`` machinery) goes stale
  past the timeout. Until then its requests wait — exactly the
  detection latency a real fleet pays.
- **Resubmission is a reconcile sweep, keyed by request id.** The fleet
  keeps the assignment table (rid → replica). Every tick it verifies
  each non-terminal request is still held by a live replica that
  actually KNOWS it; orphans (dead/released replica, or a delivery the
  ``drop_submit`` fault ate) are resubmitted to a survivor with the
  GLOBAL rid, the ORIGINAL submit timestamp (deadlines never reset),
  and a bumped ``retries`` count. The abandoned attempt emits a
  ``finish_reason="retried"`` request record — the lineage is in the
  telemetry stream, one terminal record per rid, always.
- **Resubmit is idempotent.** A duplicate delivery (the
  ``duplicate_submit`` fault — an RPC retry racing its original) is
  dropped at the replica boundary because the rid is already known
  there; a completion for a superseded attempt is dropped at collection
  because the fleet request is already terminal or re-homed
  (``stale_completions`` counts both, asserting zero surprise).
- **A stalled replica self-fences.** A replica that stops beating long
  enough to be declared dead (a GC pause, a network partition) finds,
  on waking, that its lease is gone: it evicts every slot, frees its
  blocks, and stays out of service — it never completes a request the
  fleet already re-homed (the Bamboo [R2] zombie rule).
- **Drain is the elastic scale-down path.** ``drain(replica)`` stops
  admission, re-routes the replica's QUEUED requests to survivors,
  lets RUNNING slots finish in place, then releases the replica with
  every block back in its pool — scale-down loses zero requests.

Fault injection rides the PR-10 :class:`~paddle_tpu.train.faults.
FaultSchedule` (``kill_replica_at_tick``, ``stall_replica_at_tick``,
``drop_submit_at``, ``duplicate_submit_at``), so the whole fleet path is
deterministically drilled in CI (``bench.py --fleet-child``) the same
way ``run_resilient`` is.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import logging
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional

from ..parallel import multihost
from .router import FleetRouter
from .scheduler import ContinuousBatchingScheduler, Request

__all__ = ["ReplicaWorker", "FleetRequest", "ServingFleet"]

_log = logging.getLogger("paddle_tpu.serve.fleet")


class ReplicaWorker:
    """One serving replica: engine + scheduler + heartbeat + lifecycle.

    ``state`` machine: ``"live"`` → (``drain``) → ``"draining"`` →
    ``"released"``; any non-released state → ``"dead"`` (set ONLY by the
    router's heartbeat verdict). ``killed`` and ``stall`` are fault-
    injection flags beneath the state machine — they change what the
    replica *does* (nothing), not what the fleet *knows* (that takes a
    stale heartbeat)."""

    def __init__(self, replica_id: int, engine, scheduler, root: str):
        self.replica_id = int(replica_id)
        self.engine = engine
        self.scheduler = scheduler
        self.root = root
        self.state = "live"
        self.killed = False
        self._stall_until: Optional[int] = None
        self._fenced = False
        self.known: set = set()           # rids actually delivered here
        self._collected = 0               # scheduler.completed cursor
        self._hb_seq = 0

    # -- fault hooks -------------------------------------------------------

    def kill(self) -> None:
        """Process death: no more ticks, no more beats. The engine's
        blocks die with it (a real process loses its HBM); survivors'
        pools are untouched."""
        self.killed = True

    def stall(self, until_tick: int) -> None:
        """Hang (GC pause / partition) until the fleet tick index
        ``until_tick``: no work, no beats — but unlike ``kill``, the
        replica may wake, and must then self-fence if its lease died."""
        self._stall_until = int(until_tick)

    def stalled(self, tick: int) -> bool:
        return self._stall_until is not None and tick < self._stall_until

    # -- liveness ----------------------------------------------------------

    def beat(self, now: float) -> None:
        self._hb_seq += 1
        multihost.write_heartbeat(
            self.root, host_id=self.replica_id, seq=self._hb_seq, now=now,
            extra={"role": "serving-replica",
                   "pending_new_tokens": self.scheduler.pending_new_tokens(),
                   "running": len(self.scheduler.running),
                   "queued": len(self.scheduler.queue),
                   # the prefix-locality payoff rides the beat: a
                   # cross-process router could weigh affinity against
                   # load on the same evidence it health-checks
                   "prefix_hit_blocks": self.engine.cache.prefix_hit_blocks})

    def reset(self) -> None:
        """Self-fence: evict every slot (blocks back to the pool), drop
        all bookkeeping. Run by a replica that wakes from a stall to
        find itself declared dead — its requests live elsewhere now."""
        for slot in list(self.scheduler.running):
            self.engine.evict(slot)
        for slot in list(self.scheduler.prefilling):
            self.engine.evict(slot)
        self.scheduler.running.clear()
        self.scheduler.prefilling.clear()
        self.scheduler.queue.clear()
        self.known.clear()

    def tick(self, now: float, tick_idx: int) -> None:
        """One replica tick: step the scheduler, then beat. Killed,
        released and stalled replicas do nothing; a dead one that can
        still run (a woken zombie) fences itself exactly once."""
        if self.killed or self.state == "released":
            return
        if self.state == "dead":
            if not self._fenced and not self.stalled(tick_idx):
                _log.warning("replica %d woke fenced (lease lost): "
                             "resetting", self.replica_id)
                self.reset()
                self._fenced = True
            return
        if self.stalled(tick_idx):
            return
        self.scheduler.step()
        self.beat(now)


@dataclasses.dataclass
class FleetRequest:
    """One fleet-level request: the global identity (``rid``), the SLO
    fields, the current assignment, and the resubmission lineage. The
    terminal request record (the one non-"retried" telemetry record for
    this rid) lands in ``record``."""
    rid: int
    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int]
    deadline_s: Optional[float]
    priority: int
    session_id: Optional[int]
    submit_ts: float
    replica: Optional[int] = None
    retries: int = 0
    attempts: List[int] = dataclasses.field(default_factory=list)
    local: Optional[Request] = None       # current replica-side attempt
    record: Optional[Dict[str, Any]] = None

    @property
    def done(self) -> bool:
        return self.record is not None

    @property
    def finish_reason(self) -> Optional[str]:
        return self.record["finish_reason"] if self.record else None

    @property
    def tokens(self) -> List[int]:
        return list(self.local.tokens) if self.local is not None else []


class ServingFleet:
    """N replica workers + a router + the recovery loop (see module
    docstring).

    Args:
      make_engine: ``callable(replica_id) -> DecodeEngine`` — one engine
        per replica (homogeneous capacity assumed for validation).
      n_replicas: fleet width.
      telemetry: shared :class:`~paddle_tpu.obs.Telemetry`; every
        replica's request/evict records and the fleet's shed/replica
        events land in one stream (records carry the GLOBAL rid).
      root: heartbeat directory (a fresh tempdir by default).
      clock: shared injectable clock — heartbeats, deadlines, arrival
        replay and predictions all read it (``SimClock`` for CI).
      heartbeat_timeout_s: staleness after which a replica is dead.
      order / shed / est_tick_s: scheduler admission policy, router
        shedding, and the cold-start tick-time prior (see
        :class:`ContinuousBatchingScheduler`).
      faults: a :class:`~paddle_tpu.train.faults.FaultSchedule` with the
        serving points armed.
    """

    def __init__(self, make_engine: Callable[[int], Any],
                 n_replicas: int, *, telemetry=None, root: Optional[str]
                 = None, clock=None, heartbeat_timeout_s: float = 3.0,
                 order: str = "fcfs", shed: bool = True,
                 affinity: bool = True,
                 est_tick_s: Optional[float] = None, faults=None):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self.telemetry = telemetry
        self.clock = clock if clock is not None else time.perf_counter
        self.root = root or tempfile.mkdtemp(prefix="paddle_tpu_fleet_")
        self.faults = faults
        self.workers: List[ReplicaWorker] = []
        for i in range(n_replicas):
            eng = make_engine(i)
            sched = ContinuousBatchingScheduler(
                eng, telemetry=telemetry, order=order, shed=False,
                est_tick_s=est_tick_s, clock=self.clock)
            self.workers.append(ReplicaWorker(i, eng, sched, self.root))
        self.router = FleetRouter(
            self.workers, self.root,
            heartbeat_timeout_s=heartbeat_timeout_s, clock=self.clock,
            affinity=affinity, shed=shed)
        now = self.clock()
        for w in self.workers:            # join the fleet: first beat
            w.beat(now)
        self.requests: Dict[int, FleetRequest] = {}
        # the non-terminal subset, kept separately so the per-tick
        # reconcile/outstanding sweeps are O(in-flight), not
        # O(everything ever submitted); `requests` is the full ledger
        # (prune_terminal() bounds it for long-lived fleets)
        self._active: Dict[int, FleetRequest] = {}
        self._rid = itertools.count()
        self._unplaced: List[FleetRequest] = []
        self.ticks = 0
        self.resubmits = 0
        self.shed_count = 0
        self.duplicates_dropped = 0
        self.stale_completions = 0

    # -- helpers -----------------------------------------------------------

    def _worker(self, replica_id: int) -> ReplicaWorker:
        return self.workers[replica_id]

    def _emit(self, rec: Dict[str, Any]) -> None:
        if self.telemetry is not None:
            self.telemetry.emit_event(rec)

    def _replica_event(self, event: str, worker: ReplicaWorker,
                       **extra) -> None:
        self._emit({"kind": "replica", "event": event,
                    "replica": worker.replica_id, "tick": self.ticks,
                    **extra})

    def _finalize(self, fr: FleetRequest, emit: bool = True) -> None:
        """A request reached its terminal record: drop it from the
        in-flight index (and emit the record when the fleet built it —
        replica-side completions were already emitted by the
        scheduler)."""
        if emit:
            self._emit(fr.record)
        self._active.pop(fr.rid, None)

    def _terminal_record(self, fr: FleetRequest, reason: str, now: float,
                         **extra) -> Dict[str, Any]:
        """Fleet-side terminal record (shed / parked-timeout — requests
        no replica ever ran) built through ``Request.record()`` so the
        schema lives in exactly one place."""
        req = Request(rid=fr.rid, prompt=fr.prompt,
                      max_new_tokens=fr.max_new_tokens, eos_id=fr.eos_id,
                      deadline_s=fr.deadline_s, priority=fr.priority,
                      retries=fr.retries, submit_ts=fr.submit_ts,
                      finish_ts=now, finish_reason=reason)
        rec = req.record()
        rec.update(extra)
        return rec

    # -- submission --------------------------------------------------------

    def submit(self, prompt: List[int], max_new_tokens: int,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None, priority: int = 0,
               session_id: Optional[int] = None) -> FleetRequest:
        """Route one request into the fleet. Returns a
        :class:`FleetRequest` immediately — possibly already terminal
        (``"shed"``)."""
        width = self.workers[0].engine.context_width
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new_tokens > width:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                f"exceeds slot capacity {width}")
        now = self.clock()
        fr = FleetRequest(rid=next(self._rid), prompt=list(prompt),
                          max_new_tokens=max_new_tokens, eos_id=eos_id,
                          deadline_s=deadline_s, priority=priority,
                          session_id=session_id, submit_ts=now)
        self.requests[fr.rid] = fr
        self._active[fr.rid] = fr
        dec = self.router.route(
            prompt_len=len(fr.prompt), max_new_tokens=max_new_tokens,
            deadline_s=deadline_s, session_id=session_id,
            submit_ts=now, now=now)
        if dec.shed:
            self._shed(fr, dec)
            return fr
        if dec.worker is None:
            self._unplaced.append(fr)     # no healthy capacity: park
            return fr
        self._deliver(fr, dec.worker)
        if (self.faults is not None
                and self.faults.should_duplicate_submit(fr.rid)):
            # RPC-retry duplicate: same request delivered again — the
            # replica-boundary rid check must drop it
            self._deliver(fr, dec.worker)
        return fr

    def _deliver(self, fr: FleetRequest, worker: ReplicaWorker) -> None:
        if fr.rid in worker.known:
            self.duplicates_dropped += 1
            return
        fr.replica = worker.replica_id
        fr.attempts.append(worker.replica_id)
        if (self.faults is not None
                and self.faults.should_drop_submit(fr.rid)):
            # delivery lost after assignment: the replica never learns
            # of the rid — the reconcile sweep must notice and resubmit
            fr.local = None
            return
        fr.local = worker.scheduler.submit(
            fr.prompt, fr.max_new_tokens, eos_id=fr.eos_id,
            deadline_s=fr.deadline_s, priority=fr.priority, rid=fr.rid,
            submit_ts=fr.submit_ts, retries=fr.retries)
        worker.known.add(fr.rid)

    def _shed(self, fr: FleetRequest, dec) -> None:
        self.shed_count += 1
        fr.record = self._terminal_record(
            fr, "shed", fr.submit_ts,        # shed at submit: wall 0
            shed_reason=dec.shed_reason,
            predicted_completion_s=dec.predicted_completion_s)
        self._finalize(fr)

    # -- recovery ----------------------------------------------------------

    def _resubmit(self, fr: FleetRequest, now: float,
                  reason: str) -> None:
        if fr.local is not None:
            # abandon the old attempt with visible lineage: one
            # "retried" record per abandoned attempt, never terminal
            fr.local.finish_ts = now
            fr.local.finish_reason = "retried"
            self._emit(fr.local.record())
        self.resubmits += 1
        fr.retries += 1
        fr.local, fr.replica = None, None
        _log.warning("resubmitting rid=%d (%s), retry %d",
                     fr.rid, reason, fr.retries)
        dec = self.router.route(
            prompt_len=len(fr.prompt),
            max_new_tokens=fr.max_new_tokens, deadline_s=fr.deadline_s,
            session_id=fr.session_id, submit_ts=fr.submit_ts, now=now,
            allow_shed=False)
        if dec.worker is None:
            self._unplaced.append(fr)
        else:
            self._deliver(fr, dec.worker)

    def _reconcile(self, now: float) -> None:
        """The anti-entropy sweep: every non-terminal request must be
        held by a live replica that knows its rid. Parked requests
        retry placement first (capacity may have appeared)."""
        self._place_parked(now)
        if self._unplaced and not self.router.candidates():
            # capacity emergency: parked work and zero live replicas.
            # The drain guard can be raced (a replica killed just before
            # the drain is only OBSERVED dead later), so scale-down
            # yields: cancel a drain rather than strand requests.
            w = next((w for w in self.workers if w.state == "draining"),
                     None)
            if w is not None:
                w.state = "live"
                _log.warning("drain of replica %d cancelled: no other "
                             "live capacity for %d parked request(s)",
                             w.replica_id, len(self._unplaced))
                self._replica_event("drain-cancelled", w,
                                    parked=len(self._unplaced))
                self._place_parked(now)
        for fr in list(self._active.values()):
            if fr.record is not None or fr.replica is None:
                continue
            w = self._worker(fr.replica)
            if w.state in ("dead", "released"):
                self._resubmit(fr, now, f"replica-{w.state}")
            elif fr.local is None and w.state in ("live", "draining"):
                self._resubmit(fr, now, "lost-submit")

    def _place_parked(self, now: float) -> None:
        for fr in list(self._unplaced):
            # a parked request still owns its deadline: no replica will
            # ever run the scheduler's expiry sweep for it, so the fleet
            # does — parked-forever must not exist
            if (fr.deadline_s is not None
                    and now - fr.submit_ts > fr.deadline_s):
                self._unplaced.remove(fr)
                fr.record = self._terminal_record(fr, "timeout", now)
                self._finalize(fr)
                continue
            dec = self.router.route(
                prompt_len=len(fr.prompt),
                max_new_tokens=fr.max_new_tokens,
                deadline_s=fr.deadline_s, session_id=fr.session_id,
                submit_ts=fr.submit_ts, now=now, allow_shed=False)
            if dec.worker is not None:
                self._unplaced.remove(fr)
                self._deliver(fr, dec.worker)

    def _collect(self) -> None:
        """Drain newly completed replica-side requests into fleet
        terminal records. Completions from superseded attempts (the rid
        was re-homed) or already-terminal rids are counted and dropped —
        the idempotency boundary."""
        for w in self.workers:
            if w.killed or w.state in ("dead", "released"):
                continue
            comp = w.scheduler.completed
            while w._collected < len(comp):
                req = comp[w._collected]
                w._collected += 1
                fr = self.requests.get(req.rid)
                if fr is None:
                    continue
                if fr.record is not None or fr.local is not req:
                    self.stale_completions += 1
                    continue
                fr.record = req.record()
                self._finalize(fr, emit=False)   # scheduler emitted it
                w.known.discard(req.rid)

    # -- elastic scale-down ------------------------------------------------

    def drain(self, replica_id: int) -> str:
        """Graceful drain: stop admitting to ``replica_id``, re-route
        its queued (never-admitted) requests, let running slots finish,
        then release. Returns the replica's state."""
        w = self._worker(replica_id)
        if w.state != "live":
            return w.state
        if not any(o.state == "live" for o in self.workers if o is not w):
            raise ValueError(
                f"cannot drain replica {replica_id}: it is the last live "
                f"replica (scale-down below 1 would strand every "
                f"outstanding request)")
        w.state = "draining"
        now = self.clock()
        self._replica_event("draining", w)
        for local in list(w.scheduler.queue):
            w.scheduler.queue.remove(local)
            w.known.discard(local.rid)
            fr = self.requests.get(local.rid)
            if fr is not None and fr.record is None:
                self._resubmit(fr, now, "drain")
        return w.state

    # -- the fleet tick ----------------------------------------------------

    def tick(self) -> None:
        """One fleet heartbeat: fire scheduled faults, observe health,
        reconcile assignments, tick every replica, collect completions,
        finalize drains."""
        now = self.clock()
        t = self.ticks
        if self.faults is not None:
            k = self.faults.kill_replica_for_tick(t)
            if k is not None:
                self._worker(k).kill()
            s = self.faults.stall_replica_for_tick(t)
            if s is not None:
                rep, n = s
                self._worker(rep).stall(t + n)
        for w in self.router.refresh_health(now):
            self._replica_event(
                "dead", w,
                orphans=len(w.scheduler.queue) + len(w.scheduler.running)
                + len(w.scheduler.prefilling))
        self._reconcile(now)
        for w in self.workers:
            w.tick(now, t)
        self._collect()
        for w in self.workers:
            if (w.state == "draining" and not w.scheduler.running
                    and not w.scheduler.prefilling
                    and not w.scheduler.queue):
                w.state = "released"
                self._replica_event(
                    "released", w,
                    free_blocks=w.engine.cache.free_blocks)
        self.ticks += 1

    def outstanding(self) -> bool:
        return (bool(self._active)
                or any(w.state == "draining" for w in self.workers))

    def prune_terminal(self) -> int:
        """Drop terminal requests from the ledger (a long-lived fleet's
        memory bound — the telemetry stream is the durable record).
        Returns how many were pruned."""
        dead = [rid for rid, fr in self.requests.items()
                if fr.record is not None]
        for rid in dead:
            del self.requests[rid]
        return len(dead)

    # -- workload replay ---------------------------------------------------

    def play(self, workload, *, dt_s: Optional[float] = None,
             drain_at_tick: Optional[Dict[int, int]] = None,
             max_ticks: int = 100000) -> List[FleetRequest]:
        """Replay a :func:`~paddle_tpu.serve.loadgen.make_workload`
        trace: submit every arrival whose ``at_s`` has passed, tick,
        advance the clock (``SimClock`` + ``dt_s``; a real clock just
        flows). Arrival times are relative to the START of the replay —
        the clock's epoch (perf_counter's arbitrary origin, a SimClock
        mid-run) must not collapse the trace into one burst.
        ``drain_at_tick`` maps fleet tick index → replica id for
        scripted elastic scale-down. Returns every
        :class:`FleetRequest` in rid order, all terminal."""
        pending = collections.deque(
            sorted(workload, key=lambda g: g.at_s))
        drains = dict(drain_at_tick or {})
        t0 = self.clock()
        for _ in range(max_ticks):
            now = self.clock() - t0
            while pending and pending[0].at_s <= now:
                g = pending.popleft()
                self.submit(g.prompt, g.max_new_tokens, eos_id=g.eos_id,
                            deadline_s=g.deadline_s, priority=g.priority,
                            session_id=g.session_id)
            if self.ticks in drains:
                self.drain(drains.pop(self.ticks))
            if not pending and not drains and not self.outstanding():
                return [self.requests[r] for r in sorted(self.requests)]
            self.tick()
            adv = getattr(self.clock, "advance", None)
            if adv is not None and dt_s is not None:
                adv(dt_s)
        raise RuntimeError(f"fleet did not drain in {max_ticks} ticks "
                           f"({sum(1 for f in self.requests.values() if not f.done)} "
                           f"requests outstanding)")

    # -- reporting ---------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        reasons = collections.Counter(
            fr.record["finish_reason"]
            for fr in self.requests.values() if fr.record)
        return {
            "submitted": len(self.requests),
            "terminal": sum(1 for fr in self.requests.values()
                            if fr.record is not None),
            "finish_reasons": dict(reasons),
            "resubmits": self.resubmits,
            "shed": self.shed_count,
            "duplicates_dropped": self.duplicates_dropped,
            "stale_completions": self.stale_completions,
            "unplaced": len(self._unplaced),
            "ticks": self.ticks,
            "prefix_hit_blocks": sum(
                w.engine.cache.prefix_hit_blocks for w in self.workers),
            "cow_forks": sum(
                w.engine.cache.cow_forks for w in self.workers),
            "replicas": {
                w.replica_id: {
                    "state": w.state, "killed": w.killed,
                    "engine_ticks": w.engine.ticks,
                    "free_blocks": w.engine.cache.free_blocks,
                    "prefix_hit_blocks":
                        w.engine.cache.prefix_hit_blocks,
                    "compile_counts": w.engine.compile_counts(),
                } for w in self.workers},
        }

    @classmethod
    def from_model(cls, model, variables, n_replicas: int, *,
                   engine_kwargs: Optional[Dict[str, Any]] = None,
                   **kw) -> "ServingFleet":
        """Convenience constructor: N identical engines over one
        checkpoint (the common homogeneous fleet)."""
        from .engine import DecodeEngine
        ek = dict(engine_kwargs or {})

        def mk(_i):
            return DecodeEngine(model, variables, **ek)

        return cls(mk, n_replicas, **kw)
